"""North-star benchmarks (BASELINE configs #1-#5 + engine throughput).

Headline: brute-force KNN retrieval at 1M docs x 128 dims on the TPU — the replacement
for the reference's ``src/external_integration/brute_force_knn_integration.rs:113``
(ndarray matmul + partial sort via ``src/mat_mul.rs:5``) — against a CPU numpy
implementation of the same computation (BLAS matmul + ``argpartition``), an in-process
stand-in for the reference's Rust kernel. Sub-benches cover the rest of BASELINE:

  #2 embedder     — Flax MiniLM batch-encode throughput (``models/encoder.py``)
  #3 vectorstore  — VectorStoreServer end-to-end over REST: ingest->index docs/s and
                    single-query p50 (embed + KNN + join pipeline per request)
  #4 streaming    — timed stream -> tumbling window aggregation, rows/s
  #5 sharded      — ShardedKNNStore on an 8-virtual-device mesh (subprocess, CPU mesh)
  engine          — streaming wordcount + incremental hash join vs vectorized-numpy
                    CPU proxies that maintain the same per-commit outputs

Robustness contract (a wedged single-tenant device tunnel hangs ``import jax``
forever whenever ``PALLAS_AXON_POOL_IPS`` is set — even under JAX_PLATFORMS=cpu):

  * the ORCHESTRATOR process never imports jax; backend health is probed in a
    throwaway subprocess with a timeout on EVERY path;
  * each sub-bench runs in its own subprocess under its own deadline, so one
    hung section cannot eat the round;
  * after every completed sub-bench the CUMULATIVE result line is printed and
    flushed — the driver's tail capture keeps partial results on timeout; the
    final line is the full aggregate (the ONE-JSON-line contract);
  * on CPU fallback the device-bound sections (knn/embedder/vectorstore) drop
    to smoke scale and are marked honest-invalid; the engine/window/sharded
    sections are CPU-vs-CPU comparisons and stay at full scale — their numbers
    are honest on any host;
  * the device is RE-probed (subprocess + timeout) before every device-bound
    section: a tunnel that wedges MID-round flips the rest of the round to
    reduced-scale CPU and stamps ``degraded: "cpu-fallback"`` on the result —
    device-bound numbers are only ever quoted when a probe just succeeded.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

SMOKE = bool(os.environ.get("PW_BENCH_SMOKE"))
# set by the orchestrator for sub-bench children after a failed device probe:
# device-bound sections scale down and mark their numbers honest-invalid
DEVICE_FALLBACK = bool(os.environ.get("PW_BENCH_DEVICE_FALLBACK"))
DEVICE_SCALE_DOWN = SMOKE or DEVICE_FALLBACK

N_DOCS = 1_000_000
DIM = 128
N_QUERIES = 1024
K = 10
CPU_SUBSET = 64
INGEST_CHUNK = 50_000  # one staged scatter per chunk, constant shape -> single compile

if DEVICE_SCALE_DOWN:
    # toy-scale profile for the device-bound sections: exercises every code path
    # without TPU hardware; numbers at this scale are meaningless for the
    # BASELINE targets and must never be read as comparable
    N_DOCS = 20_000
    N_QUERIES = 64
    CPU_SUBSET = 16
    INGEST_CHUNK = 5_000


def _run_cpu(data: np.ndarray, norms: np.ndarray, q: np.ndarray) -> np.ndarray:
    scores = q @ data.T
    qn = np.sum(q * q, axis=1, keepdims=True)
    dist = qn + norms[None, :] - 2.0 * scores
    idx = np.argpartition(dist, K, axis=1)[:, :K]
    part = np.take_along_axis(dist, idx, axis=1)
    order = np.argsort(part, axis=1)
    return np.take_along_axis(idx, order, axis=1)


def bench_knn() -> dict:
    import jax

    from pathway_tpu.ops.knn import DenseKNNStore

    rng = np.random.default_rng(0)
    data = rng.normal(size=(N_DOCS, DIM)).astype(np.float32)
    queries = rng.normal(size=(N_QUERIES, DIM)).astype(np.float32)

    store = DenseKNNStore(DIM, metric="l2sq", initial_capacity=N_DOCS)

    t0 = time.perf_counter()
    for i in range(0, N_DOCS, INGEST_CHUNK):
        store.add_many(list(range(i, i + INGEST_CHUNK)), data[i : i + INGEST_CHUNK])
        store._flush()
    jax.block_until_ready(store._data)
    ingest_s = time.perf_counter() - t0

    store.search_batch(queries, K)  # warmup / compile

    reps = [rng.normal(size=(N_QUERIES, DIM)).astype(np.float32) for _ in range(4)]
    latencies = []
    for q in [queries] + reps:
        t1 = time.perf_counter()
        store.search_batch(q, K)
        latencies.append(time.perf_counter() - t1)
    med = float(np.median(latencies))

    norms = np.sum(data * data, axis=1)
    t0 = time.perf_counter()
    cpu_idx = _run_cpu(data, norms, queries[:CPU_SUBSET])
    cpu_qps = CPU_SUBSET / (time.perf_counter() - t0)

    _, tpu_idx, _ = store.search_batch(queries[:CPU_SUBSET], K)
    tpu_keys = np.vectorize(lambda s: store.key_of.get(int(s), -1))(tpu_idx)
    recall = float(
        np.mean([len(set(tpu_keys[r]) & set(cpu_idx[r])) / K for r in range(CPU_SUBSET)])
    )

    # IVF-Flat (the ANN slot): measured on a CLUSTERED corpus — the distribution
    # embedding vectors actually have, and the workload ANN indexes exist for
    # (uniform random data defeats every ANN structure, HNSW included). Recall
    # is against exact numpy search over the SAME corpus.
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    n_centers = 1024
    centers = rng.normal(scale=4.0, size=(n_centers, DIM)).astype(np.float32)
    cdata = (
        centers[rng.integers(0, n_centers, N_DOCS)]
        + rng.normal(size=(N_DOCS, DIM)).astype(np.float32)
    ).astype(np.float32)
    ivf_clusters = min(1024, max(16, N_DOCS // 256))
    ivf = IvfKnnStore(
        DIM, metric="l2sq", initial_capacity=N_DOCS,
        n_clusters=ivf_clusters, n_probe=max(8, ivf_clusters // 16),
    )
    for i in range(0, N_DOCS, INGEST_CHUNK):
        ivf.add_many(list(range(i, i + INGEST_CHUNK)), cdata[i : i + INGEST_CHUNK])
    cqueries = (
        centers[rng.integers(0, n_centers, N_QUERIES)]
        + rng.normal(size=(N_QUERIES, DIM)).astype(np.float32)
    ).astype(np.float32)
    ivf.search_batch(cqueries, K)  # train + compile off the clock
    creps = [
        (
            centers[rng.integers(0, n_centers, N_QUERIES)]
            + rng.normal(size=(N_QUERIES, DIM)).astype(np.float32)
        ).astype(np.float32)
        for _ in range(4)
    ]
    ivf_lat = []
    for q in [cqueries] + creps:  # distinct batches, same protocol as dense KNN
        t1 = time.perf_counter()
        ivf.search_batch(q, K)
        ivf_lat.append(time.perf_counter() - t1)
    ivf_med = float(np.median(ivf_lat))
    cnorms = np.sum(cdata * cdata, axis=1)
    ivf_cpu_idx = _run_cpu(cdata, cnorms, cqueries[:CPU_SUBSET])
    _, ivf_idx, _ = ivf.search_batch(cqueries[:CPU_SUBSET], K)
    ivf_keys = np.vectorize(lambda s: ivf.key_of.get(int(s), -1))(ivf_idx)
    ivf_recall = float(
        np.mean(
            [len(set(ivf_keys[r]) & set(ivf_cpu_idx[r])) / K for r in range(CPU_SUBSET)]
        )
    )

    return {
        "knn_qps": round(N_QUERIES / med, 1),
        "knn_vs_cpu": round((N_QUERIES / med) / cpu_qps, 1),
        "knn_ingest_docs_per_s": round(N_DOCS / ingest_s, 1),
        "knn_p50_batch1024_ms": round(med * 1000.0, 2),
        "recall_at_10": round(recall, 4),
        "ivf_qps": round(N_QUERIES / ivf_med, 1),
        "ivf_p50_batch1024_ms": round(ivf_med * 1000.0, 2),
        "ivf_recall_at_10": round(ivf_recall, 4),
    }


def bench_ivf_scale() -> dict:
    """Tentpole check (ISSUE 15): the TIERED IVF index must sustain >= 10x
    more docs than the device-hot tier alone holds, at recall@10 >= 0.95 vs
    exact, with churn absorbed incrementally and the background rebuild never
    blocking queries for more than one bounded commit pause.

    CPU-honest like the engine sections: residency management, hit rates,
    prefetch stalls, maintenance/rebuild pauses and recall are all measured
    the same on any host (the "device-hot" tier is bookkeeping + resident
    blocks on CPU; the same code path device_puts on TPU) — so this section
    does NOT scale down on device fallback; only PW_BENCH_SMOKE shrinks it.

    Honesty keys: ``ivfscale_docs_over_hot_budget`` (>= 10x by construction,
    reported measured), ``ivfscale_recall_honest`` (recall@10 vs exact numpy
    over the live corpus), ``ivfscale_bitwise_residency`` (the same queries
    through an all-hot twin store return BITWISE identical scores/slots —
    residency must never change results), ``ivfscale_rebuild_nonblocking``
    (a full background rebuild committed while serving, with the max pause
    bounded and NO stop-the-world rebuild on the churn path)."""
    import shutil
    import tempfile

    from pathway_tpu.engine.profile import histograms
    from pathway_tpu.ops.knn_tiers import DirSpillStore, TieredIvfKnnStore

    dim = 64
    stages = [15_000, 30_000, 60_000] if SMOKE else [60_000, 120_000, 240_000]
    n_docs = stages[-1]
    n_queries, k = 256, 10
    n_centers = 256
    n_clusters = max(16, n_docs // 1024)
    rng = np.random.default_rng(11)
    centers = rng.normal(scale=4.0, size=(n_centers, dim)).astype(np.float32)

    def clustered(n: int, seed: int) -> np.ndarray:
        r = np.random.default_rng(seed)
        return (
            centers[r.integers(0, n_centers, n)]
            + r.normal(size=(n, dim)).astype(np.float32)
        ).astype(np.float32)

    data = clustered(n_docs, 12)
    queries = clustered(n_queries, 13)
    # hot budget = 1/10 of the FINAL corpus bytes: by the last stage the
    # store provably holds 10x what the hot tier can
    corpus_bytes = n_docs * dim * 4
    budget = max(1, corpus_bytes // 10)
    results: dict = {
        "ivfscale_docs": n_docs,
        "ivfscale_hot_budget_mb": round(budget / (1 << 20), 1),
    }
    spill_dir = tempfile.mkdtemp(prefix="pw-ivfscale-spill-")
    store = TieredIvfKnnStore(
        dim, metric="l2sq", n_clusters=n_clusters,
        n_probe=max(8, n_clusters // 16), hbm_budget_bytes=budget,
        spill_store=DirSpillStore(spill_dir),
    )
    keys = [f"d{i}" for i in range(n_docs)]
    ingest_t0 = time.perf_counter()
    fed = 0
    for stage_docs in stages:
        while fed < stage_docs:
            end_i = min(fed + 20_000, stage_docs)
            store.add_many(keys[fed:end_i], data[fed:end_i])
            fed = end_i
        store.search_batch(queries[:8], k)  # train/maintain off the clock
        lat = []
        for _ in range(3):
            t1 = time.perf_counter()
            store.search_batch(queries, k)
            lat.append(time.perf_counter() - t1)
        med = float(np.median(lat))
        results[f"ivfscale_qps_at_{stage_docs}"] = round(n_queries / med, 1)
    results["ivfscale_ingest_docs_per_s"] = round(
        n_docs / (time.perf_counter() - ingest_t0), 1
    )
    stats = store.tier_stats()
    probes = stats["probe_hot"] + stats["probe_cold"] + stats["probe_spilled"]
    results["ivfscale_tier_hit_rate"] = round(
        (stats["probe_hot"] + stats["probe_cold"]) / max(probes, 1), 4
    )
    results["ivfscale_hot_clusters"] = stats["hot"]
    results["ivfscale_occupancy"] = round(stats["occupancy"], 3)
    # per-slot footprint MEASURED from the resident blocks (payload dtype +
    # sidecars), not an assumed fp32 row width — the assumption misprices
    # the store whenever the payload dtype differs (PATHWAY_IVF_QUANT)
    blocks = list(store.tiers.pages.values())
    slot_bytes = sum(b.nbytes for b in blocks) / max(
        sum(b.vecs.shape[0] for b in blocks), 1
    )
    results["ivfscale_docs_over_hot_budget"] = round(
        n_docs * slot_bytes / budget, 1
    )

    # -- churn phase: sustained replace traffic while serving ------------------
    # enough waves to cross the rebuild-drift threshold: the full re-train
    # must run in the BACKGROUND and swap at one commit boundary
    import collections

    churn_rows = 0
    churn_t0 = time.perf_counter()
    wave = max(2000, n_docs // 24)
    waves = 0
    pool = collections.deque(keys)  # live keys, oldest removed first
    swaps_before = store.stats["swaps"]  # growth during the ramp may already
    # have committed one background rebuild; the churn phase must observe ITS
    # OWN rebuild land
    while waves < 40 and store.stats["swaps"] == swaps_before:
        new_keys = [f"r{waves}-{i}" for i in range(wave)]
        store.add_many(new_keys, clustered(wave, 100 + waves))
        pool.extend(new_keys)
        for _ in range(wave):
            store.remove(pool.popleft())
        churn_rows += 2 * wave
        store.search_batch(queries[:32], k)  # serving continues through churn
        if store._rebuild_inflight():
            # keep serving while the rebuild runs; the swap lands at a later
            # commit boundary
            deadline = time.perf_counter() + 120
            while store._rebuild_inflight() and time.perf_counter() < deadline:
                store.search_batch(queries[:32], k)
                time.sleep(0.02)
            store.search_batch(queries[:8], k)  # the swapping boundary
        waves += 1
    churn_s = time.perf_counter() - churn_t0
    results["ivfscale_churn_rows_per_s"] = round(churn_rows / max(churn_s, 1e-9), 1)
    results["ivfscale_rebuilds"] = int(store.stats["rebuilds"])
    results["ivfscale_rebuild_pause_max_ms"] = round(
        store.stats["max_pause_s"] * 1000.0, 1
    )
    results["ivfscale_rebuild_nonblocking"] = bool(
        store.stats["swaps"] >= 1 and store.stats["max_pause_s"] < 10.0
    )

    # -- recall + bitwise residency honesty ------------------------------------
    live_keys = list(store.slot_of.keys())
    live = np.stack([store._vector_of(store.slot_of[kk]) for kk in live_keys])
    sub = queries[:128]
    qn = np.sum(sub * sub, axis=1)[:, None]
    dn = np.sum(live * live, axis=1)[None, :]
    exact_idx = np.argsort(qn + dn - 2.0 * sub @ live.T, axis=1)[:, :k]
    # probe autotune to the recall target (the operating point is reported)
    while True:
        _s, got_idx, _v = store.search_batch(sub, k)
        hits = 0
        for r in range(len(sub)):
            got = {store.key_of.get(int(x)) for x in got_idx[r] if x >= 0}
            want = {live_keys[j] for j in exact_idx[r]}
            hits += len(got & want)
        recall = hits / (len(sub) * k)
        if recall >= 0.95 or store.n_probe >= min(store.n_clusters, 256):
            break
        store.n_probe = min(store.n_probe * 2, min(store.n_clusters, 256))
    results["ivfscale_n_probe"] = store.n_probe
    results["ivfscale_recall_at_10"] = round(recall, 4)
    results["ivfscale_recall_honest"] = bool(recall >= 0.95)
    lat = []
    for _ in range(3):
        t1 = time.perf_counter()
        store.search_batch(queries, k)
        lat.append(time.perf_counter() - t1)
    med = float(np.median(lat))
    results["ivfscale_qps"] = round(n_queries / med, 1)
    results["ivfscale_p50_batch_ms"] = round(med * 1000.0, 2)
    # bitwise residency honesty: the SAME store, the SAME queries, with the
    # residency forced from tiered (budget-bounded hot set + spill) to
    # all-hot — scores and slots must be byte-identical, or the tiers are
    # changing results
    a_s, a_i, _ = store.search_batch(sub, k)
    store.tiers.budget_bytes = 0  # lift the budget: everything is promotable
    for cid in range(store.n_clusters):
        if store.tiers.residency(cid) == "spilled":
            store.tiers.unspill(cid)
        store.tiers.promote(cid)
    b_s, b_i, _ = store.search_batch(sub, k)
    results["ivfscale_bitwise_residency"] = bool(
        np.array_equal(a_s, b_s) and np.array_equal(a_i, b_i)
    )
    # prefetch stalls: the frozen clusters probed across the churn + recall
    # sweeps (0.0 when every load hid inside the overlap window)
    results["ivfscale_spill_freezes"] = int(store.stats["spills"])  # cumulative
    results["ivfscale_frozen_clusters_end"] = int(store.tier_stats()["spilled"])
    # jit-cache regression keys (the pow2 padding discipline): ragged
    # cluster sizes must land in O(log) compile buckets, not one program per
    # cluster — the 18x ingest regression class this PR hit and fixed
    from pathway_tpu.ops.knn import kernel_cache_sizes

    caches = kernel_cache_sizes()
    results["ivfscale_assign_kernel_compiles"] = caches["tiered_assign"]
    results["ivfscale_score_kernel_compiles"] = caches["tiered_score"]
    stall = histograms().get("pathway_ivf_prefetch_stall_seconds")
    if stall is not None and stall.count:
        results["ivfscale_prefetch_stall_p50_ms"] = round(
            stall.quantile(0.50) * 1000.0, 3
        )
        results["ivfscale_prefetch_stall_p95_ms"] = round(
            stall.quantile(0.95) * 1000.0, 3
        )
        results["ivfscale_prefetch_stalls"] = int(stall.count)
    else:
        results["ivfscale_prefetch_stall_p50_ms"] = 0.0
        results["ivfscale_prefetch_stall_p95_ms"] = 0.0
        results["ivfscale_prefetch_stalls"] = 0
    store.close()
    shutil.rmtree(spill_dir, ignore_errors=True)
    return results


def bench_quant() -> dict:
    """Quantized retrieval tower (``PATHWAY_IVF_QUANT=int8``): the SAME
    corpus in an fp32-payload and an int8-payload tiered store at the SAME
    hot budget. The capacity multiple is MEASURED from actual block bytes
    (never an assumed row width), the recall cost is measured against brute
    force with the exact-rescore epilogue on, and the rescore contract is
    re-proven from outside the store: every returned score must be bitwise
    equal to ``rescore_pairs`` recomputed over the returned (query, slot)
    pairs from the fp32 source rows. CPU-honest — every key is a real
    measurement that degrades loudly, never a skip."""
    from pathway_tpu.engine.profile import histograms
    from pathway_tpu.ops.knn_quant import rescore_pairs
    from pathway_tpu.ops.knn_tiers import TieredIvfKnnStore

    dim = 128
    n_docs = 6_000 if SMOKE else 24_000
    n_queries, k = 128, 10
    n_centers = 128
    rng = np.random.default_rng(21)
    centers = rng.normal(scale=4.0, size=(n_centers, dim)).astype(np.float32)

    def clustered(n: int, seed: int) -> np.ndarray:
        r = np.random.default_rng(seed)
        return (
            centers[r.integers(0, n_centers, n)]
            + r.normal(size=(n, dim)).astype(np.float32)
        ).astype(np.float32)

    data = clustered(n_docs, 22)
    queries = clustered(n_queries, 23)
    n_clusters = max(16, n_docs // 512)
    budget = max(1, (n_docs * dim * 4) // 10)
    keys = [f"d{i}" for i in range(n_docs)]
    results: dict = {"quant_docs": n_docs, "quant_dim": dim}

    def build(quant: str) -> TieredIvfKnnStore:
        # full probe isolates the payload-dtype cost: recall differences are
        # then quantization, not probe luck
        store = TieredIvfKnnStore(
            dim, metric="l2sq", n_clusters=n_clusters, n_probe=n_clusters,
            hbm_budget_bytes=budget, quant=quant,
        )
        for s in range(0, n_docs, 4000):
            store.add_many(keys[s : s + 4000], data[s : s + 4000])
        store.search_batch(queries[:8], k)  # train/maintain off the clock
        return store

    f32 = build("off")
    q8 = build("int8")

    qn_full = np.sum(queries * queries, axis=1)
    dn = np.sum(data * data, axis=1)[None, :]
    exact_idx = np.argsort(
        qn_full[:, None] + dn - 2.0 * queries @ data.T, axis=1
    )[:, :k]
    want = [{f"d{j}" for j in exact_idx[r]} for r in range(n_queries)]

    def recall(store: TieredIvfKnnStore) -> float:
        _s, idx, _v = store.search_batch(queries, k)
        hits = 0
        for r in range(n_queries):
            got = {store.key_of.get(int(x)) for x in idx[r] if x >= 0}
            hits += len(got & want[r])
        return hits / (n_queries * k)

    recall_f = recall(f32)
    recall_q = recall(q8)
    ratio = recall_q / max(recall_f, 1e-12)
    results["quant_recall_at_10_fp32"] = round(recall_f, 4)
    results["quant_recall_at_10_int8"] = round(recall_q, 4)
    results["quant_recall_ratio"] = round(ratio, 4)
    results["quant_recall_honest"] = bool(ratio >= 0.99)
    # the store's own online audit (also populates the /metrics histogram)
    results["quant_recall_audit"] = round(
        float(q8.quant_recall_audit(queries[:64], k=k)), 4
    )

    # -- rescore-epilogue bitwise honesty --------------------------------------
    # recompute OUTSIDE the store: gather each returned slot's fp32 source
    # row, rebuild its norm with the store's own expression, push the pairs
    # through the pinned epilogue — bitwise equality or the key goes false
    s_q, i_q, _ = q8.search_batch(queries, k)
    bitwise = True
    for r in range(n_queries):
        m = i_q[r] >= 0
        slots = i_q[r][m].astype(int)
        if slots.size == 0:
            continue
        vecs = np.stack([q8._vector_of(int(s)) for s in slots]).astype(np.float32)
        norms = np.sum(vecs * vecs, axis=1)
        qi = np.full(slots.size, r)
        exact = rescore_pairs(
            queries[qi], vecs, norms, qn_full[qi], "l2sq"
        ).astype(np.float32)
        bitwise = bitwise and np.array_equal(exact, s_q[r][m])
    results["quant_rescore_bitwise"] = bool(bitwise)

    # -- measured capacity multiple at the same budget -------------------------
    def slot_bytes(store: TieredIvfKnnStore) -> float:
        blocks = list(store.tiers.pages.values())
        return sum(b.nbytes for b in blocks) / max(
            sum(b.vecs.shape[0] for b in blocks), 1
        )

    multiple = slot_bytes(f32) / max(slot_bytes(q8), 1e-12)
    results["quant_capacity_multiple"] = round(multiple, 2)
    results["quant_capacity_honest"] = bool(multiple >= 3.5)

    # -- solo-retrieve p50 (CPU fallback: host BLAS both sides) ----------------
    # per-query interleave + min-of-medians: the two stores alternate on every
    # single query (order flipped each rep) so host drift, frequency scaling,
    # and cache-warmth hit both code paths identically instead of whichever
    # store happened to run second
    f32.search_batch(queries[:1], k)  # warm both jit/BLAS paths
    q8.search_batch(queries[:1], k)
    rounds_f, rounds_q = [], []
    for rep in range(3):
        lat_f, lat_q = [], []
        for r in range(64):
            pair = ((f32, lat_f), (q8, lat_q))
            if (rep + r) % 2:
                pair = pair[::-1]
            for store, lat in pair:
                t1 = time.perf_counter()
                store.search_batch(queries[r : r + 1], k)
                lat.append(time.perf_counter() - t1)
        rounds_f.append(float(np.median(lat_f)))
        rounds_q.append(float(np.median(lat_q)))
    p50_f = min(rounds_f)
    p50_q = min(rounds_q)
    results["quant_solo_p50_ms"] = round(p50_q * 1000.0, 3)
    results["quant_solo_p50_fp32_ms"] = round(p50_f * 1000.0, 3)
    # 10% tolerance absorbs host timer noise at sub-ms latencies
    results["quant_solo_p50_no_worse"] = bool(p50_q <= p50_f * 1.10)

    # -- residency moves stay bitwise-invariant under int8 ---------------------
    sub = queries[:64]
    a_s, a_i, _ = q8.search_batch(sub, k)
    q8.tiers.budget_bytes = 0  # lift the budget: everything is promotable
    for cid in range(q8.n_clusters):
        if q8.tiers.residency(cid) == "spilled":
            q8.tiers.unspill(cid)
        q8.tiers.promote(cid)
    b_s, b_i, _ = q8.search_batch(sub, k)
    results["quant_bitwise_residency"] = bool(
        np.array_equal(a_s, b_s) and np.array_equal(a_i, b_i)
    )

    depth = histograms().get("pathway_ivf_quant_rescore_depth")
    results["quant_rescore_batches"] = int(depth.count) if depth is not None else 0
    f32.close()
    q8.close()
    return results


def bench_embedder() -> dict:
    """BASELINE #2: SentenceTransformer batch-embed throughput on the TPU.

    Steady-state measurement: fixed 1024-doc chunks (the serving batch size), with
    the SAME shape warmed up first so one-time XLA compilation is excluded — the
    engine reuses a compiled shape for every production batch. Reports the
    host-side (tokenize) vs device-side split."""
    from pathway_tpu.models.encoder import JaxSentenceEncoder

    enc = JaxSentenceEncoder("sentence-transformers/all-MiniLM-L6-v2")
    bs = 64 if DEVICE_SCALE_DOWN else 1024
    texts = [
        f"document number {i} about topic {i % 37} and theme {i % 11}"
        for i in range(4 * bs)
    ]
    enc.encode(texts[:bs])  # warmup / compile at the production shape
    # token count + host-tokenize share measured separately (untimed pre-pass)
    n_tokens = 0
    tok_s = 0.0
    for start in range(0, len(texts), bs):
        t1 = time.perf_counter()
        _ids, mask = enc._tokenize(texts[start : start + bs])
        tok_s += time.perf_counter() - t1
        n_tokens += int(mask.sum())
    t0 = time.perf_counter()
    for start in range(0, len(texts), bs):
        enc.encode(texts[start : start + bs])
    dt = time.perf_counter() - t0

    # analytic matmul FLOPs per PADDED token (the shapes actually executed):
    # per layer qkv+out = 4h^2, ffn = 2*h*ffn, x2 for multiply-add; attention
    # scores/values add 4*s*h per token. MFU is quoted against v5e peak bf16
    # (197 TFLOP/s) — the chip this bench targets.
    from pathway_tpu.models.encoder import _next_pow2

    cfg = enc.config
    mm_flops_per_token = 2 * cfg.num_layers * (
        4 * cfg.hidden_size**2 + 2 * cfg.hidden_size * cfg.intermediate_size
    )
    total_flops = 0
    for start in range(0, len(texts), bs):
        ids, _m = enc._tokenize(texts[start : start + bs])
        # the same bucketing encode_device applies — the shapes actually executed
        p2 = _next_pow2(ids.shape[1])
        b2 = _next_pow2(min(bs, len(texts) - start))
        attn_flops_per_token = cfg.num_layers * 4 * p2 * cfg.hidden_size
        total_flops += b2 * p2 * (mm_flops_per_token + attn_flops_per_token)
    tflops = total_flops / dt / 1e12
    out = {
        "embed_docs_per_s": round(len(texts) / dt, 1),
        "embed_tokens_per_s": round(n_tokens / dt, 1),
        "embed_host_tokenize_ms_per_batch": round(tok_s / (len(texts) / bs) * 1000, 2),
        "embed_dim": enc.dim,
        "embed_tflops_per_s": round(tflops, 2),
    }
    import jax

    if jax.default_backend() == "tpu":
        # MFU is quoted against v5e peak bf16 — meaningless for any other device
        out["embed_mfu_pct_v5e"] = round(100.0 * tflops / 197.0, 2)
    return out


def bench_embedpipe() -> dict:
    """EmbedPipeline (ISSUE 4): overlapped+length-sorted ingest vs the
    synchronous encode, coalesced concurrent-query p50 vs solo dispatch, and
    the content-hash cache on re-ingest — all three measured on the SAME host
    with the SAME encoder, so the ratios are honest on any backend (absolute
    docs/s is device-bound and scales down on CPU fallback like the embedder
    section). Also reports the padded-token waste ratio both ways and a
    bitwise-equality check of pipelined vs synchronous embeddings (which is
    the recall@10-unchanged guarantee: identical vectors, identical search).
    Pipelines here pin ``service_mode=False``: this section measures the PR-4
    deadline-coalescer mechanics; the persistent encoder service has its own
    ``encsvc`` section."""
    import concurrent.futures
    import threading

    from pathway_tpu.models.embed_pipeline import EmbedPipeline
    from pathway_tpu.models.encoder import JaxSentenceEncoder, _next_pow2

    enc = JaxSentenceEncoder("sentence-transformers/all-MiniLM-L6-v2")
    bs = 128 if DEVICE_SCALE_DOWN else 1024
    n_chunks = 2 if DEVICE_SCALE_DOWN else 4
    rng = np.random.default_rng(9)
    # serving-shaped corpus: mostly short chunks, a long tail of big ones — the
    # distribution where pad-to-longest burns FLOPs on the short majority
    def make_text(i: int) -> str:
        r = rng.random()
        n_words = int(rng.integers(4, 11)) if r < 0.7 else (
            int(rng.integers(20, 41)) if r < 0.95 else int(rng.integers(80, 121))
        )
        return " ".join(f"tok{(i * 131 + j * 17) % 5000}" for j in range(n_words))

    texts = [make_text(i) for i in range(n_chunks * bs)]
    sub_batch = max(16, bs // 8)  # 8 length-sorted sub-batches per commit batch

    # warm both shape families off the clock (sync longest bucket + the sorted
    # sub-batch buckets)
    enc.encode(texts[:bs])
    warm_pipe = EmbedPipeline(enc, cache_size=0, sub_batch=sub_batch, service_mode=False)
    warm_pipe.encode_batch(texts[:bs])

    out: dict = {}
    t0 = time.perf_counter()
    sync_parts = [enc.encode(texts[s : s + bs]) for s in range(0, len(texts), bs)]
    sync_s = time.perf_counter() - t0
    out["embedpipe_sync_docs_per_s"] = round(len(texts) / sync_s, 1)
    # sync-path waste: every row pays the batch-longest pow2 bucket
    padded = real = 0
    for s in range(0, len(texts), bs):
        ids, mask = enc._tokenize(texts[s : s + bs])
        padded += _next_pow2(ids.shape[0]) * _next_pow2(ids.shape[1])
        real += int(mask.sum())
    out["embedpipe_pad_waste_sync"] = round(1.0 - real / max(padded, 1), 4)

    pipe = EmbedPipeline(enc, cache_size=0, sub_batch=sub_batch, service_mode=False)  # overlap only
    t0 = time.perf_counter()
    over_parts = [pipe.encode_batch(texts[s : s + bs]) for s in range(0, len(texts), bs)]
    over_s = time.perf_counter() - t0
    out["embedpipe_overlap_docs_per_s"] = round(len(texts) / over_s, 1)
    out["embedpipe_overlap_speedup"] = round(sync_s / over_s, 2)
    out["embedpipe_pad_waste_sorted"] = round(pipe.pad_waste_ratio(), 4)
    out["embedpipe_bitwise_equal"] = bool(
        all(
            np.array_equal(a, b) for a, b in zip(sync_parts, over_parts)
        )
    )

    # -- coalesced vs solo concurrent queries --------------------------------
    n_clients = 16
    per_client = 2 if DEVICE_SCALE_DOWN else 4
    # warm every (batch, seq) bucket the comparison can hit — query texts all
    # land in one seq bucket; solo pads batch to 8, coalesced to 8/16 — so the
    # timed section measures dispatch+compute, not XLA compiles
    warm_q = [f"client {90 + c} warmup {c} about topic {c}" for c in range(16)]
    enc.encode(warm_q[:1])
    enc.encode(warm_q)
    qpipe = EmbedPipeline(enc, max_wait_ms=4.0, cache_size=0, service_mode=False)
    qpipe.embed_query_rows(warm_q[:1])
    qpipe.embed_query_rows(warm_q)

    def run_clients(embed_one) -> list:
        lats: list = []
        lock = threading.Lock()

        def client(c: int) -> None:
            for q in range(per_client):
                t1 = time.perf_counter()
                embed_one(f"client {c} question {q} about topic {c * 7 + q}")
                dt = time.perf_counter() - t1
                with lock:
                    lats.append(dt)

        with concurrent.futures.ThreadPoolExecutor(n_clients) as pool:
            list(pool.map(client, range(n_clients)))
        return lats

    # solo baseline = the pre-pipeline serving path: the engine evaluates one
    # query commit at a time on ONE thread, so 16 concurrent clients' embeds
    # serialize as 16 padded batch-of-1 dispatches (a lock models the engine's
    # single evaluation thread; unserialized parallel encodes would measure a
    # deployment that does not exist)
    solo_gate = threading.Lock()

    def solo_embed(q: str) -> None:
        with solo_gate:
            enc.encode([q])

    solo_lat = run_clients(solo_embed)
    coal_lat = run_clients(
        lambda q: np.asarray(qpipe.embed_query_rows([q])[0])
    )
    solo_p50 = float(np.median(solo_lat)) * 1000.0
    coal_p50 = float(np.median(coal_lat)) * 1000.0
    out["embedpipe_solo_q_p50_ms"] = round(solo_p50, 2)
    out["embedpipe_coalesced_q_p50_ms"] = round(coal_p50, 2)
    out["embedpipe_coalesce_speedup"] = round(solo_p50 / max(coal_p50, 1e-9), 2)
    cstats = qpipe.coalescer.stats()
    out["embedpipe_coalesce_avg_batch"] = round(
        cstats["coalesce_rows"] / max(cstats["coalesce_batches"], 1), 2
    )

    # -- content-hash cache: unchanged-corpus re-ingest ----------------------
    cpipe = EmbedPipeline(enc, cache_size=len(texts) + 16, sub_batch=sub_batch, service_mode=False)
    t0 = time.perf_counter()
    for s in range(0, len(texts), bs):
        cpipe.encode_batch(texts[s : s + bs])
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for s in range(0, len(texts), bs):
        cpipe.encode_batch(texts[s : s + bs])
    re_s = time.perf_counter() - t0
    stats = cpipe.cache.stats()
    out["embedpipe_first_ingest_docs_per_s"] = round(len(texts) / first_s, 1)
    out["embedpipe_reingest_docs_per_s"] = round(len(texts) / re_s, 1)
    out["embedpipe_cache_reingest_speedup"] = round(first_s / max(re_s, 1e-9), 2)
    out["embedpipe_cache_hit_rate"] = round(
        stats["cache_hits"] / max(stats["cache_hits"] + stats["cache_misses"], 1), 4
    )
    return out


def bench_encsvc() -> dict:
    """Persistent encoder service (ISSUE 11): solo-query p50 through the
    always-warm continuously-batched service vs the PR-4 deadline coalescer
    and vs a bare ``encode_device`` dispatch; tick occupancy under 16
    concurrent clients; semantic-cache hit speedup; and a TRUE bitwise-
    equality honesty key (exact mode) against a direct encode. The jit
    pre-warm runs — and is reported as ``encsvc_prewarm_s`` — BEFORE any timed
    request, so compilation never pollutes request latency. Device-bound:
    scales down on CPU fallback and rides the round-level ``degraded:
    "cpu-fallback"`` marker like the other device sections; the <15 ms solo
    target only means anything on device."""
    import concurrent.futures
    import threading

    from pathway_tpu.models.embed_pipeline import EmbedPipeline
    from pathway_tpu.models.encoder import JaxSentenceEncoder

    if DEVICE_SCALE_DOWN:
        # fewer pre-warm compiles at toy scale: the full bucket matrix is a
        # device-startup cost, not a CPU-fallback smoke-path cost
        os.environ.setdefault("PATHWAY_ENCSVC_PREWARM_MAX_BATCH", "16")
    enc = JaxSentenceEncoder("sentence-transformers/all-MiniLM-L6-v2")
    out: dict = {}

    # -- startup: pre-warm every reachable (batch, seq) bucket ---------------
    pipe = EmbedPipeline(enc, cache_size=0, service_mode=True, prewarm=True)
    svc = pipe.service
    out["encsvc_prewarm_ok"] = bool(svc.wait_warm(timeout_s=420.0))
    out["encsvc_prewarm_s"] = round(svc.prewarm_s, 2)
    out["encsvc_prewarm_compiles"] = svc.prewarm_compiles

    n_solo = 16 if DEVICE_SCALE_DOWN else 64

    def q(i: int) -> str:
        return f"solo retrieval question {i} about topic {i % 7}"

    # settle both paths once so the timed section is steady-state dispatch
    np.asarray(pipe.embed_query_rows([q(10_001)])[0])
    np.asarray(enc.encode_device([q(10_002)]))

    # -- solo p50: the ROADMAP item-2 headline (pre-warm excluded) -----------
    lat = []
    for i in range(n_solo):
        t0 = time.perf_counter()
        np.asarray(pipe.embed_query_rows([q(i)])[0])
        lat.append(time.perf_counter() - t0)
    solo_p50 = float(np.median(lat)) * 1000.0
    out["encsvc_solo_p50_ms"] = round(solo_p50, 2)
    out["encsvc_solo_sub15ms"] = bool(solo_p50 < 15.0)

    dlat = []
    for i in range(n_solo):
        t0 = time.perf_counter()
        np.asarray(enc.encode_device([q(i + n_solo)]))
        dlat.append(time.perf_counter() - t0)
    out["encsvc_direct_p50_ms"] = round(float(np.median(dlat)) * 1000.0, 2)

    legacy = EmbedPipeline(enc, cache_size=0, service_mode=False, max_wait_ms=2.0)
    np.asarray(legacy.embed_query_rows([q(10_003)])[0])
    llat = []
    for i in range(n_solo):
        t0 = time.perf_counter()
        np.asarray(legacy.embed_query_rows([q(i + 2 * n_solo)])[0])
        llat.append(time.perf_counter() - t0)
    legacy.coalescer.close()
    out["encsvc_legacy_solo_p50_ms"] = round(float(np.median(llat)) * 1000.0, 2)
    out["encsvc_solo_speedup_vs_legacy"] = round(
        float(np.median(llat)) / max(float(np.median(lat)), 1e-9), 2
    )

    # -- honesty key: service row bitwise == a direct encode of the same text
    probe = "bitwise honesty probe query"
    svc_row = np.asarray(pipe.embed_query_rows([probe])[0], dtype=np.float32)
    direct_row = np.asarray(enc.encode_device([probe]), dtype=np.float32)[0]
    out["encsvc_bitwise_equal"] = bool(np.array_equal(svc_row, direct_row))

    # -- occupancy under 16 concurrent clients -------------------------------
    n_clients = 16
    per_client = 2 if DEVICE_SCALE_DOWN else 4
    ticks0, rows0 = svc.ticks, svc.total_rows
    clat: list = []
    lock = threading.Lock()

    def client(c: int) -> None:
        for k in range(per_client):
            t1 = time.perf_counter()
            np.asarray(
                pipe.embed_query_rows([f"client {c} burst {k} topic {c * 7 + k}"])[0]
            )
            dt = time.perf_counter() - t1
            with lock:
                clat.append(dt)

    with concurrent.futures.ThreadPoolExecutor(n_clients) as pool:
        list(pool.map(client, range(n_clients)))
    ticks = svc.ticks - ticks0
    rows = svc.total_rows - rows0
    out["encsvc_concurrent_p50_ms"] = round(float(np.median(clat)) * 1000.0, 2)
    out["encsvc_ticks_16c"] = ticks
    out["encsvc_avg_tick_rows_16c"] = round(rows / max(ticks, 1), 2)
    out["encsvc_occupancy_16c"] = round(rows / max(ticks * n_clients, 1), 4)

    # -- semantic-cache hit speedup (exact mode: bitwise-honest hits) --------
    sem = EmbedPipeline(enc, cache_size=4096, service_mode=True, prewarm=False)
    primes = [f"semantic prime question {i} about topic {i}" for i in range(8)]
    mlat = []
    for p in primes:
        t0 = time.perf_counter()
        np.asarray(sem.embed_query_rows([p])[0])
        mlat.append(time.perf_counter() - t0)
    # wait on the SEMANTIC layer (the one being measured): its fill lands
    # after the content-cache fill on the worker thread
    deadline = time.monotonic() + 30.0
    while len(sem.semantic_cache) < len(primes) and time.monotonic() < deadline:
        time.sleep(0.01)
    hlat = []
    for i, p in enumerate(primes):
        variant = f"  Semantic PRIME question {i}  about topic {i} "
        t0 = time.perf_counter()
        np.asarray(sem.embed_query_rows([variant])[0])
        hlat.append(time.perf_counter() - t0)
    miss_p50 = float(np.median(mlat)) * 1000.0
    hit_p50 = float(np.median(hlat)) * 1000.0
    out["encsvc_semantic_miss_p50_ms"] = round(miss_p50, 3)
    out["encsvc_semantic_hit_p50_ms"] = round(hit_p50, 3)
    out["encsvc_semantic_hit_speedup"] = round(miss_p50 / max(hit_p50, 1e-9), 2)
    out["encsvc_semantic_hits"] = sem.semantic_cache.stats()["semantic_exact_hits"]
    svc.close()
    sem.service.close()
    return out


def _vs_corpus(n_docs: int) -> list:
    """The vector-store bench corpus — ONE construction shared by the main
    serving bench and the non-embed floor bench (they must measure the same
    workload for the decomposition to mean anything)."""
    import json as _json

    rng = np.random.default_rng(1)
    words = [f"term{i}" for i in range(500)]
    return [
        (" ".join(words[j] for j in rng.integers(0, 500, 12)), _json.dumps({"path": f"doc{i}"}))
        for i in range(n_docs)
    ]


def _vs_poster(port: int):
    import json as _json
    import urllib.request

    def post(route: str, payload: dict, timeout: float = 60.0) -> dict:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{route}",
            data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return _json.loads(resp.read())

    return post


def bench_vector_store(port: int = 18715) -> dict:
    """BASELINE #3: VectorStoreServer end-to-end over REST (ingest + query p50)."""
    import json as _json
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    pg.G.clear()
    n_docs = 2_000 if DEVICE_SCALE_DOWN else 20_000
    docs = _vs_corpus(n_docs)
    doc_table = pw.debug.table_from_rows(
        pw.schema_builder({"data": str, "_metadata": str}), docs
    )
    embedder = SentenceTransformerEmbedder(batch_size=64 if DEVICE_SCALE_DOWN else 1024)
    # compile the production batch shape off the clock (the engine reuses one
    # compiled shape for every ingest batch; cold-start XLA compilation is a
    # per-process constant, not a per-document cost)
    embedder.encoder.encode(["warm up"] * (64 if DEVICE_SCALE_DOWN else 1024))
    # single-query model cost, measured BEFORE the server's commit loop can
    # compete for the host (decomposes query p50 into embed vs engine+REST)
    embed_times = []
    embedder.encoder.encode(["warm single"])
    for _ in range(10):
        t1 = time.perf_counter()
        embedder.encoder.encode(["a single query string"])
        embed_times.append(time.perf_counter() - t1)
    embed_ms = float(np.median(embed_times)) * 1000.0
    server = VectorStoreServer(doc_table, embedder=embedder)
    t_start = time.perf_counter()
    server.run_server(host="127.0.0.1", port=port, threaded=True, terminate_on_error=False)
    post = _vs_poster(port)

    # ingest time: until statistics reports the corpus indexed
    deadline = time.perf_counter() + 600
    ingest_s = None
    while time.perf_counter() < deadline:
        try:
            stats = post("/v1/statistics", {}, timeout=5)
            if int(stats.get("file_count", 0)) >= 1:
                ingest_s = time.perf_counter() - t_start
                break
        except Exception:
            pass
        time.sleep(0.25)
    if ingest_s is None:
        return {"vectorstore_error": "ingest timeout"}

    post("/v1/retrieve", {"query": "term1 term2", "k": 3})  # warmup
    lat = []
    for i in range(30):
        t1 = time.perf_counter()
        post("/v1/retrieve", {"query": f"term{i} term{i+40} term{i+80}", "k": 3})
        lat.append(time.perf_counter() - t1)

    # latency floor diagnostic: one device round-trip (a trivial jit + fetch).
    # On a tunneled TPU (axon) every RPC costs ~65 ms regardless of compute; the
    # serving path is engineered down to ONE round-trip (device-resident query
    # embeddings chained into the search kernel), so p50 ~= rtt + engine overhead.
    # On locally-attached TPU hardware the same path runs in single-digit ms.
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones((8, 8))
    np.asarray(f(x))
    rtts = []
    for _ in range(10):
        t1 = time.perf_counter()
        np.asarray(f(x))
        rtts.append(time.perf_counter() - t1)
    rtt_ms = float(np.median(rtts)) * 1000.0
    p50_ms = float(np.median(lat)) * 1000.0
    # decomposition: the single-query model forward (embed_ms, measured above
    # pre-server) is reported alongside p50, NOT subtracted from it — the two
    # are measured under different host contention so the difference is not a
    # measurement (r5 artifact carried a negative "nonembed" residual). The
    # MEASURED non-embed floor is bench_vs_floor's vs_query_nonembed_p50_ms.
    return {
        "vs_ingest_docs_per_s": round(n_docs / ingest_s, 1),
        "vs_query_p50_ms": round(p50_ms, 2),
        "vs_query_p95_ms": round(float(np.percentile(lat, 95)) * 1000.0, 2),
        "device_roundtrip_p50_ms": round(rtt_ms, 2),
        "vs_query_p50_minus_rtt_ms": round(p50_ms - rtt_ms, 2),
        "vs_query_embed1_ms": round(embed_ms, 2),
    }


def bench_vs_floor(port: int = 18731) -> dict:
    """MEASURED non-embed serving floor (r4 verdict: a residual computed as
    p50 - batched_embed_amortization is not a measurement): the IDENTICAL
    REST -> engine -> KNN serving path with an instant deterministic hash
    embedder — no model forward anywhere in the loop, so this p50 IS the
    REST + engine + search floor. Runs as its own section/subprocess so the
    model server's background threads don't inflate it."""
    import hashlib
    import json as _json
    import urllib.request

    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    pg.G.clear()
    n_docs = 2_000 if DEVICE_SCALE_DOWN else 20_000
    rng = np.random.default_rng(1)
    words = [f"term{i}" for i in range(500)]
    docs = [
        (" ".join(words[j] for j in rng.integers(0, 500, 12)), _json.dumps({"path": f"doc{i}"}))
        for i in range(n_docs)
    ]

    @pw.udf
    def _instant_embed(text: str) -> np.ndarray:
        # same 384-dim as the production encoder: the KNN matmul/norm cost
        # scales with dim, so a smaller floor embedding would understate the
        # search share of the floor
        h = np.frombuffer(
            hashlib.md5(text.encode()).digest() * 24, dtype=np.uint8
        ).astype(np.float32)
        return h / (np.linalg.norm(h) + 1e-9)

    doc_table = pw.debug.table_from_rows(
        pw.schema_builder({"data": str, "_metadata": str}), docs
    )
    server = VectorStoreServer(doc_table, embedder=_instant_embed)
    server.run_server(host="127.0.0.1", port=port, threaded=True, terminate_on_error=False)
    post = _vs_poster(port)

    deadline = time.perf_counter() + 240
    while time.perf_counter() < deadline:
        try:
            stats = post("/v1/statistics", {}, timeout=5)
            if int(stats.get("file_count", 0)) >= 1:
                break
        except Exception:
            pass
        time.sleep(0.25)
    else:
        return {"vsfloor_error": "ingest timeout"}

    post("/v1/retrieve", {"query": "term1 term2", "k": 3})  # warmup
    lat = []
    for i in range(50):
        t1 = time.perf_counter()
        post("/v1/retrieve", {"query": f"term{i} term{i+11}", "k": 3})
        lat.append(time.perf_counter() - t1)
    return {
        "vs_query_nonembed_p50_ms": round(float(np.median(lat)) * 1000.0, 2),
        "vs_query_nonembed_p95_ms": round(float(np.percentile(lat, 95)) * 1000.0, 2),
    }


def bench_streaming_window() -> dict:
    """BASELINE #4: timed stream -> tumbling window aggregation."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.engine.runner import GraphRunner

    pg.G.clear()
    rng = np.random.default_rng(2)
    n = 200_000
    n_commits = 20
    per = n // n_commits
    rows = []
    for c in range(n_commits):
        ts = rng.integers(c * 100, (c + 1) * 100, per)
        sensors = rng.integers(0, 64, per)
        for t, s in zip(ts.tolist(), sensors.tolist()):
            rows.append((s, t, float(t % 7), 2 * c, 1))
    schema = pw.schema_builder({"sensor": int, "t": int, "value": float})
    tbl = pw.debug.table_from_rows(schema, rows, is_stream=True)
    win = tbl.windowby(
        tbl.t, window=pw.temporal.tumbling(duration=50), instance=tbl.sensor
    ).reduce(
        sensor=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        total=pw.reducers.sum(pw.this.value),
        n=pw.reducers.count(),
    )
    cnt = [0]
    pw.io.subscribe(win, lambda key, row, time, is_addition: cnt.__setitem__(0, cnt[0] + 1))
    t0 = time.perf_counter()
    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    dt = time.perf_counter() - t0
    return {"window_rows_per_s": round(n / dt, 1), "window_updates": cnt[0]}


def bench_telemetry() -> dict:
    """Metrics-plane overhead: streaming wordcount with per-operator
    profiling toggled PER COMMIT (even commits profiled, odd not) inside one
    run, so machine noise — which on a cpu-shared host dwarfs the true
    overhead at whole-run granularity (±20-50% between identical runs) —
    decorrelates from the measurement: adjacent commits see the same machine.
    Per-arm MEDIANS (durations are heavy-tailed), median-of-3 passes, GC off
    during the measured run (allocation-triggered pauses otherwise land on
    one parity), and a NULL calibration (same toggle bookkeeping, profiling
    off for both parities) subtracted to cancel the estimator's own parity
    bias. Contract: <2% commit-throughput delta on the headline regime.

    Two regimes: headline ``telemetry_overhead_pct`` on engine-bench-sized
    commits (~8k rows, multi-ms — what production batches look like;
    lands <1% + measurement floor), and
    ``telemetry_overhead_small_commits_pct`` on sub-millisecond few-hundred-
    row commits — the ADVERSARIAL bound where fixed per-commit bookkeeping
    (~18 µs measured standalone: per-op perf_counter pairs + one-pass
    retraction counts + the ring/fold appends) is largest relative to real
    work; expect a few percent there, by design of the regime. CPU-vs-CPU on
    any host, no device keys. Also reports the profiled commits' duration
    percentiles from the live log-bucketed histogram (what /metrics serves,
    measured not mocked).

    The tracing plane rides the same estimator: ``trace_overhead_pct``
    toggles ``PATHWAY_TRACE`` span bookkeeping per commit at the default 1%
    head-sampling rate on the headline regime (same <2% contract), and
    ``trace_output_bitwise_identical`` replays one stream traced at
    sample=1.0 vs tracing off and compares every delivered batch bitwise —
    tracing must observe, never perturb."""
    import pathway_tpu as pw
    from pathway_tpu.engine.profile import get_profiler, reset_profile
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg

    rng = np.random.default_rng(11)
    words_pool = np.array([f"word{i}" for i in range(4_000)])

    class ToggleRunner(GraphRunner):
        """Profiling on for even commits, off for odd — the per-commit A/B.
        With ``null=True`` profiling is off for BOTH parities while commits
        are still classified even/odd: that run measures the estimator's own
        parity bias (allocator drift, cache effects, throttle phase), which
        is subtracted from the toggle estimate."""

        def __init__(self, graph, *, null: bool = False):
            super().__init__(graph)
            self.null = null
            self.durations_on: list = []
            self.durations_off: list = []

        def step(self) -> bool:
            even = self._commit % 2 == 0
            profiled = even and not self.null
            saved = self._profiler
            if not profiled:
                self._profiler = None
            t0 = time.perf_counter()
            try:
                out = super().step()
            finally:
                dt = time.perf_counter() - t0
                self._profiler = saved
            (self.durations_on if even else self.durations_off).append(dt)
            return out

    class TraceToggleRunner(GraphRunner):
        """Tracing on for even commits, off for odd — the tracing plane's
        per-commit A/B (same estimator as the profiler toggle above). "On"
        means the full commit-span path at the DEFAULT head-sampling rate:
        deterministic commit context, span open/close, link drain; operator
        child spans only synthesize for the sampled ~1%."""

        def __init__(self, graph, *, null: bool = False):
            super().__init__(graph)
            self.null = null
            self.durations_on: list = []
            self.durations_off: list = []

        def step(self) -> bool:
            from pathway_tpu.engine.tracing import get_tracer

            even = self._commit % 2 == 0
            traced = even and not self.null
            tracer = get_tracer()
            saved = tracer.enabled
            if not traced:
                tracer.enabled = False
            t0 = time.perf_counter()
            try:
                out = super().step()
            finally:
                dt = time.perf_counter() - t0
                tracer.enabled = saved
            (self.durations_on if even else self.durations_off).append(dt)
            return out

    def typical(values: list) -> float:
        """Median: commit durations are heavy-tailed (GC, scheduler, state
        growth spikes run 5-10x the median) and the overhead under test is
        percent-level — a mean would be set by the tail, not the signal."""
        values = sorted(values)
        mid = len(values) // 2
        return values[mid] if len(values) % 2 else (values[mid - 1] + values[mid]) / 2

    def measure(
        n: int, n_commits: int, *, null: bool = False, runner_cls=ToggleRunner
    ) -> tuple:
        import gc

        per = n // n_commits
        words = words_pool[rng.integers(0, len(words_pool), n)]
        rows = [(w, 2 * (i // per), 1) for i, w in enumerate(words.tolist())]
        pg.G.clear()
        tbl = pw.debug.table_from_rows(
            pw.schema_builder({"word": str}), rows, is_stream=True
        )
        out = tbl.groupby(pw.this.word).reduce(pw.this.word, cnt=pw.reducers.count())
        pw.io.subscribe(out, on_batch=lambda *a: None)
        runner = runner_cls(pg.G._current, null=null)
        # GC pauses (~100 µs) are allocation-count-triggered: the profiled
        # arm's slightly higher allocation rate SHIFTS which parity pays
        # them, turning GC timing into a systematic A/B bias either way.
        # Collect up front, keep GC off for the measured run.
        gc.collect()
        gc.disable()
        try:
            runner.run(monitoring_level=pw.MonitoringLevel.NONE)
        finally:
            gc.enable()
        # drop per-arm warmup (first profiled + first unprofiled commit pay
        # first-touch costs) before the medians
        on_mean = typical(runner.durations_on[1:])
        off_mean = typical(runner.durations_off[1:])
        return (on_mean - off_mean) / off_mean * 100.0, on_mean, off_mean

    def calibrated(n: int, n_commits: int, *, runner_cls=ToggleRunner) -> tuple:
        """Bias-corrected overhead: median-of-3 toggle passes MINUS
        median-of-3 null passes (same runner, profiling off for both
        parities). The null measures everything the estimator picks up that
        is NOT profiling — even/odd parity bias from allocator drift, cache
        phase, and the host's cpu-share throttle — which in this container
        runs ±1-3%, the same order as the effect under test."""
        toggles = sorted(
            measure(n, n_commits, runner_cls=runner_cls) for _ in range(3)
        )
        nulls = sorted(
            measure(n, n_commits, null=True, runner_cls=runner_cls)[0]
            for _ in range(3)
        )
        pct, on_t, off_t = toggles[1]
        return pct - nulls[1], on_t, off_t

    prev = os.environ.get("PATHWAY_PROFILE")
    os.environ["PATHWAY_PROFILE"] = "1"
    try:
        scale = 4 if SMOKE else 1
        reset_profile()
        # representative: engine-bench-sized commit batches (~8k rows/commit,
        # multi-ms commits) — the regime the <2% contract is about; per-commit
        # bookkeeping (~18 µs measured standalone) amortizes to well under 1%
        rep_n = 400_000 if SMOKE else 800_000
        rep_pct, rep_on, rep_off = calibrated(rep_n, rep_n // 8_000)
        totals = get_profiler().operator_totals()  # folds pending profiles
        pct = get_profiler().commit_hist.percentiles()
        # by NAME, like the flight-recorder summary and /v1/statistics — kind
        # alone cannot distinguish two groupby nodes
        slowest = max(totals, key=lambda e: e["seconds"])["name"] if totals else ""
        reset_profile()
        # adversarial: the regime is DEFINED by its ~500-row sub-ms commits —
        # scaling rows down further would measure a regime nothing runs in
        small_pct, _on, _off = calibrated(200_000 // scale, 400 // scale)
        # tracing plane: the same bias-corrected per-commit estimator, span
        # bookkeeping at the default head-sampling rate vs PATHWAY_TRACE=off
        # — the distributed-tracing README row shares the <2% contract
        trace_prev = {
            k: os.environ.get(k)
            for k in ("PATHWAY_TRACE", "PATHWAY_TRACE_SAMPLE")
        }
        os.environ["PATHWAY_TRACE"] = "on"
        os.environ["PATHWAY_TRACE_SAMPLE"] = "0.01"
        from pathway_tpu.engine.tracing import reset_tracing

        reset_tracing()
        try:
            # full headline regime: the per-commit trace path costs ~15 µs
            # standalone (two sha1 context derivations + pending-buffer
            # routing), percent-level on multi-ms commits. PAIRED estimator
            # here rather than `calibrated`: host cpu-share drift between the
            # toggle group and the null group reads as ±5-10% bias at this
            # arm's position late in the bench, so each toggle pass is
            # corrected by the null pass run immediately after it, and the
            # median of the paired differences is reported.
            trace_pairs = []
            for _ in range(3):
                t_pct, t_on, t_off = measure(
                    rep_n, rep_n // 8_000, runner_cls=TraceToggleRunner
                )
                null_pct, _, _ = measure(
                    rep_n, rep_n // 8_000, null=True,
                    runner_cls=TraceToggleRunner,
                )
                trace_pairs.append((t_pct - null_pct, t_on, t_off))
            trace_pairs.sort()
            trace_pct, _t_on, _t_off = trace_pairs[1]

            # honesty: tracing must not perturb results — the SAME stream,
            # traced at sample=1.0 (every commit spanned, operator child
            # spans synthesized) and with tracing off, must agree BITWISE
            def final_batches(trace_env: str) -> list:
                os.environ["PATHWAY_TRACE"] = trace_env
                os.environ["PATHWAY_TRACE_SAMPLE"] = "1.0"
                reset_tracing()
                cap_rng = np.random.default_rng(17)
                words = words_pool[
                    cap_rng.integers(0, len(words_pool), 60_000)
                ]
                rows = [
                    (w, 2 * (i // 6_000), 1)
                    for i, w in enumerate(words.tolist())
                ]
                pg.G.clear()
                tbl = pw.debug.table_from_rows(
                    pw.schema_builder({"word": str}), rows, is_stream=True
                )
                out = tbl.groupby(pw.this.word).reduce(
                    pw.this.word, cnt=pw.reducers.count()
                )
                captured: list = []

                def on_batch(keys, diffs, columns, time):
                    captured.append((
                        keys.tobytes(),
                        diffs.tobytes(),
                        tuple(
                            (nm, np.asarray(col).tobytes())
                            if np.asarray(col).dtype != object
                            else (nm, repr(np.asarray(col).tolist()).encode())
                            for nm, col in sorted(columns.items())
                        ),
                    ))

                pw.io.subscribe(out, on_batch=on_batch)
                pw.run(monitoring_level=pw.MonitoringLevel.NONE)
                return captured

            trace_bitwise = final_batches("on") == final_batches("off")
        finally:
            for k, v in trace_prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            reset_tracing()
    finally:
        if prev is None:
            os.environ.pop("PATHWAY_PROFILE", None)
        else:
            os.environ["PATHWAY_PROFILE"] = prev
    reset_profile()
    return {
        "telemetry_overhead_pct": round(rep_pct, 2),
        "telemetry_overhead_small_commits_pct": round(small_pct, 2),
        "telemetry_profiled_commit_ms": round(rep_on * 1000, 3),
        "telemetry_unprofiled_commit_ms": round(rep_off * 1000, 3),
        "telemetry_commit_p50_ms": round(pct["p50"] * 1000, 3),
        "telemetry_commit_p99_ms": round(pct["p99"] * 1000, 3),
        "telemetry_slowest_operator": slowest,
        "trace_overhead_pct": round(trace_pct, 2),
        "trace_output_bitwise_identical": bool(trace_bitwise),
    }


def bench_engine() -> dict:
    """Streaming wordcount + incremental join vs vectorized-numpy CPU proxies
    maintaining identical per-commit results (VERDICT round-2 item 1).

    Fairness contract, both sides: data preparation (row lists / numpy arrays,
    sorted build sides) happens OFF the clock; the timed region is per-commit
    incremental processing + delivery of the update batches.

    Reading the ratios: wordcount/join (string keys) are the headline bars
    (>= 1.0x). join_int is secondary and sits ~0.6x (r5: single-int keys now
    derive via an identity mix instead of xxh3, and the inner all-matched emit
    path skips its splicing — up from ~0.47): the proxy is a non-incremental
    branchless binary search over sorted int64s near the memory-bandwidth
    floor, while the engine maintains a fully incremental, retraction-capable
    arrangement and gathers object-cell outputs; closing the rest needs typed
    (non-object) string columns. The join_churn metric is the same workload
    once the build side actually churns: there incrementality wins ~2.5x,
    which is the workload this engine exists for. The engine delivers
    through the vectorized ``pw.io.subscribe(on_batch=...)`` sink (columnar arrays,
    the TPU-native delivery path); the proxies consume by updating their own
    result state. Join keys are string entity ids (the representative ETL join);
    the int-key variant is reported as a secondary metric."""
    import pathway_tpu as pw
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.engine.runner import GraphRunner

    def _warmup() -> None:
        # Compile the jit'd groupby/join/consolidation kernels off the clock: the
        # timed region measures steady-state throughput (compiles amortize away in
        # any real deployment; the numpy proxy has no compile step to pay either).
        rngw = np.random.default_rng(0)
        ww = [f"w{i}" for i in range(256)]
        rows = [(ww[j], 2 * (i // 2048), 1) for i, j in enumerate(rngw.integers(0, 256, 8192).tolist())]
        pg.G.clear()
        t = pw.debug.table_from_rows(pw.schema_builder({"word": str}), rows, is_stream=True)
        out = t.groupby(pw.this.word).reduce(pw.this.word, cnt=pw.reducers.count())
        pw.io.subscribe(out, on_batch=lambda *a: None)
        GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
        pg.G.clear()
        lt = pw.debug.table_from_rows(
            pw.schema_builder({"k": str}),
            [(ww[j], 2 * (i // 2048), 1) for i, j in enumerate(rngw.integers(0, 256, 8192).tolist())],
            is_stream=True,
        )
        rt = pw.debug.table_from_rows(
            pw.schema_builder({"k2": str, "name": str}), [(w, w.upper()) for w in ww]
        )
        j = lt.join(rt, lt.k == rt.k2).select(lt.k, rt.name)
        pw.io.subscribe(j, on_batch=lambda *a: None)
        GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)

    _warmup()

    rng = np.random.default_rng(3)
    n = 400_000
    n_commits = 20
    words_pool = np.array([f"word{i}" for i in range(20_000)])
    word_ids = rng.integers(0, len(words_pool), n)
    words = words_pool[word_ids]

    # numpy proxy: per commit np.unique + count accumulation + changed-group emission
    per = n // n_commits
    t0 = time.perf_counter()
    counts: dict = {}
    emitted = 0
    for c in range(n_commits):
        batch = words[c * per : (c + 1) * per]
        uniq, cnt = np.unique(batch, return_counts=True)
        for w, k in zip(uniq.tolist(), cnt.tolist()):
            counts[w] = counts.get(w, 0) + k
        emitted += len(uniq)
    proxy_wc_s = time.perf_counter() - t0

    pg.G.clear()
    rows = [
        (w, 2 * (i // per), 1) for i, w in enumerate(words.tolist())
    ]
    tbl = pw.debug.table_from_rows(pw.schema_builder({"word": str}), rows, is_stream=True)
    out = tbl.groupby(pw.this.word).reduce(pw.this.word, cnt=pw.reducers.count())
    delivered = [0]
    pw.io.subscribe(
        out, on_batch=lambda keys, diffs, columns, time: delivered.__setitem__(
            0, delivered[0] + len(keys)
        )
    )
    t0 = time.perf_counter()
    GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
    engine_wc_s = time.perf_counter() - t0

    # join: 200k probe rows against a 20k-row build side, streamed in 10 commits.
    # Keys are string ids; the proxy probes a pre-sorted build side via searchsorted
    # (numpy's fastest honest string lookup), the engine runs its full incremental
    # hash join (both sides arranged, retraction-capable).
    nj = 200_000
    build_n = 20_000
    per_j = nj // 10
    probe_pos = rng.integers(0, build_n, nj)
    build_keys = np.array([f"user_{i:08d}" for i in range(build_n)])
    build_names = np.array([f"name{i}" for i in range(build_n)])
    probe_keys = build_keys[probe_pos]

    def proxy_join(build_k: np.ndarray, probe_k: np.ndarray) -> float:
        import gc

        gc.collect()
        order = np.argsort(build_k)
        sb, sn = build_k[order], build_names[order]
        t0 = time.perf_counter()
        for c in range(10):
            keys = probe_k[c * per_j : (c + 1) * per_j]
            pos = np.searchsorted(sb, keys)
            _ = keys, sn[pos]  # emitted join rows (key, name)
        return time.perf_counter() - t0

    def engine_join(schema_k: type, build_vals: list, probe_vals: list) -> float:
        import gc

        gc.collect()  # isolate from the previous sub-measurement's garbage
        pg.G.clear()
        lrows = [(k, 2 * (i // per_j), 1) for i, k in enumerate(probe_vals)]
        lt = pw.debug.table_from_rows(
            pw.schema_builder({"k": schema_k}), lrows, is_stream=True
        )
        rt = pw.debug.table_from_rows(
            pw.schema_builder({"k2": schema_k, "name": str}),
            [(k, f"name{i}") for i, k in enumerate(build_vals)],
        )
        j = lt.join(rt, lt.k == rt.k2).select(lt.k, rt.name)
        pw.io.subscribe(j, on_batch=lambda keys, diffs, columns, time: None)
        t0 = time.perf_counter()
        GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
        return time.perf_counter() - t0

    proxy_join_s = proxy_join(build_keys, probe_keys)
    engine_join_s = engine_join(str, build_keys.tolist(), probe_keys.tolist())
    proxy_join_int_s = proxy_join(np.arange(build_n), probe_pos)
    engine_join_int_s = engine_join(
        int, list(range(build_n)), [int(k) for k in probe_pos]
    )

    # -- incremental join under build-side churn ---------------------------------
    # After every second probe commit, 2k build rows change their name; every
    # previously-arrived probe row joining a changed key must emit a retract+insert
    # pair (the defining obligation of an INCREMENTAL join). The proxy does the same
    # with the best vectorized numpy available: sorted-build searchsorted for probe
    # lookups, np.isin over the accumulated probe history for retro updates.
    churn_rounds = [(2 * r + 1, rng.integers(0, build_n, 2_000)) for r in range(5)]

    def proxy_churn() -> float:
        order = np.argsort(build_keys)
        sb = build_keys[order]
        names_cur = build_names[order].copy()
        hist: list = []
        churn = {t: pos for t, pos in churn_rounds}
        t0 = time.perf_counter()
        for c in range(10):
            keys = probe_keys[c * per_j : (c + 1) * per_j]
            pos = np.searchsorted(sb, keys)
            _ = keys, names_cur[pos]  # emitted join rows
            hist.append(keys)
            if c in churn:
                changed_pos = np.unique(churn[c])
                changed_keys = build_keys[changed_pos]
                sc = np.sort(changed_keys)
                h = np.concatenate(hist)
                hit = h[np.isin(h, sc)]
                hp = np.searchsorted(sb, hit)
                old = names_cur[hp]  # retractions carry old values
                bp = np.searchsorted(sb, changed_keys)
                names_cur[bp] = np.char.add(build_names[changed_pos], f"_v{c}")
                new = names_cur[hp]  # re-inserts carry new values
                _ = hit, old, new  # emitted retract+insert update pairs
        return time.perf_counter() - t0

    def engine_churn() -> float:
        pg.G.clear()
        lrows = [(k, 4 * (i // per_j), 1) for i, k in enumerate(probe_keys.tolist())]
        lt = pw.debug.table_from_rows(
            pw.schema_builder({"k": str}), lrows, is_stream=True
        )
        rrows: list = [
            (k, f"name{i}", 0, 1) for i, k in enumerate(build_keys.tolist())
        ]
        current = {k: f"name{i}" for i, k in enumerate(build_keys.tolist())}
        for c, pos in churn_rounds:
            t = 4 * c + 2  # between probe commits c and c+1
            for p in np.unique(pos).tolist():
                k = build_keys[p]
                rrows.append((k, current[k], t, -1))
                current[k] = f"name{p}_v{c}"
                rrows.append((k, current[k], t, 1))
        rt = pw.debug.table_from_rows(
            pw.schema_builder({"k2": str, "name": str}), rrows, is_stream=True
        )
        j = lt.join(rt, lt.k == rt.k2).select(lt.k, rt.name)
        pw.io.subscribe(j, on_batch=lambda keys, diffs, columns, time: None)
        t0 = time.perf_counter()
        GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
        return time.perf_counter() - t0

    proxy_churn_s = proxy_churn()
    engine_churn_s = engine_churn()

    return {
        "wordcount_rows_per_s": round(n / engine_wc_s, 1),
        "wordcount_vs_numpy": round(proxy_wc_s / engine_wc_s, 3),
        "wordcount_updates_delivered": delivered[0],
        "join_rows_per_s": round(nj / engine_join_s, 1),
        "join_vs_numpy": round(proxy_join_s / engine_join_s, 3),
        "join_int_rows_per_s": round(nj / engine_join_int_s, 1),
        "join_int_vs_numpy": round(proxy_join_int_s / engine_join_int_s, 3),
        "join_churn_rows_per_s": round(nj / engine_churn_s, 1),
        "join_churn_vs_numpy": round(proxy_churn_s / engine_churn_s, 3),
    }


def bench_fusion() -> dict:
    """Whole-commit fusion A/B: the join/groupby chain workload with the
    fusion compiler toggled PER COMMIT inside one run (even commits fused, odd
    per-node dispatch — the telemetry section's parity discipline, because
    whole-run timing swings ±20-50% on this shared host), medians per arm,
    median-of-3 passes, GC off during the measured region.

    Workload: a wide integer feature-derivation chain (the shape of a
    production feature pipeline — money in cents, timestamps, categorical
    codes; ~150 elementwise ops across 20 derivation stages — feature-store width), a selectivity filter,
    an incremental hash join against a dimension table, a short post-join
    derivation chain, and a groupby summing two int columns. The numpy proxy
    performs the same per-commit computation the obvious vectorized way
    (op-at-a-time temporaries, pre-sorted searchsorted join, ``np.add.at``
    aggregation) and maintains the same per-commit group outputs.

    Keys: ``fused_join_speedup`` (unfused/fused commit medians),
    ``join_vs_numpy`` (numpy proxy / FUSED engine — the ROADMAP trajectory
    metric, engine now ahead of numpy instead of 0.7-1.1x parity),
    ``fusion_join_vs_numpy_unfused`` (same ratio, fusion off — the before
    picture), ``bitwise_equal`` (fused vs unfused sink bytes, XLA path forced,
    the honesty key), and the recompile discipline counters
    (``fusion_jit_compiles``/``fusion_shape_buckets`` from a ragged
    commit-size sweep — pow2 bucketing must hold compiles at O(log) of the
    size spread). CPU-vs-CPU on any host; no device-only keys."""
    import gc

    import pathway_tpu as pw
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg

    n_commits = 8
    per = 50_000 if SMOKE else 200_000
    build_n = 4_000
    n = per * n_commits
    rng = np.random.default_rng(17)
    uids = rng.integers(0, build_n, n)
    amounts = rng.integers(1, 10**6, n)
    qtys = rng.integers(1, 50, n)
    tss = rng.integers(0, 10**9, n)
    cats = rng.integers(0, 32, n)
    b_region = np.arange(build_n) % 7
    b_tier = (np.arange(build_n) * 13) % 1000

    # ONE chain definition consumed by both sides: `c` maps feature name ->
    # column (pw expression or numpy array), `W` is if_else/np.where. Values
    # are re-bounded with mods so 10 stages stay in int64 range either way.
    # ops are deliberately the memory-bound mix (mul/add/sub/xor/shift/where/
    # compare) a feature pipeline compiles to — the regime where one fused XLA
    # pass beats numpy's one-temporary-per-op; the single ``// 86400`` is the
    # realistic timestamp normalization (integer division is ALU-bound, fusion
    # neither helps nor hurts it)
    def _derive(c: dict, W) -> dict:
        return {
            "total": c["amount"] * c["qty"],
            "day": c["ts"] // 86400,
            "hod": (c["ts"] >> 7) & 31,
            "dow": (c["ts"] >> 12) & 7,
        }

    def _seed_feats(c: dict, W) -> dict:
        return {
            "net": W(c["total"] > 10**7, c["total"] - (c["total"] >> 4), c["total"]),
            "bucket": c["dow"] * 32 + c["cat"],
            "fa": c["total"] & 0xFFFFF,
            "fb": c["day"] * 24 + c["hod"],
            "fc": (c["total"] >> 3) & 0xFFFFF,
            "fd": c["hod"] * 3600 + c["dow"],
        }

    def _stage(c: dict, W) -> dict:
        return {
            "fa": (c["fb"] * 3 + c["fc"]) & 0xFFFFF,
            "fb": W(c["fa"] > c["fd"], c["fa"] - c["fd"], c["fd"] - c["fa"]),
            "fc": ((c["fc"] >> 3) ^ (c["fa"] * 7)) + c["bucket"],
            "fd": (c["fd"] + (c["fa"] & 0x3FF)) ^ (c["fb"] >> 5),
        }

    def _gate(c: dict):
        return (c["net"] > 500_000) & ((c["fa"] & 3) != 0)

    def _finalize(c: dict, W) -> dict:
        return {
            "final": (c["fa"] + c["fb"]) >> 3,
            "cap": W(c["fc"] > 10**8, 10**8, c["fc"]),
        }

    N_STAGES = 20

    def build_graph(rows: list, capture=None):
        pg.G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_builder(
                {"uid": int, "amount": int, "qty": int, "ts": int, "cat": int}
            ),
            rows,
            is_stream=True,
        )
        dim = pw.debug.table_from_rows(
            pw.schema_builder({"uid2": int, "region": int, "tier": int}),
            [(int(i), int(r), int(ti)) for i, (r, ti) in enumerate(zip(b_region, b_tier))],
        )

        def cols_of(tbl, names):
            return {nm: getattr(tbl, nm) for nm in names}

        c0 = cols_of(t, ["uid", "cat", "amount", "qty", "ts"])
        t1 = t.select(t.uid, t.cat, **_derive(c0, pw.if_else))
        c1 = cols_of(t1, ["uid", "cat", "total", "day", "hod", "dow"])
        cur = t1.select(t1.uid, **_seed_feats(c1, pw.if_else))
        for _s in range(N_STAGES):
            c = cols_of(cur, ["uid", "net", "bucket", "fa", "fb", "fc", "fd"])
            cur = cur.select(
                cur.uid, cur.net, cur.bucket, **_stage(c, pw.if_else)
            )
        cg = cols_of(cur, ["net", "fa"])
        kept = cur.filter(_gate(cg))
        ck = cols_of(kept, ["fa", "fb", "fc"])
        t_fin = kept.select(kept.uid, kept.net, kept.bucket, **_finalize(ck, pw.if_else))
        j = t_fin.join(dim, t_fin.uid == dim.uid2).select(
            t_fin.final, t_fin.net, t_fin.cap, t_fin.bucket, dim.region, dim.tier
        )
        p1 = j.select(
            j.region, j.net, j.cap, j.bucket,
            boosted=j.final * (j.tier + 1),
        )
        p2 = p1.select(
            p1.region, p1.net,
            margin=p1.boosted - (p1.cap // 2 + p1.bucket),
        )
        out = p2.groupby(p2.region).reduce(
            p2.region,
            s=pw.reducers.sum(p2.net),
            m=pw.reducers.sum(p2.margin),
            cnt=pw.reducers.count(),
        )
        if capture is None:
            pw.io.subscribe(out, on_batch=lambda *a: None)
        else:
            def on_batch(keys, diffs, columns, time):
                capture.append(
                    (
                        keys.tobytes(),
                        diffs.tobytes(),
                        tuple(
                            (nm, np.asarray(col).tobytes())
                            if np.asarray(col).dtype != object
                            else (nm, repr(np.asarray(col).tolist()).encode())
                            for nm, col in sorted(columns.items())
                        ),
                    )
                )

            pw.io.subscribe(out, on_batch=on_batch)

    def make_rows(sizes: list) -> list:
        rows = []
        pos = 0
        for ci, sz in enumerate(sizes):
            for i in range(pos, pos + sz):
                rows.append(
                    (int(uids[i]), int(amounts[i]), int(qtys[i]), int(tss[i]),
                     int(cats[i]), 2 * ci, 1)
                )
            pos += sz
        return rows

    class ToggleRunner(GraphRunner):
        """Fusion on for even commits, off for odd — per-commit A/B over the
        SAME evaluator state (outputs are identical either way, so the state
        evolution is shared and adjacent commits see the same machine)."""

        def __init__(self, graph):
            super().__init__(graph)
            self.fused_t: list = []
            self.unfused_t: list = []

        def step(self) -> bool:
            fused = self._commit % 2 == 0
            saved = self._fusion_schedule
            if not fused:
                self._fusion_schedule = None
            t0 = time.perf_counter()
            try:
                return super().step()
            finally:
                dt = time.perf_counter() - t0
                self._fusion_schedule = saved
                (self.fused_t if fused else self.unfused_t).append(dt)

    def typical(values: list) -> float:
        values = sorted(values)
        mid = len(values) // 2
        return values[mid] if len(values) % 2 else (values[mid - 1] + values[mid]) / 2

    rows_even = make_rows([per] * n_commits)
    prev_fusion = os.environ.get("PATHWAY_FUSION")
    prev_profile = os.environ.get("PATHWAY_PROFILE")
    os.environ["PATHWAY_FUSION"] = "on"
    # per-operator profiling off for the measured arms (it costs the same in
    # both, but the A/B is about the dispatch path, not the metrics plane)
    os.environ["PATHWAY_PROFILE"] = "0"

    def ab_pass() -> tuple:
        build_graph(rows_even)
        runner = ToggleRunner(pg.G._current)
        gc.collect()
        gc.disable()
        try:
            runner.run(monitoring_level=pw.MonitoringLevel.NONE)
        finally:
            gc.enable()
        stats = [
            it.stats()
            for it in (runner._fusion_schedule or [])
            if hasattr(it, "stats")
        ]
        # drop per-arm warmup (the first fused commit pays every jit compile,
        # the first unfused commit pays first-touch state growth) and, in BOTH
        # arms symmetrically, the near-zero trailing drain steps the run loop
        # appends after sources finish — falling back to the raw samples if a
        # very fast host filters an arm empty
        def arm(samples: list) -> list:
            kept = [x for x in samples[1:] if x > 1e-4]
            return kept or samples[1:] or samples
        return typical(arm(runner.fused_t)), typical(arm(runner.unfused_t)), stats

    # -- numpy proxy: same per-commit computation, vectorized the obvious way
    def proxy_pass() -> float:
        group_sums: dict = {}
        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            for ci in range(n_commits):
                sl = slice(ci * per, (ci + 1) * per)
                c = {
                    "uid": uids[sl], "amount": amounts[sl], "qty": qtys[sl],
                    "ts": tss[sl], "cat": cats[sl],
                }
                c.update(_derive(c, np.where))
                c.update(_seed_feats(c, np.where))
                for _s in range(N_STAGES):
                    c.update(_stage(c, np.where))
                keep = np.asarray(_gate(c))
                kept = {k: v[keep] for k, v in c.items()
                        if k in ("uid", "net", "bucket", "fa", "fb", "fc")}
                kept.update(_finalize(kept, np.where))
                reg = b_region[kept["uid"]]
                tier = b_tier[kept["uid"]]
                boosted = kept["final"] * (tier + 1)
                margin = boosted - (kept["cap"] // 2 + kept["bucket"])
                s = np.zeros(7, dtype=np.int64)
                m = np.zeros(7, dtype=np.int64)
                cnt = np.zeros(7, dtype=np.int64)
                np.add.at(s, reg, kept["net"])
                np.add.at(m, reg, margin)
                np.add.at(cnt, reg, 1)
                for g in range(7):
                    prev = group_sums.get(g, (0, 0, 0))
                    group_sums[g] = (
                        prev[0] + int(s[g]), prev[1] + int(m[g]), prev[2] + int(cnt[g]),
                    )
            return (time.perf_counter() - t0) / n_commits
        finally:
            gc.enable()

    # engine A/B passes and proxy passes INTERLEAVE so each (engine, proxy)
    # pair sees the same phase of this host's cpu-share throttle, and the
    # headline numbers are MEDIANS OF PER-PASS RATIOS: a ratio computed inside
    # one pass compares like with like even while absolute times drift ±30%
    # between passes (a proxy measured minutes after the engine would
    # effectively compare across different machines)
    pairs = []
    for _ in range(3):
        fused_i, unfused_i, stats_i = ab_pass()
        proxy_i = proxy_pass()
        pairs.append((fused_i, unfused_i, proxy_i, stats_i))
    speedup = sorted(u / f for f, u, _p, _s in pairs)[1]
    vs_numpy = sorted(p / f for f, _u, p, _s in pairs)[1]
    vs_numpy_unfused = sorted(p / u for _f, u, p, _s in pairs)[1]
    fused_s, unfused_s, numpy_s, chain_stats = sorted(pairs, key=lambda p: p[0])[1]

    # -- bitwise honesty: fused (XLA path FORCED down to small batches) vs
    # unfused sink bytes over a seeded multi-commit stream
    prev_jit_rows = os.environ.get("PATHWAY_FUSION_JIT_ROWS")
    os.environ["PATHWAY_FUSION_JIT_ROWS"] = "512"
    bit_rows = make_rows([4_000] * 4)
    captures: dict = {}
    for mode in ("on", "off"):
        os.environ["PATHWAY_FUSION"] = mode
        got: list = []
        build_graph(bit_rows, capture=got)
        GraphRunner(pg.G._current).run(monitoring_level=pw.MonitoringLevel.NONE)
        captures[mode] = got
    bitwise_equal = captures["on"] == captures["off"]

    # -- ragged commit sizes: pow2 bucketing must bound recompiles
    os.environ["PATHWAY_FUSION"] = "on"
    os.environ["PATHWAY_FUSION_JIT_ROWS"] = "1024"
    ragged_sizes = [3_000, 5_000, 9_000, 3_500, 6_500, 12_000, 4_100, 7_900]
    build_graph(make_rows(ragged_sizes))
    runner = GraphRunner(pg.G._current)
    runner.run(monitoring_level=pw.MonitoringLevel.NONE)
    ragged_stats = [
        it.stats() for it in (runner._fusion_schedule or []) if hasattr(it, "stats")
    ]
    if prev_jit_rows is None:
        os.environ.pop("PATHWAY_FUSION_JIT_ROWS", None)
    else:
        os.environ["PATHWAY_FUSION_JIT_ROWS"] = prev_jit_rows
    if prev_fusion is None:
        os.environ.pop("PATHWAY_FUSION", None)
    else:
        os.environ["PATHWAY_FUSION"] = prev_fusion
    if prev_profile is None:
        os.environ.pop("PATHWAY_PROFILE", None)
    else:
        os.environ["PATHWAY_PROFILE"] = prev_profile

    chain_ops = sum(len(s["nodes"]) for s in chain_stats)
    return {
        "fused_join_speedup": round(speedup, 3),
        "join_vs_numpy": round(vs_numpy, 3),
        "fusion_join_vs_numpy_unfused": round(vs_numpy_unfused, 3),
        "fusion_fused_commit_ms": round(fused_s * 1000, 2),
        "fusion_unfused_commit_ms": round(unfused_s * 1000, 2),
        "fusion_numpy_commit_ms": round(numpy_s * 1000, 2),
        "fusion_rows_per_commit": per,
        "fusion_ops_fused": chain_ops,
        "fusion_chains": len(chain_stats),
        "fusion_jit_compiles": sum(s["jit_compiles"] for s in chain_stats),
        "fusion_jit_verified": sum(s["jit_verified"] for s in chain_stats),
        "fusion_parity_rejects": sum(s["jit_disabled"] for s in chain_stats),
        "bitwise_equal": bool(bitwise_equal),
        "fusion_ragged_commits": len(ragged_sizes),
        "fusion_ragged_jit_compiles": sum(s["jit_compiles"] for s in ragged_stats),
        "fusion_ragged_shape_buckets": len(
            {b for s in ragged_stats for b in s["jit_buckets"]}
        ),
    }


def bench_scale() -> dict:
    """Honest at-scale run (BASELINE north star): ~10M x 384 vectors with REAL
    MiniLM embedding geometry through ingest -> index -> query.

    Corpus construction is reported in the keys, not hidden: ``scale_real_docs``
    texts are embedded with the production encoder; the remainder is
    manifold-sampled from those embeddings (real vector + gaussian noise at 25%
    of the measured mean nearest-neighbor distance, re-normalized) — the
    distribution ANN indexes face, unlike gaussian-cluster toys. Vectors are
    stored bfloat16 so the full corpus fits one v5e chip's HBM (10M x 384 x 2B
    = 7.7 GB); recall@10 is IVF measured against the exact dense search over
    the SAME corpus. At reduced scale (smoke/fallback) the numbers only prove
    the code path."""
    import jax
    import jax.numpy as jnp

    from pathway_tpu.models.encoder import JaxSentenceEncoder
    from pathway_tpu.ops.knn import DenseKNNStore
    from pathway_tpu.ops.knn_ivf import IvfKnnStore

    n_total = 50_000 if DEVICE_SCALE_DOWN else 10_000_000
    n_real = 2_000 if DEVICE_SCALE_DOWN else 200_000
    n_queries = 256 if DEVICE_SCALE_DOWN else 1024
    dim = 384
    k = 10
    chunk = 10_000 if DEVICE_SCALE_DOWN else 100_000

    enc = JaxSentenceEncoder()
    rng = np.random.default_rng(7)
    topics = [f"topic{i}" for i in range(997)]

    def texts(start: int, count: int) -> list:
        return [
            f"document {start + i} about {topics[(start + i) % 997]} and "
            f"{topics[(start + i * 31) % 997]} with detail {(start + i) % 89}"
            for i in range(count)
        ]

    t0 = time.perf_counter()
    bs = 512 if DEVICE_SCALE_DOWN else 2048
    base_parts = []
    for s in range(0, n_real, bs):
        base_parts.append(enc.encode(texts(s, min(bs, n_real - s))))
    base = np.concatenate(base_parts).astype(np.float32)
    embed_s = time.perf_counter() - t0

    # noise scale from the real corpus's own geometry: mean NN distance on a
    # sample. The 25%-of-NN-distance budget is the DISPLACEMENT NORM, so the
    # per-coordinate std divides by sqrt(dim) — passing the norm directly as the
    # coordinate std (the r4 bug) inflates displacement by sqrt(384) ~ 19.6x and
    # turns the corpus into near-uniform sphere noise, which has no manifold
    # structure (nothing like real embeddings) and is the degenerate worst case
    # for any ANN index.
    sample = base[rng.choice(n_real, size=min(2048, n_real), replace=False)]
    d2 = (
        np.sum(sample * sample, axis=1)[:, None]
        + np.sum(sample * sample, axis=1)[None, :]
        - 2.0 * sample @ sample.T
    )
    np.fill_diagonal(d2, np.inf)
    nn_dist = float(np.mean(np.sqrt(np.maximum(d2.min(axis=1), 0.0))))
    sigma = 0.25 * nn_dist / float(np.sqrt(dim))

    def corpus_chunk(start: int, count: int) -> np.ndarray:
        take = rng.integers(0, n_real, count)
        out = base[take] + rng.normal(scale=sigma, size=(count, dim)).astype(np.float32)
        out /= np.maximum(np.linalg.norm(out, axis=1, keepdims=True), 1e-9)
        return out.astype(np.float32)

    qtexts = texts(10_000_000_000, n_queries)
    queries = np.concatenate(
        [enc.encode(qtexts[s : s + bs]) for s in range(0, n_queries, bs)]
    ).astype(np.float32)

    results: dict = {
        "scale_docs": n_total,
        "scale_real_docs": n_real,
        "scale_embed_docs_per_s": round(n_real / embed_s, 1),
        "scale_nn_dist": round(nn_dist, 4),
        "scale_noise_norm": round(0.25 * nn_dist, 4),
    }

    # corpus held on host in f16 (7.7 GB at full scale) so dense and IVF ingest
    # the IDENTICAL vectors without doubling device HBM
    corpus = np.empty((n_total, dim), dtype=np.float16)
    for s in range(0, n_total, chunk):
        corpus[s : s + chunk] = corpus_chunk(s, min(chunk, n_total - s))

    store = DenseKNNStore(dim, metric="l2sq", initial_capacity=n_total, dtype=jnp.bfloat16)
    t0 = time.perf_counter()
    for s in range(0, n_total, chunk):
        end = min(s + chunk, n_total)
        store.add_many(list(range(s, end)), corpus[s:end].astype(np.float32))
        store._flush()
    jax.block_until_ready(store._data)
    results["scale_ingest_docs_per_s"] = round(n_total / (time.perf_counter() - t0), 1)

    store.search_batch(queries, k)  # compile off the clock
    lat = []
    for _ in range(5):
        t1 = time.perf_counter()
        dense_scores, dense_idx, _ = store.search_batch(queries, k)
        lat.append(time.perf_counter() - t1)
    med = float(np.median(lat))
    results["scale_dense_qps"] = round(n_queries / med, 1)
    results["scale_dense_p50_batch_ms"] = round(med * 1000.0, 2)
    dense_keys = np.vectorize(lambda s_: store.key_of.get(int(s_), -1))(dense_idx)
    del store  # free HBM before the IVF copy

    # cluster count: pow2 with ~640 docs/cluster, so probe=8 touches < 1% of the
    # corpus at 10M (16384 clusters) — bytes gathered per query stay under the
    # per-query share of a full dense scan, which is where the qps win comes from
    n_clusters = 64
    while n_clusters * 640 < n_total and n_clusters < 16384:
        n_clusters *= 2
    ivf = IvfKnnStore(
        dim, metric="l2sq", initial_capacity=n_total,
        n_clusters=n_clusters, n_probe=8,
        dtype=jnp.bfloat16,
    )
    t0 = time.perf_counter()
    for s in range(0, n_total, chunk):
        end = min(s + chunk, n_total)
        ivf.add_many(list(range(s, end)), corpus[s:end].astype(np.float32))
        ivf._flush()  # per-chunk: ONE staged mega-flush would pad 10M rows to 16M f32
    ivf.search_batch(queries, k)  # train + compile off the clock
    results["scale_ivf_train_plus_ingest_s"] = round(time.perf_counter() - t0, 1)

    # auto-tune n_probe (faiss-style): smallest probe count reaching >=0.95
    # recall@10 on a query subsample, then measure qps at that operating point.
    # The chosen probe is REPORTED — recall and speed are both in the artifact.
    tune_n = min(128, n_queries)

    def _recall(idx_rows: np.ndarray, n_rows: int) -> float:
        keys = np.vectorize(lambda s_: ivf.key_of.get(int(s_), -1))(idx_rows)
        return float(
            np.mean(
                [len(set(keys[r]) & set(dense_keys[r])) / k for r in range(n_rows)]
            )
        )

    probe_cap = min(ivf.n_clusters, 256)
    probe = ivf.n_probe
    while True:
        ivf.n_probe = probe
        _s, tune_idx, _v = ivf.search_batch(queries[:tune_n], k)
        r = _recall(tune_idx, tune_n)
        if r >= 0.95 or probe >= probe_cap:
            break
        probe = min(probe * 2, probe_cap)
    results["scale_ivf_n_probe"] = probe

    lat = []
    for _ in range(5):
        t1 = time.perf_counter()
        _sc, ivf_idx, _v = ivf.search_batch(queries, k)
        lat.append(time.perf_counter() - t1)
    med = float(np.median(lat))
    results["scale_ivf_qps"] = round(n_queries / med, 1)
    results["scale_ivf_p50_batch_ms"] = round(med * 1000.0, 2)
    results["scale_ivf_recall_at_10_vs_exact"] = round(_recall(ivf_idx, n_queries), 4)
    return results


_SHARDED_CHILD = """
import json, time
import numpy as np
import jax
from jax.sharding import Mesh
from pathway_tpu.parallel.knn_sharded import ShardedKNNStore

devices = np.array(jax.devices())
mesh = Mesh(devices, ("data",))
rng = np.random.default_rng(0)
n, dim, q, k = 100_000, 64, 256, 10
data = rng.normal(size=(n, dim)).astype(np.float32)
store = ShardedKNNStore(mesh, dim, metric="l2sq", initial_capacity=n)
t0 = time.perf_counter()
store.add_many(list(range(n)), data)
store._flush()
ingest_s = time.perf_counter() - t0
queries = rng.normal(size=(q, dim)).astype(np.float32)
store.search_batch(queries, k)
lat = []
for _ in range(5):
    t1 = time.perf_counter()
    store.search_batch(queries, k)
    lat.append(time.perf_counter() - t1)
med = float(np.median(lat))
print(json.dumps({
    "sharded_devices": len(devices),
    "sharded_qps": round(q / med, 1),
    "sharded_ingest_docs_per_s": round(n / ingest_s, 1),
}))
"""


def bench_sharded() -> dict:
    """BASELINE #5: sharded index with all-gather top-k merge on a virtual mesh."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        out = subprocess.run(
            [sys.executable, "-c", _SHARDED_CHILD],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        line = out.stdout.strip().splitlines()[-1]
        return json.loads(line)
    except Exception as exc:
        return {"sharded_error": f"{type(exc).__name__}: {exc}"[:200]}


# -- rejoin: bounded-time recovery at any journal length ----------------------

_REJOIN_PROG = """
import json, os, signal, threading, time
import pathway_tpu as pw

tmp = os.environ["PW_BENCH_TMP"]
pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

class WordSchema(pw.Schema):
    word: str

t = pw.io.fs.read(
    os.path.join(tmp, "in"), format="csv", schema=WordSchema,
    mode="streaming", refresh_interval=0.02,
)
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

out_path = os.path.join(tmp, f"out_{pid}.json")
rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
    else:
        rows.pop(repr(key), None)
    with open(out_path + ".tmp", "w") as f:
        json.dump(list(rows.values()), f)
    os.replace(out_path + ".tmp", out_path)

pw.io.subscribe(counts, on_change)

# assassin: the FIRST incarnation of rank 1 SIGKILLs itself when the bench
# drops the marker (time-controlled kills; commit-id gating would race the
# feed). The relaunched incarnation (bumped restart count) must not re-die.
if pid == 1 and int(os.environ.get("PATHWAY_RESTART_COUNT", "0")) == 0:
    marker = os.path.join(tmp, "kill-marker")
    def _assassin():
        while not os.path.exists(marker):
            time.sleep(0.05)
        os.kill(os.getpid(), signal.SIGKILL)
    threading.Thread(target=_assassin, daemon=True).start()

cfg = pw.persistence.Config(
    pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
)
pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
"""


def _journal_frames(path: str) -> int:
    """Count complete frames in one journal shard (magic line + json meta
    line, then 8-byte-BE-length-prefixed frames).

    Standalone copy of the PWTPUJ2 framing from persistence/engine.py —
    the orchestrator never imports pathway_tpu (the jax import chain is what
    the TPU-probe honesty machinery keeps OUT of this process), so it cannot
    call load_journal. The magic check keeps the copy honest: a journal
    format bump fails the bench loudly instead of silently counting garbage
    into the rejoin headline ratios."""
    import struct as _struct

    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return 0
    if not data.startswith(b"PWTPUJ2\n"):
        raise RuntimeError(
            f"journal {path!r} does not start with the PWTPUJ2 magic this "
            "parser understands — persistence/engine.py changed the on-disk "
            "format; update _journal_frames to match"
        )
    off = data.find(b"\n", data.find(b"\n") + 1) + 1
    if off <= 0:
        return 0
    n = 0
    while off + 8 <= len(data):
        (ln,) = _struct.unpack(">Q", data[off:off + 8])
        off += 8 + ln
        if off <= len(data):
            n += 1
    return n


_REJOIN_PORT_SALT = [0]  # distinct port block per run: no TIME_WAIT collisions


def _rejoin_run(tag: str, feed_s: float, ckpt_interval_s: float) -> dict:
    """One measured failover: spawn -n 2, feed the journal for ``feed_s``
    seconds (one tiny csv per source poll -> journal frames grow with feed
    time), SIGKILL rank 1 via the in-program assassin, and parse the
    survivor's rejoin duration + recovery mode from stderr."""
    import re
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix=f"pw-bench-rejoin-{tag}-")
    out: dict = {}
    proc = None
    try:
        os.makedirs(os.path.join(tmp, "in"))
        prog = os.path.join(tmp, "prog.py")
        with open(prog, "w") as f:
            f.write(_REJOIN_PROG)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.abspath(__file__))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        env["PW_BENCH_TMP"] = tmp
        env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
        env["PATHWAY_BARRIER_TIMEOUT_S"] = "120"
        env["PATHWAY_CHECKPOINT_INTERVAL_S"] = str(ckpt_interval_s)
        if not ckpt_interval_s:
            # pre-checkpoint baseline (the PR 3 path): no coordinated
            # checkpoints AND no undo ring — survivors full-replay too
            env["PATHWAY_UNDO_RING_DEPTH"] = "0"
        _REJOIN_PORT_SALT[0] += 1
        first_port = 27000 + (os.getpid() * 16 + _REJOIN_PORT_SALT[0] * 4) % 2600
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "pathway_tpu.cli", "spawn",
                "-n", "2", "--first-port", str(first_port),
                "--max-restarts", "1",
                sys.executable, prog,
            ],
            env=env, cwd=tmp, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
        )

        def _merged() -> dict:
            merged: dict = {}
            for p in range(2):
                path = os.path.join(tmp, f"out_{p}.json")
                try:
                    with open(path) as f:
                        for r in json.load(f):
                            merged[r["word"]] = r["total"]
                except (OSError, ValueError):
                    pass
            return merged

        def _await(expected: dict, deadline_s: float) -> None:
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(f"spawn exited early rc={proc.returncode}")
                if _merged() == expected:
                    return
                time.sleep(0.1)
            raise RuntimeError(f"no convergence to {expected}, got {_merged()}")

        # feed: one file per source poll window grows the journal by roughly
        # one frame per poll — journal length is proportional to feed_s. Each
        # frame carries a realistic row batch (2-row frames would make replay
        # look artificially free next to the fixed relaunch cost)
        cats = 0
        i = 0
        deadline = time.monotonic() + feed_s
        while time.monotonic() < deadline:
            with open(os.path.join(tmp, "in", f"f{i:06d}.csv"), "w") as f:
                f.write("word\n" + "cat\n" * 60)
            cats += 60
            i += 1
            time.sleep(0.02)
        _await({"cat": cats}, 90)
        # journal length AT THE KILL (late data lands after recovery)
        frames = sum(
            _journal_frames(os.path.join(tmp, "store", f"process-{p}", "journal.bin"))
            for p in range(2)
        )
        with open(os.path.join(tmp, "kill-marker"), "w") as f:
            f.write("now")
        # post-failover convergence proves the heal, not just the relaunch
        time.sleep(1.0)
        with open(os.path.join(tmp, "in", "late.csv"), "w") as f:
            f.write("word\nowl\nowl\nowl\n")
        _await({"cat": cats, "owl": 3}, 150)
        # convergence proves the engine healed; give the supervisor a beat to
        # observe the epoch flip in the status files and log the rejoin line
        # this bench parses for its latency number
        time.sleep(2.0)
        out["frames"] = frames
    finally:
        err = ""
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                _, err = proc.communicate(timeout=20)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                _, err = proc.communicate()
        shutil.rmtree(tmp, ignore_errors=True)
    # the SUPERVISOR's wall clock is the honest rejoin latency: relaunch of the
    # killed rank -> every status file reports the new epoch. It covers the
    # replacement's journal-proportional recovery, which is what this bench
    # sweeps (a survivor's own rejoin line would mix in the O(1) rewind rung)
    m = re.search(
        r"rank 1 rejoined the cluster at epoch 1 in ([0-9.]+)s", err or ""
    )
    if not m:
        raise RuntimeError(f"no supervisor rejoin line in stderr:\n{(err or '')[-2000:]}")
    out["rejoin_s"] = float(m.group(1))
    out["mode"] = (
        "checkpoint+tail replay"
        if "cold-starting from cluster checkpoint manifest" in (err or "")
        else "full journal replay"
    )
    return out


def bench_rejoin() -> dict:
    """Recovery-SLO headline: survivor rejoin latency vs journal length, with
    coordinated checkpoints OFF (pre-checkpoint path: full journal-union
    replay, grows linearly) and ON (checkpoint + bounded tail: flat). The
    acceptance claim is the ckpt ratio staying within 2x while the journal
    grows ~10x. CPU-only (localhost cluster) — honest on any host."""
    feed_1x, feed_10x = (2.0, 20.0) if DEVICE_SCALE_DOWN else (3.0, 30.0)
    res: dict = {}
    runs = {
        ("replay", "1x"): (feed_1x, 0.0),
        ("replay", "10x"): (feed_10x, 0.0),
        ("ckpt", "1x"): (feed_1x, 0.3),
        ("ckpt", "10x"): (feed_10x, 0.3),
    }
    for (kind, scale), (feed_s, interval) in runs.items():
        r = _rejoin_run(f"{kind}-{scale}", feed_s, interval)
        res[f"rejoin_{kind}_{scale}_s"] = round(r["rejoin_s"], 2)
        res[f"rejoin_{kind}_{scale}_frames"] = r["frames"]
        res[f"rejoin_{kind}_{scale}_mode"] = r["mode"]
    res["rejoin_journal_growth"] = round(
        res["rejoin_replay_10x_frames"] / max(1, res["rejoin_replay_1x_frames"]), 1
    )
    res["rejoin_replay_growth_ratio"] = round(
        res["rejoin_replay_10x_s"] / max(1e-9, res["rejoin_replay_1x_s"]), 2
    )
    res["rejoin_ckpt_flat_ratio"] = round(
        res["rejoin_ckpt_10x_s"] / max(1e-9, res["rejoin_ckpt_1x_s"]), 2
    )
    # the acceptance headline: checkpointed rejoin stays flat (within 2x)
    # while the journal grows ~10x
    res["rejoin_ckpt_flat"] = bool(res["rejoin_ckpt_flat_ratio"] <= 2.0)
    return res


_ELASTIC_PROG = """
import json, os
import pathway_tpu as pw

tmp = os.environ["PW_BENCH_TMP"]
pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

class WordSchema(pw.Schema):
    word: str

t = pw.io.fs.read(
    os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming"
)
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())

out_path = os.path.join(tmp, f"out_{pid}.json")
rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
    else:
        rows.pop(repr(key), None)
    with open(out_path + ".tmp", "w") as f:
        json.dump(list(rows.values()), f)
    os.replace(out_path + ".tmp", out_path)

pw.io.subscribe(counts, on_change)
cfg = pw.persistence.Config(
    pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
)
pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
"""


def _elastic_cycle(
    prog_text: str,
    prefix: str,
    *,
    feed_total_s: float,
    rows_per_file: int,
    port_base: int,
    scale_plan: list,
) -> dict:
    """One spawn n=2 -> 4 -> 2 scale cycle under live ingestion for
    ``prog_text``; returns ``{prefix}_*`` keys (pause p50/max, rows handed
    off/s, throughput dip, exactness + joiner-catch-up honesty keys)."""
    import re
    import shutil
    import statistics
    import tempfile

    tmp = tempfile.mkdtemp(prefix=f"pw-bench-{prefix}-")
    res: dict = {}
    proc = None
    try:
        os.makedirs(os.path.join(tmp, "in"))
        prog = os.path.join(tmp, "prog.py")
        with open(prog, "w") as f:
            f.write(prog_text)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.abspath(__file__))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        env["PW_BENCH_TMP"] = tmp
        env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
        env["PATHWAY_BARRIER_TIMEOUT_S"] = "120"
        env["PATHWAY_MEMBERSHIP_DEADLINE_S"] = "90"
        env["PATHWAY_SCALE_PLAN"] = json.dumps(scale_plan)
        _REJOIN_PORT_SALT[0] += 1
        first_port = port_base + (os.getpid() * 16 + _REJOIN_PORT_SALT[0] * 4) % 2600
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "pathway_tpu.cli", "spawn",
                "-n", "2", "--first-port", str(first_port),
                "--max-restarts", "2",
                sys.executable, prog,
            ],
            env=env, cwd=tmp, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
        )

        def _total() -> int:
            total = 0
            for p in range(4):
                try:
                    with open(os.path.join(tmp, f"out_{p}.json")) as f:
                        total += sum(r["total"] for r in json.load(f))
                except (OSError, ValueError):
                    pass
            return total

        # steady feed; sample delivered-output totals on a fixed clock so the
        # transition windows show up as rate dips in the timeline
        fed = 0
        i = 0
        samples: list = []  # (t, delivered_total)
        deadline = time.monotonic() + feed_total_s
        t0 = time.monotonic()
        while time.monotonic() < deadline:
            with open(os.path.join(tmp, "in", f"f{i:06d}.csv"), "w") as f:
                f.write("word\n" + f"w{i % 23}\n" * rows_per_file)
            fed += rows_per_file
            i += 1
            samples.append((time.monotonic() - t0, _total()))
            time.sleep(0.05)
        # convergence: everything fed is delivered exactly once
        conv_deadline = time.monotonic() + 60
        while time.monotonic() < conv_deadline:
            if proc.poll() is not None:
                raise RuntimeError(f"spawn exited early rc={proc.returncode}")
            if _total() == fed:
                break
            time.sleep(0.1)
        if _total() != fed:
            raise RuntimeError(f"no convergence: fed {fed}, got {_total()}")
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        _out, err = proc.communicate(timeout=30)
        proc = None
        # per-rank transition durations ("reshard pause": the commit loop's
        # time inside MEMBERSHIP_CHANGE) + rows handed off
        pauses = [
            float(m)
            for m in re.findall(
                r"membership transition to n=\d+ complete .* in ([0-9.]+)s", err
            )
        ]
        drains = [
            float(m)
            for m in re.findall(r"drained for scale-down .* in ([0-9.]+)s", err)
        ]
        handed = [
            int(m) for m in re.findall(r"(\d+) row\(s\) handed off", err)
        ]
        tails = [
            int(m)
            for m in re.findall(
                r"membership manifest \+ handoff fragments at commit \d+ "
                r"\(\+(\d+) journal tail frame\(s\)\)",
                err,
            )
        ]
        if not pauses:
            raise RuntimeError(f"no completed transitions in stderr:\n{err[-2000:]}")
        all_pauses = pauses + drains
        res[f"{prefix}_reshard_pause_p50_s"] = round(
            statistics.median(all_pauses), 3
        )
        res[f"{prefix}_reshard_pause_max_s"] = round(max(all_pauses), 3)
        res[f"{prefix}_rows_handed_off"] = int(sum(handed))
        res[f"{prefix}_rows_handed_off_per_s"] = round(
            sum(handed) / max(1e-9, sum(all_pauses)), 1
        )
        # throughput dip: delivered-rows/s in the worst 2 s window vs the
        # overall steady rate (the transitions are the stalls)
        rates: list = []
        for a in range(len(samples)):
            b = a
            while b + 1 < len(samples) and samples[b + 1][0] - samples[a][0] < 2.0:
                b += 1
            if b > a:
                dt = samples[b][0] - samples[a][0]
                rates.append((samples[b][1] - samples[a][1]) / dt)
        steady = statistics.median(rates) if rates else 0.0
        worst = min(rates) if rates else 0.0
        res[f"{prefix}_throughput_dip_pct"] = (
            round(100.0 * (1.0 - worst / steady), 1) if steady > 0 else None
        )
        res[f"{prefix}_ingest_rows_per_s"] = round(steady, 1)
        # honesty keys: both transitions completed, joiners caught up from
        # manifest + fragments with a near-empty tail, and never a restart
        res[f"{prefix}_transitions_complete"] = (
            "membership change complete: cluster is n=4" in err
            and "membership change complete: cluster is n=2" in err
        )
        res[f"{prefix}_join_tail_frames_max"] = max(tails) if tails else None
        res[f"{prefix}_join_no_replay"] = bool(
            tails
            and max(tails) <= 2
            and err.count("no journal replay") >= 2
            and "restarting the cluster" not in err
        )
        res[f"{prefix}_exact"] = _total() == fed
        return res
    finally:
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.communicate()
        shutil.rmtree(tmp, ignore_errors=True)


_ELASTIC_JOINDEDUP_PROG = """
import json, os
import pathway_tpu as pw

tmp = os.environ["PW_BENCH_TMP"]
pid = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

class WordSchema(pw.Schema):
    word: str

t = pw.io.fs.read(
    os.path.join(tmp, "in"), format="csv", schema=WordSchema, mode="streaming"
)
counts = t.groupby(t.word).reduce(t.word, total=pw.reducers.count())
joined = t.join(counts, t.word == counts.word).select(t.word, total=counts.total)
best = joined.deduplicate(
    value=joined.total, instance=joined.word, acceptor=lambda new, old: new >= old
)
final = best.with_id_from(best.word)

out_path = os.path.join(tmp, f"out_{pid}.json")
rows = {}
def on_change(key, row, time, is_addition):
    if is_addition:
        rows[repr(key)] = {"word": row["word"], "total": int(row["total"])}
    else:
        rows.pop(repr(key), None)
    with open(out_path + ".tmp", "w") as f:
        json.dump(list(rows.values()), f)
    os.replace(out_path + ".tmp", out_path)

pw.io.subscribe(final, on_change)
cfg = pw.persistence.Config(
    pw.persistence.Backend.filesystem(os.path.join(tmp, "store"))
)
pw.run(persistence_config=cfg, monitoring_level=pw.MonitoringLevel.NONE)
"""


def _bench_handoff_rss_sweep() -> dict:
    """Peak-handoff-memory honesty key: partition the same join+dedup graph's
    state at 1x / 2x / 4x size through BOTH transports and report the peak
    transport allocation (tracemalloc, donor side, state excluded via
    reset_peak). The chunked schedule must stay flat (<= 1.5x across the 4x
    sweep) while the gather baseline grows ~linearly with state — in-process
    and CPU-only, honest on any host."""
    import pickle as _pickle
    import tracemalloc

    import pathway_tpu as pw
    from pathway_tpu.engine.runner import GraphRunner
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.parallel.membership import (
        build_fragment_chunks,
        build_fragments,
        compute_reshard_plan,
    )

    # production-shaped rows: a few-hundred-byte payload per row, so state is
    # payload-dominated (the regime the chunked transport bounds); the O(rows)
    # int owner metadata the exporters scan is second-order and amortizes
    # under the chunk budget
    # (the budget scales with the profile: state must exceed several chunks
    # at the smallest sweep point or the sweep never leaves the 1-chunk
    # regime and measures nothing)
    chunk_bytes = 1 << 18 if DEVICE_SCALE_DOWN else 1 << 20
    payload = "x" * 400

    def runner_with_rows(n_rows: int) -> GraphRunner:
        pg.G.clear()
        left = pw.debug.table_from_rows(
            pw.schema_builder({"k": int, "a": int, "p": str}),
            [(i, i * 3, payload + str(i)) for i in range(n_rows)],
        )
        right = pw.debug.table_from_rows(
            pw.schema_builder({"k": int, "b": int}),
            [(i, i * 7) for i in range(n_rows)],
        )
        joined = left.join(right, left.k == right.k).select(
            left.a, left.p, right.b
        )
        best = joined.deduplicate(
            value=joined.b, instance=joined.a, acceptor=lambda new, old: new >= old
        )
        pw.io.subscribe(best, lambda *a, **kw: None)
        runner = GraphRunner(pg.G._current)
        runner.lint_exempt = True
        runner.run(monitoring_level=pw.MonitoringLevel.NONE, max_commits=3)
        return runner

    base_rows = 1500 if DEVICE_SCALE_DOWN else 4000
    sizes = [base_rows, base_rows * 2, base_rows * 4]
    chunk_peaks: list = []
    gather_peaks: list = []
    for n_rows in sizes:
        runner = runner_with_rows(n_rows)
        for node in runner._nodes:
            ev = runner.evaluators[node.id]
            ev._cluster_policies = tuple(
                ev.cluster_input_policy(i) for i in range(len(node.inputs))
            )
        plan = compute_reshard_plan(runner)
        if not plan.ok:
            raise RuntimeError(f"reshard plan refused: {plan.refusals}")
        tracemalloc.start()
        try:
            # chunked: the donor only ever holds open chunks + one pickle
            tracemalloc.reset_peak()
            chunk_iter, _stats = build_fragment_chunks(
                runner, plan, 2, commit=3, generation=1, chunk_bytes=chunk_bytes
            )
            for _dest, chunk in chunk_iter:
                _pickle.dumps(chunk, protocol=_pickle.HIGHEST_PROTOCOL)
            chunk_peaks.append(tracemalloc.get_traced_memory()[1])
            # gather baseline: every destination's full fragment materializes
            # at once before any write
            tracemalloc.reset_peak()
            frags, _stats = build_fragments(runner, plan, 2, commit=3, generation=1)
            for _dest, frag in sorted(frags.items()):
                _pickle.dumps(frag, protocol=_pickle.HIGHEST_PROTOCOL)
            del frags
            gather_peaks.append(tracemalloc.get_traced_memory()[1])
        finally:
            tracemalloc.stop()
        pg.G.clear()
    return {
        "elastic_handoff_state_rows": sizes,
        "elastic_handoff_chunk_bytes": chunk_bytes,
        "elastic_handoff_chunked_peak_mb": [
            round(p / 1e6, 2) for p in chunk_peaks
        ],
        "elastic_handoff_gather_peak_mb": [
            round(p / 1e6, 2) for p in gather_peaks
        ],
        "elastic_chunk_peak_growth_x": round(
            chunk_peaks[-1] / max(1, chunk_peaks[0]), 2
        ),
        "elastic_gather_peak_growth_x": round(
            gather_peaks[-1] / max(1, gather_peaks[0]), 2
        ),
        # the honesty key: chunked flat across a 4x state sweep, gather is not
        "elastic_chunk_peak_flat": bool(
            chunk_peaks[-1] <= 1.5 * chunk_peaks[0]
            and gather_peaks[-1] >= 2.0 * gather_peaks[0]
        ),
    }


def bench_elastic() -> dict:
    """Elastic-membership headline: n=2 -> 4 -> 2 scale cycles under live
    ingestion, for a groupby pipeline AND a join+dedup-heavy pipeline (the
    graphs the preflight refused before universal reshardability). Measures
    the reshard pause (per-rank transition duration, the window the commit
    loop spends inside MEMBERSHIP_CHANGE), the ingest throughput dip around
    the transitions, rows handed off per second, and two honesty families:
    every joiner catches up from the membership manifest + handoff fragments
    with a near-empty journal tail (never a full-history replay), and the
    chunked transport's peak handoff memory stays FLAT across a 4x
    state-size sweep while the gather baseline grows ~linearly. CPU-only
    (localhost cluster) — honest on any host; feed scales down on fallback
    like the other sections."""
    res = _elastic_cycle(
        _ELASTIC_PROG,
        "elastic",
        feed_total_s=10.0 if DEVICE_SCALE_DOWN else 18.0,
        rows_per_file=40 if DEVICE_SCALE_DOWN else 80,
        port_base=29200,
        scale_plan=[{"after_commit": 8, "n": 4}, {"after_commit": 30, "n": 2}],
    )
    res.update(
        _elastic_cycle(
            _ELASTIC_JOINDEDUP_PROG,
            "elastic_joindedup",
            feed_total_s=8.0 if DEVICE_SCALE_DOWN else 12.0,
            rows_per_file=30 if DEVICE_SCALE_DOWN else 60,
            port_base=30100,
            scale_plan=[
                {"after_commit": 8, "n": 4},
                {"after_commit": 24, "n": 2},
            ],
        )
    )
    res.update(_bench_handoff_rss_sweep())
    return res


def bench_autoscale() -> dict:
    """Closed-loop autoscaler headline: a ramping synthetic load at n=2 must
    scale the cluster to 4 and back to 2 with NO operator input. The load
    profile is a chaos-plan ``load_spike`` (deterministic; the same op the
    tests replay), fed as CSV files whose rate follows ``Chaos.load_rate``.
    Reports time-to-scale (spike start -> cluster stable at n=4, observed
    through the supervisor control endpoint's ``status`` command), the shed
    rate the controller saw during the scale window, the reshard pauses, and
    a NO-FLAP honesty key: exactly one transition per direction, flap lock
    never engaged, final delivered counts exact. CPU-only (localhost
    cluster) — honest on any host."""
    import re
    import shutil
    import socket as socket_mod
    import tempfile

    from pathway_tpu.internals.chaos import Chaos

    base_rate = 80.0 if DEVICE_SCALE_DOWN else 140.0
    spike_rate = 650.0 if DEVICE_SCALE_DOWN else 1100.0
    spike_at_s, spike_len_s = 4.0, 9.0
    feed_total_s = 20.0
    rows_per_worker = 180.0 if DEVICE_SCALE_DOWN else 300.0
    load = Chaos(0, {"load": {
        "op": "load_spike", "at_s": spike_at_s, "duration_s": spike_len_s,
        "low": base_rate, "high": spike_rate,
    }})
    tmp = tempfile.mkdtemp(prefix="pw-bench-autoscale-")
    res: dict = {}
    proc = None
    try:
        os.makedirs(os.path.join(tmp, "in"))
        prog = os.path.join(tmp, "prog.py")
        with open(prog, "w") as f:
            f.write(_ELASTIC_PROG)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (
            os.path.dirname(os.path.abspath(__file__))
            + os.pathsep + env.get("PYTHONPATH", "")
        )
        env["PW_BENCH_TMP"] = tmp
        env["PATHWAY_HEARTBEAT_INTERVAL_S"] = "0.2"
        env["PATHWAY_BARRIER_TIMEOUT_S"] = "120"
        env["PATHWAY_MEMBERSHIP_DEADLINE_S"] = "90"
        env["PATHWAY_AUTOSCALE"] = "on"
        env["PATHWAY_AUTOSCALE_MIN"] = "2"
        env["PATHWAY_AUTOSCALE_MAX"] = "4"
        env["PATHWAY_AUTOSCALE_ROWS_PER_WORKER"] = str(rows_per_worker)
        env["PATHWAY_AUTOSCALE_SAMPLE_S"] = "0.5"
        env["PATHWAY_AUTOSCALE_UP_SAMPLES"] = "2"
        env["PATHWAY_AUTOSCALE_DOWN_SAMPLES"] = "4"
        env["PATHWAY_AUTOSCALE_UP_COOLDOWN_S"] = "2"
        env["PATHWAY_AUTOSCALE_DOWN_COOLDOWN_S"] = "4"
        env["PATHWAY_AUTOSCALE_FLAP_WINDOW_S"] = "60"
        env["PATHWAY_AUTOSCALE_FLAP_REVERSALS"] = "3"
        _REJOIN_PORT_SALT[0] += 1
        first_port = 23400 + (os.getpid() * 16 + _REJOIN_PORT_SALT[0] * 4) % 2600
        control_port = first_port + 1299
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "pathway_tpu.cli", "spawn",
                "-n", "2", "--first-port", str(first_port),
                "--max-restarts", "2",
                "--control-port", str(control_port),
                sys.executable, prog,
            ],
            env=env, cwd=tmp, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
        )

        def _control_status() -> dict:
            try:
                with socket_mod.create_connection(
                    ("127.0.0.1", control_port), timeout=2.0
                ) as conn:
                    conn.sendall(b"status\n")
                    buf = b""
                    while not buf.endswith(b"\n"):
                        chunk = conn.recv(4096)
                        if not chunk:
                            break
                        buf += chunk
                return json.loads(buf.decode())
            except (OSError, ValueError):
                return {}

        def _total() -> int:
            total = 0
            for p in range(4):
                try:
                    with open(os.path.join(tmp, f"out_{p}.json")) as f:
                        total += sum(r["total"] for r in json.load(f))
                except (OSError, ValueError):
                    pass
            return total

        # feed at the chaos-plan load profile; observe topology through the
        # control endpoint's status command on a fixed clock
        fed = 0
        i = 0
        t0 = time.monotonic()
        seen_n: list = []  # (elapsed, n, max_shed_rate)
        carry = 0.0
        last_tick = 0.0
        while True:
            elapsed = time.monotonic() - t0
            if elapsed >= feed_total_s:
                break
            rate = load.load_rate(elapsed)
            if rate is None:  # 0.0 is a legitimate idle rate, not "no profile"
                rate = base_rate
            carry += rate * max(0.0, elapsed - last_tick)
            last_tick = elapsed
            rows = int(carry)
            if rows > 0:
                carry -= rows
                with open(os.path.join(tmp, "in", f"f{i:06d}.csv"), "w") as f:
                    f.write("word\n" + f"w{i % 23}\n" * rows)
                fed += rows
                i += 1
            status = _control_status()
            if status:
                ctrl = status.get("autoscaler") or {}
                signals = ctrl.get("signals") or {}
                seen_n.append((
                    elapsed,
                    int(status.get("n") or 0),
                    float(signals.get("shed_rate") or 0.0),
                ))
            time.sleep(0.1)
        # convergence: everything fed is delivered exactly once (and the
        # cluster is back at n=2 — the scale-in under the fading load)
        conv_deadline = time.monotonic() + 90
        back_to_2 = None
        while time.monotonic() < conv_deadline:
            if proc.poll() is not None:
                raise RuntimeError(f"spawn exited early rc={proc.returncode}")
            status = _control_status()
            n_now = int(status.get("n") or 0) if status else 0
            if back_to_2 is None and n_now == 2 and any(
                n == 4 for _t, n, _s in seen_n
            ):
                back_to_2 = time.monotonic() - t0
            if _total() == fed and n_now == 2 and not status.get(
                "transition_in_flight"
            ):
                break
            time.sleep(0.2)
        if _total() != fed:
            raise RuntimeError(f"no convergence: fed {fed}, got {_total()}")
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        _out, err = proc.communicate(timeout=30)
        proc = None
        first_at_4 = next((t for t, n, _s in seen_n if n >= 4), None)
        res["autoscale_time_to_scale_s"] = (
            round(first_at_4 - spike_at_s, 2) if first_at_4 is not None else None
        )
        res["autoscale_scale_in_at_s"] = (
            round(back_to_2, 2) if back_to_2 is not None else None
        )
        res["autoscale_shed_rate_window_max"] = round(
            max((s for _t, _n, s in seen_n), default=0.0), 2
        )
        pauses = [
            float(m)
            for m in re.findall(
                r"membership transition to n=\d+ complete .* in ([0-9.]+)s", err
            )
        ]
        res["autoscale_reshard_pause_max_s"] = (
            round(max(pauses), 3) if pauses else None
        )
        res["autoscale_ingest_rows_per_s"] = round(fed / feed_total_s, 1)
        requested = re.findall(r"membership change requested: n=\d+ -> n=(\d+)", err)
        # honesty keys: scaled out AND back with no operator input, exactly
        # one transition per direction, the flap lock never engaged, counts
        # exact — an autoscaler that oscillates or loses rows fails loudly
        res["autoscale_transitions"] = len(requested)
        res["autoscale_no_flap"] = bool(
            len(requested) == 2
            and "FLAP-LOCKED" not in err
            and "membership change complete: cluster is n=4" in err
            and "membership change complete: cluster is n=2" in err
            and "restarting the cluster" not in err
        )
        res["autoscale_exact"] = _total() == fed
        return res
    finally:
        if proc is not None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.communicate()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_replicas() -> dict:
    """Read-replica serving fleet: bootstrap cost, query scaling, feed tax.

    All in-process (followers + HTTP servers + the client router), CPU-only —
    honest on any host. Reports:

    - bootstrap wall time + rows/s for a bounded-fragment cold start;
    - the BITWISE honesty key: the replica's results at the same commit id
      must equal the primary's exactly (keys AND float scores) — a replica
      that drifts is worse than no replica;
    - router queries/s at 1 vs 2 replicas (the independent-scaling claim);
    - kill-invisibility: one replica server closed mid-load, zero client
      errors (every query answered by the survivor or the primary);
    - the feed tax: primary ingest commits/s with frame recording on vs off.
    """
    import shutil
    import tempfile
    import threading

    import numpy as np

    from pathway_tpu.ops.knn import BruteForceKnnIndex
    from pathway_tpu.parallel.replica import (
        ReplicaFollower,
        ReplicaRouter,
        ReplicaServer,
        default_index_factory,
    )
    from pathway_tpu.persistence.replica_feed import ReplicaFeed

    dim = 64 if DEVICE_SCALE_DOWN else 128
    n_rows = 4_000 if DEVICE_SCALE_DOWN else 40_000
    n_queries = 64
    load_s = 1.5 if DEVICE_SCALE_DOWN else 3.0
    rng = np.random.default_rng(7)
    rows = rng.normal(size=(n_rows, dim)).astype(np.float32)
    queries = rng.normal(size=(n_queries, dim)).astype(np.float32)
    keys = [f"d{i}" for i in range(n_rows)]

    primary = BruteForceKnnIndex(dim)
    primary.add_many(keys, rows)
    primary.search_many(list(queries[:1]), [1])  # warm the kernel

    tmp = tempfile.mkdtemp(prefix="pw-bench-replicas-")
    res: dict = {}
    servers = []
    try:
        feed = ReplicaFeed(os.path.join(tmp, "feed"))
        t0 = time.perf_counter()
        feed.export_bootstrap(1, primary, rows_per_fragment=4096)
        res["replicas_export_s"] = round(time.perf_counter() - t0, 3)

        followers = []
        t0 = time.perf_counter()
        for rid in range(2):
            f = ReplicaFollower(
                feed, default_index_factory, replica_id=rid, poll_s=0.02
            )
            f.bootstrap()
            followers.append(f)
        boot_s = time.perf_counter() - t0
        res["replicas_bootstrap_s"] = round(boot_s / 2, 3)
        res["replicas_bootstrap_rows_per_s"] = round(2 * n_rows / boot_s, 1)

        # tail catch-up: 20 frames of 32 rows each
        extra = rng.normal(size=(20 * 32, dim)).astype(np.float32)
        for c in range(20):
            feed.record_commit(
                2 + c,
                [f"t{c}_{j}" for j in range(32)],
                extra[c * 32 : (c + 1) * 32],
            )
        primary.add_many(
            [f"t{c}_{j}" for c in range(20) for j in range(32)], extra
        )
        t0 = time.perf_counter()
        for f in followers:
            f.poll_frames()
        res["replicas_catchup_frames_per_s"] = round(
            2 * 20 / (time.perf_counter() - t0), 1
        )

        # -- BITWISE honesty key: replica == primary at the same commit -----
        k = 10
        want = primary.search_many(list(queries), [k] * n_queries)
        bitwise = True
        for f in followers:
            commit, got = f.search_many(list(queries), [k] * n_queries)
            bitwise = bitwise and commit == 21 and got == want
        res["replicas_bitwise_equal"] = bool(bitwise)

        servers = [ReplicaServer(f) for f in followers]
        endpoints = [f"http://127.0.0.1:{s.port}" for s in servers]

        def primary_serve(vectors, kk, filters):
            return 21, primary.search_many(
                list(vectors), [kk] * len(vectors), filters
            )

        payload = [[float(x) for x in queries[0]]]

        def hammer(router, duration_s, errors):
            done = time.perf_counter() + duration_s
            count = 0
            while time.perf_counter() < done:
                try:
                    router.retrieve(payload, k)
                    count += 1
                except Exception:
                    errors.append(1)
            return count

        def measure_qps(eps) -> float:
            router = ReplicaRouter(eps, primary=primary_serve, timeout_s=10.0)
            counts = []
            errors: list = []
            threads = [
                threading.Thread(
                    target=lambda: counts.append(
                        hammer(router, load_s, errors)
                    )
                )
                for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            return sum(counts) / load_s

        qps_1 = measure_qps(endpoints[:1])
        qps_2 = measure_qps(endpoints)
        res["replicas_qps_n1"] = round(qps_1, 1)
        res["replicas_qps_n2"] = round(qps_2, 1)
        res["replicas_qps_scaling_x"] = round(qps_2 / max(qps_1, 1e-9), 2)

        # -- kill-invisibility under load -----------------------------------
        router = ReplicaRouter(
            endpoints, primary=primary_serve, timeout_s=10.0
        )
        errors: list = []
        counts: list = []
        threads = [
            threading.Thread(
                target=lambda: counts.append(hammer(router, load_s, errors))
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(load_s / 3)
        servers[0].close()  # half the fleet vanishes mid-load
        for t in threads:
            t.join()
        res["replicas_kill_queries"] = int(sum(counts))
        res["replicas_kill_client_errors"] = len(errors)  # honesty: must be 0
        res["replicas_kill_failovers"] = int(router.stats["failovers"])

        # -- the feed tax on primary ingest ---------------------------------
        batch = rng.normal(size=(256, dim)).astype(np.float32)
        bkeys = [f"f{j}" for j in range(256)]

        def ingest(commits: int, with_feed: bool) -> float:
            t0 = time.perf_counter()
            for c in range(commits):
                primary.add_many(bkeys, batch)  # upserts: steady-state size
                if with_feed:
                    feed.record_commit(100 + c, bkeys, batch)
            return commits / (time.perf_counter() - t0)

        commits = 20 if DEVICE_SCALE_DOWN else 60
        ingest(3, False)  # warm
        off = ingest(commits, False)
        on = ingest(commits, True)
        res["replicas_ingest_commits_per_s_feed_off"] = round(off, 1)
        res["replicas_ingest_commits_per_s_feed_on"] = round(on, 1)
        res["replicas_feed_tax_frac"] = round(max(0.0, 1.0 - on / off), 3)
        return res
    finally:
        for s in servers:
            s.close()
        shutil.rmtree(tmp, ignore_errors=True)


# -- section registry ---------------------------------------------------------
#
# One registration per section derives the runner table, the device-bound set,
# AND both deadline tables — a section can no longer be added without
# deadlines (a missing entry used to KeyError the orchestrator at run time).

SUB_BENCHES: dict = {}
# sections whose numbers require the device; everything else is a CPU-vs-CPU
# comparison that stays honest (and full-scale) on any host. embedpipe's
# RATIOS (overlap/coalesce/cache speedups) are same-host comparisons that stay
# honest anywhere, but its absolute docs/s are encoder-bound — it scales down
# with the embedder section on fallback.
DEVICE_BOUND: set = set()
# per-sub-bench wall deadlines (seconds): generous on device, tight at toy scale
_DEADLINES_FULL: dict = {}
_DEADLINES_SMALL: dict = {}


def _register_section(
    name: str, fn, *, full: int = 600, small: int = 300, device_bound: bool = False
) -> None:
    SUB_BENCHES[name] = fn
    _DEADLINES_FULL[name] = full
    _DEADLINES_SMALL[name] = small
    if device_bound:
        DEVICE_BOUND.add(name)


_register_section("knn", lambda: bench_knn(), full=600, small=300, device_bound=True)
_register_section("ivfscale", lambda: bench_ivf_scale(), full=900, small=900)
_register_section("quant", lambda: bench_quant(), full=600, small=300)
_register_section("embedder", lambda: bench_embedder(), full=420, small=240, device_bound=True)
_register_section("embedpipe", lambda: bench_embedpipe(), full=600, small=420, device_bound=True)
_register_section("encsvc", lambda: bench_encsvc(), full=600, small=420, device_bound=True)
_register_section("window", lambda: bench_streaming_window(), full=300, small=300)
_register_section("engine", lambda: bench_engine(), full=600, small=600)
_register_section("fusion", lambda: bench_fusion(), full=600, small=420)
_register_section("telemetry", lambda: bench_telemetry(), full=420, small=420)
_register_section("vectorstore", lambda: bench_vector_store(), full=600, small=300, device_bound=True)
_register_section("vsfloor", lambda: bench_vs_floor(), full=300, small=300)
_register_section("sharded", lambda: bench_sharded(), full=660, small=660)
_register_section("scale", lambda: bench_scale(), full=1500, small=420, device_bound=True)
_register_section("rejoin", lambda: bench_rejoin(), full=420, small=300)
_register_section("elastic", lambda: bench_elastic(), full=480, small=360)
_register_section("autoscale", lambda: bench_autoscale(), full=360, small=300)
_register_section("replicas", lambda: bench_replicas(), full=360, small=240)


def _terminate_gently(proc: subprocess.Popen, grace: float = 15.0) -> None:
    """SIGTERM first, SIGKILL only as a last resort: hard-killing a process that
    holds the single-tenant device claim is exactly what wedges the tunnel."""
    proc.terminate()
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def _run_with_deadline(cmd: list, env: dict, deadline: float) -> tuple[int, str]:
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    try:
        out, _ = proc.communicate(timeout=deadline)
        return proc.returncode, out or ""
    except subprocess.TimeoutExpired:
        _terminate_gently(proc)
        return -1, ""


def _probe_backend() -> tuple[str | None, str]:
    """Decide the backend WITHOUT importing jax in this process.

    A wedged device tunnel hangs ``import jax`` whenever PALLAS_AXON_POOL_IPS
    is set — including under JAX_PLATFORMS=cpu — so the probe runs in a
    subprocess with a timeout on EVERY path, and on failure the tunnel env is
    stripped so children import instantly. Returns (fallback_marker, device)."""
    pool = os.environ.get("PALLAS_AXON_POOL_IPS")
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms == "cpu":
        # CPU was explicitly requested: no tunnel to probe — but the tunnel env
        # must still be stripped, because ``import jax`` hangs while it is set
        # (the axon plugin initializes even under JAX_PLATFORMS=cpu). Outside
        # smoke mode this still forces reduced scale + the honesty marker for
        # the device-bound sections (full-scale CPU "results" would be neither
        # finishable nor comparable).
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        if SMOKE:
            return None, "cpu (requested)"
        return (
            "cpu requested via JAX_PLATFORMS; device-bound sections at reduced "
            "scale — NOT comparable",
            "cpu (requested)",
        )
    # no tunneled plugin: nothing can wedge, but the probe must still run to
    # learn whether an accelerator exists at all — a plain CPU host running the
    # device-bound sections at full scale with no honesty marker would break
    # this file's contract (the probe costs a few seconds there; the driver's
    # env always has the tunnel and takes the long-timeout path anyway)
    timeout = 120 if pool else 60
    rc, out = _run_with_deadline(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print('PROBE_OK', d[0])"],
        dict(os.environ), timeout,
    )
    if rc == 0 and "PROBE_OK" in out:
        device = out.split("PROBE_OK", 1)[1].strip().splitlines()[0]
        if "cpu" in device.lower() and not SMOKE:
            return (
                "no accelerator visible; CPU numbers for device-bound sections NOT comparable",
                device,
            )
        return None, device
    # strip the tunnel env so every child (and any later in-process import)
    # can initialize a CPU backend without touching the wedged plugin
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return (
        "tpu unreachable (backend init hung/failed); CPU fallback at reduced scale — "
        "device-bound numbers NOT comparable",
        "cpu (fallback)",
    )


def _final_line(results: dict, device: str) -> str:
    return json.dumps(
        {
            "metric": "knn_query_qps_1Mx128",
            "value": results.get("knn_qps", 0.0),
            "unit": "queries/s",
            "vs_baseline": results.get("knn_vs_cpu", 0.0),
            "baseline": "numpy BLAS matmul+argpartition (reference rust-kernel proxy)",
            "device": device,
            **{k: v for k, v in results.items() if k not in ("knn_qps", "knn_vs_cpu")},
        }
    )


def _child_main(name: str) -> None:
    try:
        out = SUB_BENCHES[name]()
    except Exception as exc:
        out = {f"{name}_error": f"{type(exc).__name__}: {exc}"[:200]}
    print(json.dumps(out), flush=True)


def _reprobe_device(env: dict) -> bool:
    """Mid-round device health check (subprocess + timeout, same contract as
    the startup probe): True only when an accelerator still answers. A TPU
    tunnel that wedges BETWEEN sections otherwise produces CPU numbers
    silently attributed to the device — r04/r05 lost two rounds of device
    truth to exactly that."""
    timeout = 90 if env.get("PALLAS_AXON_POOL_IPS") else 45
    rc, out = _run_with_deadline(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print('PROBE_OK', d[0])"],
        dict(env), timeout,
    )
    if rc != 0 or "PROBE_OK" not in out:
        return False
    device = out.split("PROBE_OK", 1)[1].strip().splitlines()[0]
    return "cpu" not in device.lower()


def main() -> None:
    fallback, device = _probe_backend()
    results: dict = {}
    if fallback:
        results["device_fallback"] = fallback
        # the round-level honesty marker the driver keys on: these numbers
        # came from a CPU, never quote them as device truth
        results["degraded"] = "cpu-fallback"
    deadlines = _DEADLINES_SMALL if (SMOKE or fallback) else _DEADLINES_FULL
    env = dict(os.environ)
    if fallback:
        env["PW_BENCH_DEVICE_FALLBACK"] = "1"
        # fallback children: the full jit pre-warm bucket matrix is a device
        # startup cost — cap it so CPU smoke sections don't burn their
        # deadline compiling buckets they never dispatch
        env.setdefault("PATHWAY_ENCSVC_PREWARM_MAX_BATCH", "16")
    # mid-round probes only make sense while we believe a device is answering
    on_device = fallback is None and "cpu" not in device.lower()
    me = os.path.abspath(__file__)
    for name in SUB_BENCHES:
        if name in DEVICE_BOUND and on_device and not _reprobe_device(env):
            # the backend died mid-round: degrade LOUDLY, not silently —
            # remaining device-bound sections run at reduced scale on CPU and
            # the whole round is marked, instead of reporting CPU numbers as
            # device truth
            on_device = False
            fallback = (
                f"tpu became unreachable mid-round (probe failed before "
                f"section {name!r}); remaining device-bound numbers are CPU "
                "fallback at reduced scale — NOT comparable"
            )
            results["device_fallback"] = fallback
            results["degraded"] = "cpu-fallback"
            deadlines = _DEADLINES_SMALL
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
            env["PW_BENCH_DEVICE_FALLBACK"] = "1"
            env.setdefault("PATHWAY_ENCSVC_PREWARM_MAX_BATCH", "16")
            print(_final_line(results, device), flush=True)
        t0 = time.perf_counter()
        rc, out = _run_with_deadline(
            [sys.executable, me, "--sub", name], env, deadlines[name]
        )
        if rc == 0 and out.strip():
            try:
                results.update(json.loads(out.strip().splitlines()[-1]))
            except Exception as exc:
                results[f"{name}_error"] = f"unparseable output: {exc}"[:200]
        elif rc == -1:
            results[f"{name}_error"] = (
                f"deadline {deadlines[name]}s exceeded after {time.perf_counter() - t0:.0f}s"
            )
        else:
            results[f"{name}_error"] = f"exit code {rc}"
        # cumulative flushed line after EVERY section: a driver timeout keeps
        # everything completed so far, and the LAST line is always the most
        # complete aggregate (the one the driver parses)
        print(_final_line(results, device), flush=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--sub":
        _child_main(sys.argv[2])
    elif len(sys.argv) == 2 and sys.argv[1] in SUB_BENCHES:
        # `bench.py NAME` is an alias for `--sub NAME` — it used to silently
        # ignore the name and run EVERY section
        _child_main(sys.argv[1])
    elif len(sys.argv) >= 2:
        print(
            f"bench.py: unknown section {sys.argv[1]!r}\n"
            f"usage: bench.py [NAME | --sub NAME]   (no args = all sections)\n"
            f"sections: {', '.join(sorted(SUB_BENCHES))}",
            file=sys.stderr,
        )
        sys.exit(2)
    else:
        main()
