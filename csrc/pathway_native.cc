// Native runtime kernels for pathway_tpu.
//
// TPU-native counterpart of the reference engine's Rust host-side hot paths:
//   - 128-bit row-key fingerprinting (reference src/engine/value.rs:41 `Key`,
//     xxh3-based) over typed column batches,
//   - DSV field splitting + typed coercion (reference src/connectors/data_format.rs
//     Dsv parser).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image). The
// serialization format byte-matches pathway_tpu/internals/keys.py::_serialize_value so
// native and Python key derivation are interchangeable.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define XXH_INLINE_ALL
#include "xxhash.h"

#include <cassert>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Mirror of keys.py serialization tags.
constexpr uint8_t TAG_NONE = 0x00;
constexpr uint8_t TAG_BOOL = 0x02;
constexpr uint8_t TAG_INT = 0x03;
constexpr uint8_t TAG_FLOAT = 0x04;
constexpr uint8_t TAG_STR = 0x05;

inline void put_u64_le(std::string& buf, uint64_t v) {
  for (int i = 0; i < 8; ++i) buf.push_back(static_cast<char>((v >> (8 * i)) & 0xff));
}

// 16-byte little-endian signed integer (Python int.to_bytes(16, "little", signed=True))
inline void put_i128_le(std::string& buf, int64_t v) {
  uint64_t lo = static_cast<uint64_t>(v);
  uint64_t hi = v < 0 ? ~0ULL : 0ULL;
  put_u64_le(buf, lo);
  put_u64_le(buf, hi);
}

inline uint64_t bswap64(uint64_t v) { return __builtin_bswap64(v); }

// Python reads the canonical digest little-endian: digest[:8] is the big-endian
// encoding of XXH3's high64, so hi = bswap(high64); likewise lo = bswap(low64).
inline void write_hash(const std::string& buf, uint64_t* hi, uint64_t* lo) {
  XXH128_hash_t h = XXH3_128bits(buf.data(), buf.size());
  *hi = bswap64(h.high64);
  *lo = bswap64(h.low64);
}

}  // namespace

extern "C" {

// Column value kinds for pwtpu_hash_typed.
//   1 = int64    (data: int64_t*)
//   2 = float64  (data: double*)
//   3 = bool     (data: uint8_t*)
//   4 = utf8     (data: char buffer, offsets: uint64_t[n+1])
//   5 = pyobject (data: PyObject** — a numpy object column's backing array;
//                 caller must hold the GIL, i.e. load via ctypes.PyDLL)
//   6 = key128   (data: uint64_t pairs [hi,lo] little-endian, i.e. the raw bytes of
//                 a KEY_DTYPE structured column — serialized as a Pointer value)
// A column's mask (optional, uint8_t*) marks rows as present (1) or None (0).
struct PwCol {
  int32_t kind;
  const void* data;
  const uint64_t* offsets;
  const uint8_t* mask;
};

namespace {

// Serialize one Python value exactly like keys.py::_serialize_value for the scalar
// types the engine's hot columns carry. np_bool / np_integer are numpy's np.bool_ and
// np.integer for scalar detection. Returns false for unsupported values (tuples,
// ndarrays, Json, huge ints …) — caller falls back to the Python serializer.
bool serialize_pyvalue(PyObject* v, PyObject* np_bool, PyObject* np_integer,
                       std::string& buf) {
  if (v == Py_None) {
    buf.push_back(static_cast<char>(TAG_NONE));
    return true;
  }
  if (PyBool_Check(v) || PyObject_TypeCheck(v, reinterpret_cast<PyTypeObject*>(np_bool))) {
    buf.push_back(static_cast<char>(TAG_BOOL));
    buf.push_back(PyObject_IsTrue(v) ? '\x01' : '\x00');
    return true;
  }
  if (PyFloat_Check(v)) {  // also covers np.float64 (a float subclass)
    buf.push_back(static_cast<char>(TAG_FLOAT));
    double d = PyFloat_AS_DOUBLE(v);
    char raw[8];
    std::memcpy(raw, &d, 8);
    buf.append(raw, 8);
    return true;
  }
  if (PyLong_Check(v) ||
      PyObject_TypeCheck(v, reinterpret_cast<PyTypeObject*>(np_integer))) {
    int overflow = 0;
    long long val = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow != 0) return false;  // >64-bit int: python path handles 128-bit
    if (val == -1 && PyErr_Occurred()) {
      // np.integer scalars are not PyLong; go through __index__
      PyErr_Clear();
      PyObject* as_int = PyNumber_Index(v);
      if (as_int == nullptr) {
        PyErr_Clear();
        return false;
      }
      val = PyLong_AsLongLongAndOverflow(as_int, &overflow);
      Py_DECREF(as_int);
      if (overflow != 0 || (val == -1 && PyErr_Occurred())) {
        PyErr_Clear();
        return false;
      }
    }
    buf.push_back(static_cast<char>(TAG_INT));
    put_i128_le(buf, static_cast<int64_t>(val));
    return true;
  }
  if (PyUnicode_Check(v)) {
    Py_ssize_t size = 0;
    const char* utf8 = PyUnicode_AsUTF8AndSize(v, &size);
    if (utf8 == nullptr) {
      PyErr_Clear();
      return false;
    }
    buf.push_back(static_cast<char>(TAG_STR));
    put_u64_le(buf, static_cast<uint64_t>(size));
    buf.append(utf8, static_cast<size_t>(size));
    return true;
  }
  return false;
}

// -- single-int identity-mix keys -------------------------------------------
// A row whose key derives from EXACTLY ONE int value (int64 column cell, or a
// python/numpy integer in an object column) uses a splitmix-style 128-bit mix
// of the value instead of salted xxh3 over its serialization: the single-int
// join/groupby key is the hottest derivation and the mix is ~10x cheaper while
// keeping full 64->128-bit avalanche. internals/keys.py implements the SAME
// function for the scalar (pointer_from) and vectorized numpy paths — all
// derivation sites must produce identical bits for equal values.
inline uint64_t pw_intkey_mix64(uint64_t x) {
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ULL;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBULL;
  x ^= x >> 31;
  return x;
}

constexpr uint64_t PW_INTKEY_LO = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t PW_INTKEY_HI = 0xD6E8FEB86659FD93ULL;

// Extract an int64-able integer (not a bool) from a python object; mirrors the
// serializer's integer recognition so the fast path and the serialized path
// agree on what counts as an int.
inline bool pw_try_int64(PyObject* v, PyObject* np_bool, PyObject* np_integer,
                         uint64_t* out) {
  if (PyBool_Check(v) ||
      PyObject_TypeCheck(v, reinterpret_cast<PyTypeObject*>(np_bool))) {
    return false;
  }
  if (!(PyLong_Check(v) ||
        PyObject_TypeCheck(v, reinterpret_cast<PyTypeObject*>(np_integer)))) {
    return false;
  }
  int overflow = 0;
  long long val = PyLong_AsLongLongAndOverflow(v, &overflow);
  if (overflow != 0) return false;  // >64-bit int: serialized path
  if (val == -1 && PyErr_Occurred()) {
    PyErr_Clear();
    PyObject* as_int = PyNumber_Index(v);
    if (as_int == nullptr) {
      PyErr_Clear();
      return false;
    }
    val = PyLong_AsLongLongAndOverflow(as_int, &overflow);
    Py_DECREF(as_int);
    if (overflow != 0 || (val == -1 && PyErr_Occurred())) {
      PyErr_Clear();
      return false;
    }
  }
  *out = static_cast<uint64_t>(val);
  return true;
}

}  // namespace

// Fingerprint n rows over ncols typed columns. salt is prefixed to every row.
// Returns -1 on success, else the index of the first row holding a value the native
// serializer doesn't support (caller falls back to Python for the whole batch).
int64_t pwtpu_hash_typed(const PwCol* cols, int32_t ncols, uint64_t n,
                         const uint8_t* salt, uint64_t salt_len, PyObject* np_bool,
                         PyObject* np_integer, uint64_t* out_hi, uint64_t* out_lo) {
  std::string buf;
  for (uint64_t i = 0; i < n; ++i) {
    if (ncols == 1) {
      // single-int fast path (see pw_intkey_mix64 above); masked/None rows and
      // non-int values fall through to the serialized path
      const PwCol& c0 = cols[0];
      bool present = (c0.mask == nullptr || c0.mask[i] != 0);
      if (present && c0.kind == 1) {
        uint64_t v = static_cast<uint64_t>(static_cast<const int64_t*>(c0.data)[i]);
        out_lo[i] = pw_intkey_mix64(v + PW_INTKEY_LO);
        out_hi[i] = pw_intkey_mix64(v ^ PW_INTKEY_HI);
        continue;
      }
      if (present && c0.kind == 5) {
        PyObject* pv = static_cast<PyObject* const*>(c0.data)[i];
        uint64_t v = 0;
        if (pw_try_int64(pv, np_bool, np_integer, &v)) {
          out_lo[i] = pw_intkey_mix64(v + PW_INTKEY_LO);
          out_hi[i] = pw_intkey_mix64(v ^ PW_INTKEY_HI);
          continue;
        }
      }
    }
    buf.assign(reinterpret_cast<const char*>(salt), salt_len);
    for (int32_t c = 0; c < ncols; ++c) {
      const PwCol& col = cols[c];
      if (col.mask != nullptr && col.mask[i] == 0) {
        buf.push_back(static_cast<char>(TAG_NONE));
        continue;
      }
      switch (col.kind) {
        case 1:
          buf.push_back(static_cast<char>(TAG_INT));
          put_i128_le(buf, static_cast<const int64_t*>(col.data)[i]);
          break;
        case 2: {
          buf.push_back(static_cast<char>(TAG_FLOAT));
          double v = static_cast<const double*>(col.data)[i];
          char raw[8];
          std::memcpy(raw, &v, 8);
          buf.append(raw, 8);
          break;
        }
        case 3:
          buf.push_back(static_cast<char>(TAG_BOOL));
          buf.push_back(static_cast<const uint8_t*>(col.data)[i] ? '\x01' : '\x00');
          break;
        case 4: {
          buf.push_back(static_cast<char>(TAG_STR));
          uint64_t start = col.offsets[i];
          uint64_t end = col.offsets[i + 1];
          put_u64_le(buf, end - start);
          buf.append(static_cast<const char*>(col.data) + start, end - start);
          break;
        }
        case 5: {
          PyObject* v = static_cast<PyObject* const*>(col.data)[i];
          if (!serialize_pyvalue(v, np_bool, np_integer, buf)) {
            return static_cast<int64_t>(i);
          }
          break;
        }
        case 6:
          // Pointer tag + raw hi/lo (already little-endian in a KEY_DTYPE column)
          buf.push_back('\x01');
          buf.append(static_cast<const char*>(col.data) + 16 * i, 16);
          break;
        default:
          return static_cast<int64_t>(i);
      }
    }
    write_hash(buf, &out_hi[i], &out_lo[i]);
  }
  return -1;
}

// Fingerprint pre-serialized rows (payloads concatenated in buf, offsets[n+1]).
void pwtpu_hash_serialized(const uint8_t* buf, const uint64_t* offsets, uint64_t n,
                           uint64_t* out_hi, uint64_t* out_lo) {
  for (uint64_t i = 0; i < n; ++i) {
    XXH128_hash_t h =
        XXH3_128bits(buf + offsets[i], offsets[i + 1] - offsets[i]);
    out_hi[i] = bswap64(h.high64);
    out_lo[i] = bswap64(h.low64);
  }
}

// Autogenerated sequential row ids (reference: dense ints hashed for uniform
// sharding; mirrors keys.py sequential_keys).
void pwtpu_sequential_keys(const uint8_t* salt, uint64_t salt_len, int64_t start,
                           uint64_t count, uint64_t* out_hi, uint64_t* out_lo) {
  std::string buf;
  for (uint64_t i = 0; i < count; ++i) {
    buf.assign(reinterpret_cast<const char*>(salt), salt_len);
    buf.append("seq", 3);
    put_i128_le(buf, start + static_cast<int64_t>(i));
    write_hash(buf, &out_hi[i], &out_lo[i]);
  }
}

// ---------------------------------------------------------------------------
// DSV splitting (reference data_format.rs Dsv parser): split `data` into rows
// by '\n' and fields by `delimiter`, honoring double-quote quoting with ""
// escapes — csv-module semantics: a quote is only special at field start;
// elsewhere it is literal. Emits a flat field buffer + per-field offsets +
// per-row field counts (+ optional per-row had-quotes flags, to distinguish a
// quoted empty string from a blank line). Returns the number of rows;
// *needed_* outputs let the caller size buffers (call once with null outputs
// to measure, then with buffers).
uint64_t pwtpu_split_dsv(const char* data, uint64_t len, char delimiter,
                         char* field_buf, uint64_t* field_offsets,
                         uint64_t* row_field_counts, uint8_t* row_had_quotes,
                         uint64_t* needed_bytes, uint64_t* needed_fields) {
  uint64_t rows = 0, fields = 0, bytes = 0;
  bool measuring = field_buf == nullptr;
  uint64_t field_start_bytes = 0;
  bool in_quotes = false;
  bool row_open = false;
  bool field_started = false;
  bool had_quotes = false;
  uint64_t row_fields = 0;

  auto end_field = [&]() {
    if (!measuring) field_offsets[fields] = field_start_bytes;
    ++fields;
    ++row_fields;
    field_start_bytes = bytes;
    field_started = false;
  };
  auto end_row = [&]() {
    end_field();
    if (!measuring) {
      row_field_counts[rows] = row_fields;
      if (row_had_quotes != nullptr) row_had_quotes[rows] = had_quotes ? 1 : 0;
    }
    ++rows;
    row_fields = 0;
    row_open = false;
    had_quotes = false;
  };

  for (uint64_t i = 0; i < len; ++i) {
    char ch = data[i];
    row_open = true;
    if (in_quotes) {
      if (ch == '"') {
        if (i + 1 < len && data[i + 1] == '"') {
          if (!measuring) field_buf[bytes] = '"';
          ++bytes;
          ++i;
        } else {
          in_quotes = false;
        }
      } else {
        if (!measuring) field_buf[bytes] = ch;
        ++bytes;
      }
      continue;
    }
    if (ch == '"' && !field_started) {
      // csv-module rule: quoting starts only at the beginning of a field
      in_quotes = true;
      field_started = true;
      had_quotes = true;
    } else if (ch == delimiter) {
      end_field();
    } else if (ch == '\r') {
      if (i + 1 < len && data[i + 1] == '\n') {
        // CRLF: drop the \r, the \n closes the row next iteration
      } else {
        end_row();  // bare CR line ending (csv-module behavior)
      }
    } else if (ch == '\n') {
      end_row();
    } else {
      if (!measuring) field_buf[bytes] = ch;
      ++bytes;
      field_started = true;
    }
  }
  if (row_open) end_row();
  if (!measuring && fields > 0) field_offsets[fields] = bytes;
  if (needed_bytes != nullptr) *needed_bytes = bytes;
  if (needed_fields != nullptr) *needed_fields = fields;
  return rows;
}

namespace {

// Python-int coercion: strtoll fast path, CPython PyLong_FromString fallback so
// big ints / underscore literals behave exactly like the Python int() in _coerce.
PyObject* coerce_int(const char* s, size_t slen, PyObject* error_obj,
                     std::string& scratch) {
  while (slen > 0 && (s[0] == ' ' || s[0] == '\t')) { ++s; --slen; }
  while (slen > 0 && (s[slen - 1] == ' ' || s[slen - 1] == '\t')) --slen;
  scratch.assign(s, slen);
  char* end = nullptr;
  errno = 0;
  long long v = strtoll(scratch.c_str(), &end, 10);
  if (errno == 0 && slen != 0 && end == scratch.c_str() + slen) {
    return PyLong_FromLongLong(v);
  }
  PyObject* big = PyLong_FromString(scratch.c_str(), nullptr, 10);
  if (big != nullptr) return big;
  PyErr_Clear();
  Py_INCREF(error_obj);
  return error_obj;
}

// Python-float coercion: strtod fast path for plain decimal forms, otherwise
// PyFloat_FromString (handles 1e-320 subnormals, '_' grouping, inf/nan words,
// and rejects C hex floats — exactly float()'s rules).
PyObject* coerce_float(const char* s, size_t slen, PyObject* error_obj,
                       std::string& scratch) {
  while (slen > 0 && (s[0] == ' ' || s[0] == '\t')) { ++s; --slen; }
  while (slen > 0 && (s[slen - 1] == ' ' || s[slen - 1] == '\t')) --slen;
  bool plain = slen > 0;
  for (size_t i = 0; i < slen; ++i) {
    char c = s[i];
    if (!((c >= '0' && c <= '9') || c == '.' || c == '+' || c == '-' || c == 'e' ||
          c == 'E')) {
      plain = false;
      break;
    }
  }
  scratch.assign(s, slen);
  if (plain) {
    char* end = nullptr;
    double v = strtod(scratch.c_str(), &end);  // ERANGE over/underflow matches float()
    if (end == scratch.c_str() + slen) return PyFloat_FromDouble(v);
  }
  PyObject* str = PyUnicode_DecodeUTF8(s, static_cast<Py_ssize_t>(slen), "replace");
  if (str == nullptr) {
    PyErr_Clear();
    Py_INCREF(error_obj);
    return error_obj;
  }
  PyObject* val = PyFloat_FromString(str);
  Py_DECREF(str);
  if (val != nullptr) return val;
  PyErr_Clear();
  Py_INCREF(error_obj);
  return error_obj;
}

}  // namespace

// ---------------------------------------------------------------------------
// Fused DSV parse: split + typed coercion + row-dict construction, entirely
// native (the counterpart of data_format.rs DsvParser::parse). Called with the
// GIL held (ctypes.PyDLL).
//
//   data/len/delim : raw file bytes (header row included; quoted headers fine —
//                    name→column resolution happens here, against the split header)
//   names          : Python tuple of wanted column-name strings
//   tags           : per wanted column: 0=str 1=int 2=float 3=bool (others: raw str)
//   ncols          : number of wanted columns
//   error_obj      : sentinel stored for malformed typed fields (Value::Error)
//
// Wanted columns absent from the header are omitted from the row dicts (same as
// the DictReader fallback). Returns a new reference to a list of per-row dicts,
// or NULL on internal error.
PyObject* pwtpu_parse_dsv_rows(const char* data, uint64_t len, char delim,
                               PyObject* names, const int32_t* tags, int32_t ncols,
                               PyObject* error_obj) {
  uint64_t needed_bytes = 0, needed_fields = 0;
  uint64_t nrows = pwtpu_split_dsv(data, len, delim, nullptr, nullptr, nullptr,
                                   nullptr, &needed_bytes, &needed_fields);
  PyObject* out = PyList_New(0);
  if (out == nullptr) return nullptr;
  if (nrows == 0) return out;
  std::vector<char> field_buf(needed_bytes > 0 ? needed_bytes : 1);
  std::vector<uint64_t> offsets(needed_fields + 1);
  std::vector<uint64_t> counts(nrows);
  std::vector<uint8_t> quoted(nrows);
  pwtpu_split_dsv(data, len, delim, field_buf.data(), offsets.data(),
                  counts.data(), quoted.data(), nullptr, nullptr);

  // resolve wanted names against the (properly split) header row
  std::vector<int64_t> src_idx(ncols, -1);
  uint64_t header_fields = counts[0];
  for (int32_t c = 0; c < ncols; ++c) {
    PyObject* name = PyTuple_GET_ITEM(names, c);
    Py_ssize_t name_len = 0;
    const char* name_utf8 = PyUnicode_AsUTF8AndSize(name, &name_len);
    if (name_utf8 == nullptr) {
      PyErr_Clear();
      continue;
    }
    for (uint64_t j = 0; j < header_fields; ++j) {
      uint64_t fl = offsets[j + 1] - offsets[j];
      if (fl == static_cast<uint64_t>(name_len) &&
          std::memcmp(field_buf.data() + offsets[j], name_utf8, fl) == 0) {
        src_idx[c] = static_cast<int64_t>(j);
        break;
      }
    }
  }

  uint64_t f = header_fields;
  std::string scratch;
  for (uint64_t r = 1; r < nrows; ++r) {
    uint64_t k = counts[r];
    if (k == 1 && offsets[f + 1] == offsets[f] && !quoted[r]) {
      f += k;
      continue;  // blank line (a quoted "" row is genuine data)
    }
    PyObject* row = PyDict_New();
    if (row == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    for (int32_t c = 0; c < ncols; ++c) {
      int64_t j = src_idx[c];
      if (j < 0) continue;  // column absent from header: omit, like DictReader
      PyObject* name = PyTuple_GET_ITEM(names, c);
      PyObject* value = nullptr;
      if (static_cast<uint64_t>(j) >= k) {
        Py_INCREF(Py_None);
        value = Py_None;
      } else {
        const char* s = field_buf.data() + offsets[f + j];
        size_t slen = offsets[f + j + 1] - offsets[f + j];
        switch (tags[c]) {
          case 1:
            value = coerce_int(s, slen, error_obj, scratch);
            break;
          case 2:
            value = coerce_float(s, slen, error_obj, scratch);
            break;
          case 3: {  // bool ("true"/"True"/"1" ... mirrors io/fs.py _coerce)
            scratch.assign(s, slen);
            if (scratch == "true" || scratch == "True" || scratch == "1") {
              Py_INCREF(Py_True);
              value = Py_True;
            } else if (scratch == "false" || scratch == "False" || scratch == "0") {
              Py_INCREF(Py_False);
              value = Py_False;
            } else {
              Py_INCREF(error_obj);
              value = error_obj;
            }
            break;
          }
          default:
            value = PyUnicode_DecodeUTF8(s, static_cast<Py_ssize_t>(slen), "replace");
        }
      }
      if (value == nullptr || PyDict_SetItem(row, name, value) < 0) {
        Py_XDECREF(value);
        Py_DECREF(row);
        Py_DECREF(out);
        return nullptr;
      }
      Py_DECREF(value);
    }
    if (PyList_Append(out, row) < 0) {
      Py_DECREF(row);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(row);
    f += k;
  }
  return out;
}

// ---------------------------------------------------------------------------
// Key combination: derive output keys from two (maskable) key columns by
// splitmix-style arithmetic mixing (see internals/keys.py::combine_keys — this
// is its exact native twin; both must produce identical bits).

void pwtpu_combine_keys(const uint64_t* lkeys, const uint64_t* rkeys,
                        const uint8_t* lmask, const uint8_t* rmask, int64_t n,
                        uint64_t salt, uint64_t* out_keys) {
  constexpr uint64_t C1 = 0x9E3779B97F4A7C15ULL;
  constexpr uint64_t C2 = 0xC2B2AE3D27D4EB4FULL;
  constexpr uint64_t C3 = 0x165667B19E3779F9ULL;
  constexpr uint64_t Z = 0x27D4EB2F165667C5ULL;
  for (int64_t i = 0; i < n; ++i) {
    bool lm = lmask == nullptr || lmask[i];
    bool rm = rmask == nullptr || rmask[i];
    uint64_t lh = lm ? lkeys[2 * i] : 0x6C6E756C6CULL;
    uint64_t ll = lm ? lkeys[2 * i + 1] : 0x1B873593ULL;
    uint64_t rh = rm ? rkeys[2 * i] : 0x726E756C6CULL;
    uint64_t rl = rm ? rkeys[2 * i + 1] : 0x85EBCA77ULL;
    uint64_t hi = (lh * C1) ^ (rh * C2) ^ ((rl >> 31) + salt * C3);
    uint64_t lo = (ll * C2) ^ (rl * C1) ^ ((lh << 17) | (lh >> 47));
    hi ^= hi >> 29;
    hi *= Z;
    hi ^= hi >> 32;
    lo ^= lo >> 29;
    lo *= C3;
    lo ^= lo >> 32;
    lo ^= hi * C1;
    lo ^= lo >> 31;
    out_keys[2 * i] = hi;
    out_keys[2 * i + 1] = lo;
  }
}

// ---------------------------------------------------------------------------
// KeyIndex: open-addressing hash table, 128-bit key -> dense int64 slot.
//
// The native replacement for the engine's Python dict key indexes (StateTable
// row index, groupby group index, join-side row index). Keys arrive as the raw
// bytes of a KEY_DTYPE structured column: interleaved little-endian [hi, lo]
// uint64 pairs. Keys are xxh3 fingerprints already, so `lo` is the hash.
// Slots are dense ints assigned on insert and recycled through a free stack,
// so the Python side can keep column arrays indexed by slot.

namespace {

struct KeyIndex {
  std::vector<uint64_t> khi, klo;
  std::vector<int8_t> state;  // 0 empty, 1 full, 2 tombstone
  std::vector<int64_t> slots;
  uint64_t mask = 0;
  int64_t live = 0;
  int64_t filled = 0;  // live + tombstones
  int64_t next_slot = 0;
  std::vector<int64_t> free_slots;

  explicit KeyIndex(uint64_t cap_hint) {
    uint64_t cap = 16;
    while (cap < cap_hint * 2) cap <<= 1;
    rebuild(cap);
  }

  void rebuild(uint64_t cap) {
    khi.assign(cap, 0);
    klo.assign(cap, 0);
    state.assign(cap, 0);
    slots.assign(cap, -1);
    mask = cap - 1;
    filled = live;  // tombstones vanish on rebuild
  }

  // Rebuild at `new_cap` (same size = tombstone purge) re-inserting live entries.
  void rehash_to(uint64_t new_cap) {
    std::vector<uint64_t> ohi, olo;
    std::vector<int8_t> ost;
    std::vector<int64_t> osl;
    ohi.swap(khi);
    olo.swap(klo);
    ost.swap(state);
    osl.swap(slots);
    rebuild(new_cap);
    for (uint64_t i = 0; i < ost.size(); ++i) {
      if (ost[i] != 1) continue;
      uint64_t pos = olo[i] & mask;
      while (state[pos] == 1) pos = (pos + 1) & mask;
      khi[pos] = ohi[i];
      klo[pos] = olo[i];
      state[pos] = 1;
      slots[pos] = osl[i];
    }
  }

  void rehash_if_needed() {
    uint64_t cap = mask + 1;
    if (static_cast<uint64_t>(filled) * 2 < cap) return;  // max load 0.5
    // tombstone-dominated tables rebuild at the SAME size (purge, not grow) so
    // insert/remove churn with constant live keys keeps memory bounded; only a
    // genuinely full table doubles
    uint64_t new_cap = cap;
    while (static_cast<uint64_t>(live) * 4 >= new_cap) new_cap <<= 1;
    rehash_to(new_cap);
  }

  // Guarantee capacity for `extra` further inserts without a mid-batch rehash,
  // so batch loops can prefetch probe positions safely. (If the early-return
  // fails, the growth loop always doubles at least once: need*2 >= cap implies
  // (live+extra)*4 >= cap whenever filled == live, and a tombstoned table is
  // purged by the same-size rebuild.)
  void reserve_for(uint64_t extra) {
    uint64_t cap = mask + 1;
    if ((static_cast<uint64_t>(filled) + extra) * 2 < cap) return;
    uint64_t new_cap = cap;
    while ((static_cast<uint64_t>(live) + extra) * 4 >= new_cap) new_cap <<= 1;
    rehash_to(new_cap);
  }

  // Returns the table position of `key` if present, else the first insertable
  // position (tombstone or empty).
  uint64_t find(uint64_t hi, uint64_t lo, bool* found) const {
    uint64_t pos = lo & mask;
    int64_t first_tomb = -1;
    for (;;) {
      int8_t st = state[pos];
      if (st == 0) {
        *found = false;
        return first_tomb >= 0 ? static_cast<uint64_t>(first_tomb) : pos;
      }
      if (st == 1 && klo[pos] == lo && khi[pos] == hi) {
        *found = true;
        return pos;
      }
      if (st == 2 && first_tomb < 0) first_tomb = static_cast<int64_t>(pos);
      pos = (pos + 1) & mask;
    }
  }

  int64_t upsert(uint64_t hi, uint64_t lo, uint8_t* is_new) {
    rehash_if_needed();
    bool found = false;
    uint64_t pos = find(hi, lo, &found);
    if (found) {
      *is_new = 0;
      return slots[pos];
    }
    int64_t slot;
    if (!free_slots.empty()) {
      slot = free_slots.back();
      free_slots.pop_back();
    } else {
      slot = next_slot++;
    }
    if (state[pos] == 0) ++filled;
    khi[pos] = hi;
    klo[pos] = lo;
    state[pos] = 1;
    slots[pos] = slot;
    ++live;
    *is_new = 1;
    return slot;
  }

  int64_t lookup(uint64_t hi, uint64_t lo) const {
    bool found = false;
    uint64_t pos = find(hi, lo, &found);
    return found ? slots[pos] : -1;
  }

  int64_t remove(uint64_t hi, uint64_t lo) {
    bool found = false;
    uint64_t pos = find(hi, lo, &found);
    if (!found) return -1;
    int64_t slot = slots[pos];
    state[pos] = 2;  // tombstone (filled count unchanged)
    slots[pos] = -1;
    --live;
    free_slots.push_back(slot);
    return slot;
  }
};

inline const uint64_t* key_hi_lo(const uint64_t* keys, uint64_t i) {
  return keys + 2 * i;
}

}  // namespace

void* pwtpu_idx_new(uint64_t cap_hint) { return new KeyIndex(cap_hint); }

void pwtpu_idx_free(void* h) { delete static_cast<KeyIndex*>(h); }

int64_t pwtpu_idx_len(void* h) { return static_cast<KeyIndex*>(h)->live; }

// One past the largest slot ever assigned: the Python side sizes its column
// arrays to this bound.
int64_t pwtpu_idx_slot_bound(void* h) {
  return static_cast<KeyIndex*>(h)->next_slot;
}

// keys: interleaved [hi, lo] pairs (raw KEY_DTYPE bytes). Duplicate keys within
// one batch resolve to the same slot (is_new only on the first occurrence).
void pwtpu_idx_upsert(void* h, const uint64_t* keys, int64_t n,
                      int64_t* out_slots, uint8_t* out_is_new) {
  KeyIndex* idx = static_cast<KeyIndex*>(h);
  idx->reserve_for(static_cast<uint64_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    if (i + 8 < n) __builtin_prefetch(&idx->state[keys[2 * (i + 8) + 1] & idx->mask]);
    const uint64_t* k = key_hi_lo(keys, i);
    uint8_t is_new = 0;
    out_slots[i] = idx->upsert(k[0], k[1], &is_new);
    if (out_is_new != nullptr) out_is_new[i] = is_new;
  }
}

void pwtpu_idx_lookup(void* h, const uint64_t* keys, int64_t n,
                      int64_t* out_slots) {
  const KeyIndex* idx = static_cast<const KeyIndex*>(h);
  for (int64_t i = 0; i < n; ++i) {
    if (i + 8 < n) __builtin_prefetch(&idx->state[keys[2 * (i + 8) + 1] & idx->mask]);
    const uint64_t* k = key_hi_lo(keys, i);
    out_slots[i] = idx->lookup(k[0], k[1]);
  }
}

// Removed keys free their slot for reuse; absent keys report -1.
void pwtpu_idx_remove(void* h, const uint64_t* keys, int64_t n,
                      int64_t* out_slots) {
  KeyIndex* idx = static_cast<KeyIndex*>(h);
  for (int64_t i = 0; i < n; ++i) {
    if (i + 8 < n) __builtin_prefetch(&idx->state[keys[2 * (i + 8) + 1] & idx->mask]);
    const uint64_t* k = key_hi_lo(keys, i);
    out_slots[i] = idx->remove(k[0], k[1]);
  }
}

// Fused per-row fingerprint + KeyIndex upsert (the groupby hot pair): hashing
// and slot assignment in one crossing, no intermediate interleaved key buffer —
// the hash lands in out_hi/out_lo and upserts straight from there. Returns -1
// on success, else the first unsupported row; on that failure the index is
// UNTOUCHED (hashing runs to completion before any upsert).
int64_t pwtpu_hash_upsert(const PwCol* cols, int32_t ncols, uint64_t n,
                          const uint8_t* salt, uint64_t salt_len, PyObject* np_bool,
                          PyObject* np_integer, void* idx_handle, uint64_t* out_hi,
                          uint64_t* out_lo, int64_t* out_slots, uint8_t* out_is_new) {
  int64_t status = pwtpu_hash_typed(cols, ncols, n, salt, salt_len, np_bool,
                                    np_integer, out_hi, out_lo);
  if (status != -1) return status;
  KeyIndex* idx = static_cast<KeyIndex*>(idx_handle);
  idx->reserve_for(n);
  for (uint64_t i = 0; i < n; ++i) {
    if (i + 8 < n) __builtin_prefetch(&idx->state[out_lo[i + 8] & idx->mask]);
    uint8_t is_new = 0;
    out_slots[i] = idx->upsert(out_hi[i], out_lo[i], &is_new);
    out_is_new[i] = is_new;
  }
  return -1;
}

// Checkpoint-restore path: insert keys with EXPLICIT slot assignments (slot ids
// index the caller's column arrays and must survive a pickle round-trip exactly),
// then rebuild the free list from the gaps below next_slot.
void pwtpu_idx_restore(void* h, const uint64_t* keys, const int64_t* in_slots,
                       int64_t n, int64_t next_slot) {
  KeyIndex* idx = static_cast<KeyIndex*>(h);
  std::vector<bool> used(static_cast<size_t>(next_slot), false);
  for (int64_t i = 0; i < n; ++i) {
    idx->rehash_if_needed();
    const uint64_t* k = key_hi_lo(keys, i);
    bool found = false;
    uint64_t pos = idx->find(k[0], k[1], &found);
    if (!found) {
      if (idx->state[pos] == 0) ++idx->filled;
      ++idx->live;
    }
    idx->khi[pos] = k[0];
    idx->klo[pos] = k[1];
    idx->state[pos] = 1;
    idx->slots[pos] = in_slots[i];
    if (in_slots[i] >= 0 && in_slots[i] < next_slot) used[in_slots[i]] = true;
  }
  idx->next_slot = next_slot;
  idx->free_slots.clear();
  for (int64_t s = next_slot - 1; s >= 0; --s) {
    if (!used[s]) idx->free_slots.push_back(s);
  }
}

// Dump live (key, slot) pairs; buffers must hold pwtpu_idx_len entries.
void pwtpu_idx_items(void* h, uint64_t* out_keys, int64_t* out_slots) {
  const KeyIndex* idx = static_cast<const KeyIndex*>(h);
  uint64_t j = 0;
  for (uint64_t pos = 0; pos <= idx->mask; ++pos) {
    if (idx->state[pos] != 1) continue;
    out_keys[2 * j] = idx->khi[pos];
    out_keys[2 * j + 1] = idx->klo[pos];
    out_slots[j] = idx->slots[pos];
    ++j;
  }
}

// ---------------------------------------------------------------------------
// MultiMap: 128-bit key -> bag of int64 values (join-side jk -> row slots).
// Same open-addressing scheme; each full entry owns a value vector. Probes
// answer in CSR form (count pass, then fill pass).

namespace {

// Values are dense unique non-negative ids (join-side row SLOTS): each value lives
// in at most one bag at a time. That contract lets bags be intrusive doubly-linked
// lists over two flat arrays indexed by value — O(1) insert/remove, no per-key
// allocation, and a rehash that only moves the fixed-size header entries.
struct MultiMap {
  std::vector<uint64_t> khi, klo;
  std::vector<int8_t> state;
  std::vector<int64_t> head;  // first value in the bag
  std::vector<int64_t> cnt;   // bag size
  std::vector<int64_t> nxt, prv;  // intrusive links, indexed by value
  std::vector<uint64_t> vhi, vlo;  // owning key per linked value (membership check)
  std::vector<uint8_t> linked;     // 1 while the value sits in some bag
  uint64_t mask = 0;
  int64_t live = 0;
  int64_t filled = 0;
  int64_t total_vals = 0;

  MultiMap() { rebuild(16); }

  void rebuild(uint64_t cap) {
    khi.assign(cap, 0);
    klo.assign(cap, 0);
    state.assign(cap, 0);
    head.assign(cap, -1);
    cnt.assign(cap, 0);
    mask = cap - 1;
    filled = live;
  }

  void ensure_links(int64_t v) {
    assert(v >= 0 && "MultiMap values must be non-negative slot ids");
    if (static_cast<size_t>(v) >= nxt.size()) {
      size_t n = nxt.size() ? nxt.size() : 64;
      while (n <= static_cast<size_t>(v)) n *= 2;
      nxt.resize(n, -1);
      prv.resize(n, -1);
      vhi.resize(n, 0);
      vlo.resize(n, 0);
      linked.resize(n, 0);
    }
  }

  void rehash_to(uint64_t new_cap) {
    std::vector<uint64_t> ohi, olo;
    std::vector<int8_t> ost;
    std::vector<int64_t> ohd, ocn;
    ohi.swap(khi);
    olo.swap(klo);
    ost.swap(state);
    ohd.swap(head);
    ocn.swap(cnt);
    rebuild(new_cap);
    for (uint64_t i = 0; i < ost.size(); ++i) {
      if (ost[i] != 1) continue;
      uint64_t pos = olo[i] & mask;
      while (state[pos] == 1) pos = (pos + 1) & mask;
      khi[pos] = ohi[i];
      klo[pos] = olo[i];
      state[pos] = 1;
      head[pos] = ohd[i];
      cnt[pos] = ocn[i];
    }
  }

  void rehash_if_needed() {
    uint64_t cap = mask + 1;
    if (static_cast<uint64_t>(filled) * 2 < cap) return;  // max load 0.5
    uint64_t new_cap = cap;
    while (static_cast<uint64_t>(live) * 4 >= new_cap) new_cap <<= 1;
    rehash_to(new_cap);
  }

  uint64_t find(uint64_t hi, uint64_t lo, bool* found) const {
    uint64_t pos = lo & mask;
    int64_t first_tomb = -1;
    for (;;) {
      int8_t st = state[pos];
      if (st == 0) {
        *found = false;
        return first_tomb >= 0 ? static_cast<uint64_t>(first_tomb) : pos;
      }
      if (st == 1 && klo[pos] == lo && khi[pos] == hi) {
        *found = true;
        return pos;
      }
      if (st == 2 && first_tomb < 0) first_tomb = static_cast<int64_t>(pos);
      pos = (pos + 1) & mask;
    }
  }

  void insert(uint64_t hi, uint64_t lo, int64_t v) {
    rehash_if_needed();
    bool found = false;
    uint64_t pos = find(hi, lo, &found);
    if (!found) {
      if (state[pos] == 0) ++filled;
      khi[pos] = hi;
      klo[pos] = lo;
      state[pos] = 1;
      head[pos] = -1;
      cnt[pos] = 0;
      ++live;
    }
    ensure_links(v);
    int64_t h = head[pos];
    nxt[v] = h;
    prv[v] = -1;
    if (h >= 0) prv[h] = v;
    head[pos] = v;
    vhi[v] = hi;
    vlo[v] = lo;
    linked[v] = 1;
    ++cnt[pos];
    ++total_vals;
  }

  // Removes v from the bag at `key` (unique-value contract). Returns true if found.
  bool remove(uint64_t hi, uint64_t lo, int64_t v) {
    bool found = false;
    uint64_t pos = find(hi, lo, &found);
    if (!found) return false;
    if (static_cast<size_t>(v) >= nxt.size()) return false;
    // O(1) membership check: v must currently be linked, and into THIS bag —
    // a value mid-chain in a different bag would otherwise be unlinked from
    // that bag while this bag's cnt is decremented (silent corruption)
    if (!linked[v] || vhi[v] != hi || vlo[v] != lo) return false;
    if (prv[v] < 0 && head[pos] == v) {
      head[pos] = nxt[v];
      if (nxt[v] >= 0) prv[nxt[v]] = -1;
    } else {
      nxt[prv[v]] = nxt[v];
      if (nxt[v] >= 0) prv[nxt[v]] = prv[v];
    }
    nxt[v] = -1;
    prv[v] = -1;
    linked[v] = 0;
    --total_vals;
    if (--cnt[pos] == 0) {
      state[pos] = 2;
      head[pos] = -1;
      --live;
    }
    return true;
  }

  // Bag accessors (head/cnt by table position; -1/0 when absent).
  int64_t bag_head(uint64_t hi, uint64_t lo) const {
    bool found = false;
    uint64_t pos = find(hi, lo, &found);
    return found ? head[pos] : -1;
  }
  int64_t bag_count(uint64_t hi, uint64_t lo) const {
    bool found = false;
    uint64_t pos = find(hi, lo, &found);
    return found ? cnt[pos] : 0;
  }
};

}  // namespace

void* pwtpu_mm_new() { return new MultiMap(); }

void pwtpu_mm_free(void* h) { delete static_cast<MultiMap*>(h); }

int64_t pwtpu_mm_total(void* h) { return static_cast<MultiMap*>(h)->total_vals; }

void pwtpu_mm_insert(void* h, const uint64_t* keys, const int64_t* values,
                     int64_t n) {
  MultiMap* mm = static_cast<MultiMap*>(h);
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t* k = key_hi_lo(keys, i);
    mm->insert(k[0], k[1], values[i]);
  }
}

// out_found (optional): 1 where an occurrence was removed.
void pwtpu_mm_remove(void* h, const uint64_t* keys, const int64_t* values,
                     int64_t n, uint8_t* out_found) {
  MultiMap* mm = static_cast<MultiMap*>(h);
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t* k = key_hi_lo(keys, i);
    bool ok = mm->remove(k[0], k[1], values[i]);
    if (out_found != nullptr) out_found[i] = ok ? 1 : 0;
  }
}

// Per-probe-row match counts; returns the total (CSR sizing pass).
int64_t pwtpu_mm_count(void* h, const uint64_t* keys, int64_t n,
                       int64_t* out_counts) {
  const MultiMap* mm = static_cast<const MultiMap*>(h);
  int64_t total = 0;
  const uint64_t msk = mm->mask;
  for (int64_t i = 0; i < n; ++i) {
    if (i + 8 < n) {
      __builtin_prefetch(&mm->state[keys[2 * (i + 8) + 1] & msk]);
    }
    const uint64_t* k = key_hi_lo(keys, i);
    int64_t c = mm->bag_count(k[0], k[1]);
    out_counts[i] = c;
    total += c;
  }
  return total;
}

// CSR fill pass: out_values must hold the total from pwtpu_mm_count, laid out
// row-major in probe order. Within one key the values come out in
// reverse-insertion (LIFO head-insert) order — deterministic for a given
// insert/remove history, but NOT the insertion order the pre-intrusive-list
// implementation produced; consumers needing a stable cross-version order
// (goldens, checkpoint diffs) must sort.
void pwtpu_mm_fill(void* h, const uint64_t* keys, int64_t n,
                   int64_t* out_values) {
  const MultiMap* mm = static_cast<const MultiMap*>(h);
  int64_t w = 0;
  const uint64_t msk = mm->mask;
  for (int64_t i = 0; i < n; ++i) {
    if (i + 8 < n) {
      __builtin_prefetch(&mm->state[keys[2 * (i + 8) + 1] & msk]);
    }
    const uint64_t* k = key_hi_lo(keys, i);
    for (int64_t v = mm->bag_head(k[0], k[1]); v >= 0; v = mm->nxt[v]) {
      out_values[w++] = v;
    }
  }
}

// ---------------------------------------------------------------------------
// Fused join-side maintenance: row-index upsert + slot-array writes + join-key
// multimap upkeep in ONE pass (the per-commit arrangement update of a join side).
// keys_arr / jk_arr are the caller's slot-indexed KEY_DTYPE arrays (interleaved
// [hi, lo] pairs), pre-sized to at least slot_bound + n entries.

void pwtpu_side_insert(void* idx_h, void* mm_h, const uint64_t* row_keys,
                       const uint64_t* jkeys, int64_t n, uint64_t* keys_arr,
                       uint64_t* jk_arr, int64_t* out_slots) {
  KeyIndex* idx = static_cast<KeyIndex*>(idx_h);
  MultiMap* mm = static_cast<MultiMap*>(mm_h);
  idx->reserve_for(static_cast<uint64_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    if (i + 8 < n) {
      __builtin_prefetch(&idx->state[row_keys[2 * (i + 8) + 1] & idx->mask]);
      __builtin_prefetch(&mm->state[jkeys[2 * (i + 8) + 1] & mm->mask]);
    }
    const uint64_t* rk = key_hi_lo(row_keys, i);
    const uint64_t* jk = key_hi_lo(jkeys, i);
    uint8_t is_new = 0;
    int64_t slot = idx->upsert(rk[0], rk[1], &is_new);
    if (!is_new) {
      // duplicate row-key insert: replace — unlink the old row from the join-key
      // bucket it actually sits in
      mm->remove(jk_arr[2 * slot], jk_arr[2 * slot + 1], slot);
    }
    keys_arr[2 * slot] = rk[0];
    keys_arr[2 * slot + 1] = rk[1];
    jk_arr[2 * slot] = jk[0];
    jk_arr[2 * slot + 1] = jk[1];
    mm->insert(jk[0], jk[1], slot);
    out_slots[i] = slot;
  }
}

void pwtpu_side_remove(void* idx_h, void* mm_h, const uint64_t* row_keys,
                       int64_t n, const uint64_t* jk_arr, int64_t* out_slots) {
  KeyIndex* idx = static_cast<KeyIndex*>(idx_h);
  MultiMap* mm = static_cast<MultiMap*>(mm_h);
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t* rk = key_hi_lo(row_keys, i);
    int64_t slot = idx->remove(rk[0], rk[1]);
    out_slots[i] = slot;
    if (slot >= 0) {
      mm->remove(jk_arr[2 * slot], jk_arr[2 * slot + 1], slot);
    }
  }
}

// Dump every (key, value) pair; buffers sized by pwtpu_mm_total.
void pwtpu_mm_items(void* h, uint64_t* out_keys, int64_t* out_values) {
  const MultiMap* mm = static_cast<const MultiMap*>(h);
  int64_t j = 0;
  for (uint64_t pos = 0; pos <= mm->mask; ++pos) {
    if (mm->state[pos] != 1) continue;
    for (int64_t v = mm->head[pos]; v >= 0; v = mm->nxt[v]) {
      out_keys[2 * j] = mm->khi[pos];
      out_keys[2 * j + 1] = mm->klo[pos];
      out_values[j] = v;
      ++j;
    }
  }
}

}  // extern "C"
