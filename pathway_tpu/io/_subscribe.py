"""``pw.io.subscribe`` — change callbacks (parity: reference ``io/subscribe``).

Two delivery modes: per-row ``on_change(key, row, time, is_addition)`` (the reference
API), and the TPU-first vectorized ``on_batch(keys, diffs, columns, time)`` which hands
the subscriber one commit's update batch as columnar numpy arrays (keys: KEY_DTYPE
structured array; diffs: +1/-1 int64; columns: dict name -> value array) without
materializing per-row Python objects.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G


def subscribe(
    table: Any,
    on_change: Callable[..., None] | None = None,
    on_end: Callable[[], None] | None = None,
    on_time_end: Callable[[int], None] | None = None,
    name: str | None = None,
    *,
    on_batch: Callable[..., None] | None = None,
) -> None:
    """Call ``on_change(key, row, time, is_addition)`` for every row update of
    ``table``, and/or ``on_batch(keys, diffs, columns, time)`` once per commit."""
    if on_change is None and on_batch is None:
        raise ValueError("subscribe needs on_change and/or on_batch")

    callback = None
    if on_change is not None:
        def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
            on_change(key=key, row=row, time=time, is_addition=is_addition)

    G.add_node(
        pg.OutputNode(
            inputs=[table],
            callback=callback,
            batch_callback=on_batch,
            on_end=on_end,
            on_time_end=on_time_end,
        )
    )
