"""``pw.io.subscribe`` — per-row change callbacks (parity: reference ``io/subscribe``)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G


def subscribe(
    table: Any,
    on_change: Callable[..., None],
    on_end: Callable[[], None] | None = None,
    on_time_end: Callable[[int], None] | None = None,
    name: str | None = None,
) -> None:
    """Call ``on_change(key, row, time, is_addition)`` for every row update of ``table``."""

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        on_change(key=key, row=row, time=time, is_addition=is_addition)

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=on_end))
