"""HTTP connectors: REST ingestion + generic http source/sink.

Parity: reference ``io/http/`` with ``_server.py`` (``PathwayWebserver``, ``rest_connector``).
Implementation lives in ``_server`` (aiohttp-based).
"""

from pathway_tpu.io.http._server import EndpointDocumentation, PathwayWebserver, rest_connector

__all__ = ["EndpointDocumentation", "PathwayWebserver", "rest_connector"]
