"""REST ingestion server.

Parity: reference ``io/http/_server.py`` (``PathwayWebserver:329``, ``rest_connector:624``):
an aiohttp server turns each HTTP request into a row of a streaming table; a response writer
subscribes to a result table and resolves the pending HTTP future for the query's key.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence

from pathway_tpu.engine import tracing
from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.engine.profile import histogram as _histogram
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Pointer, pointer_from
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


class EndpointDocumentation:
    """Per-endpoint settings for the OpenAPI v3 document (reference
    ``io/http/_server.py:126``)."""

    DEFAULT_RESPONSES = {
        "200": {"description": "OK"},
        "400": {
            "description": "The request is incorrect. Please check if it complies "
            "with the endpoint's input schema"
        },
    }

    def __init__(
        self,
        *,
        summary: str | None = None,
        description: str | None = None,
        tags: Sequence[str] | None = None,
        method_types: Sequence[str] | None = None,
    ):
        self.summary = summary
        self.description = description
        self.tags = list(tags) if tags else None
        self.method_types = (
            {m.upper() for m in method_types} if method_types is not None else None
        )

    def generate_docs(self, method: str, schema: Any) -> dict | None:
        method = method.upper()
        if self.method_types is not None and method not in self.method_types:
            return None
        entry: dict = {"responses": dict(self.DEFAULT_RESPONSES)}
        if self.summary:
            entry["summary"] = self.summary
        if self.description:
            entry["description"] = self.description
        if self.tags:
            entry["tags"] = self.tags
        properties, required = _openapi_schema_fields(schema)
        if method == "GET":
            entry["parameters"] = [
                {
                    "name": name,
                    "in": "query",
                    "required": name in required,
                    "schema": spec,
                }
                for name, spec in properties.items()
            ]
        else:
            entry["requestBody"] = {
                "content": {
                    "application/json": {
                        "schema": {
                            "type": "object",
                            "properties": properties,
                            "required": sorted(required),
                        }
                    }
                },
                "required": True,
            }
        return entry


def _openapi_schema_fields(schema: Any) -> tuple[dict, set]:
    from pathway_tpu.internals import dtype as dt

    type_map = {
        dt.INT: {"type": "integer"},
        dt.FLOAT: {"type": "number"},
        dt.BOOL: {"type": "boolean"},
        dt.STR: {"type": "string"},
        dt.JSON: {"type": "object"},
        dt.BYTES: {"type": "string", "format": "binary"},
    }
    properties: dict = {}
    required: set = set()
    for name, col in schema.columns().items():
        base = col.dtype.strip_optional()
        properties[name] = dict(type_map.get(base, {"type": "string"}))
        has_default = getattr(col, "has_default", False)
        if has_default() if callable(has_default) else has_default:
            if col.default_value is not None and col.default_value is not ...:
                properties[name]["default"] = col.default_value
        elif col.dtype == base:  # non-optional, no default
            required.add(name)
    return properties, required


class PathwayWebserver:
    """One aiohttp server shared by any number of rest_connector endpoints.

    When ``openapi_docs_path`` is set (default ``/_schema``), the server exposes the
    auto-generated OpenAPI v3 document for every registered endpoint (reference
    ``EndpointDocumentation`` docgen)."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 8080,
        with_cors: bool = False,
        openapi_docs_path: str | None = "/_schema",
    ):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self.openapi_docs_path = openapi_docs_path
        self._routes: Dict[tuple, Any] = {}
        self._docs: Dict[tuple, tuple] = {}  # (method, route) -> (schema, docs)
        self._started = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner = None

    def _register_docs(
        self,
        route: str,
        methods: Sequence[str],
        schema: Any,
        documentation: "EndpointDocumentation | None" = None,
    ) -> None:
        # documentation is declared at connector-construction time (before any
        # engine run), so the OpenAPI document is complete without serving
        for method in methods:
            self._docs[(method.upper(), route)] = (
                schema,
                documentation or EndpointDocumentation(),
            )

    def _register(self, route: str, methods: Sequence[str], handler: Any) -> None:
        if (
            self.openapi_docs_path is not None
            and route == self.openapi_docs_path
            and any(m.upper() == "GET" for m in methods)
        ):
            raise ValueError(
                f"route {route!r} collides with the OpenAPI docs endpoint; pass "
                "openapi_docs_path=None (or another path) to PathwayWebserver"
            )
        for method in methods:
            self._routes[(method.upper(), route)] = handler
        if self.openapi_docs_path is not None:
            self._routes.setdefault(("GET", self.openapi_docs_path), self._serve_openapi)
        self._ensure_running()

    async def _serve_openapi(self, request: Any) -> Any:
        import aiohttp.web as web
        import json as _json

        return web.Response(
            text=_json.dumps(self.openapi_description()),
            content_type="application/json",
        )

    def openapi_description(self) -> dict:
        """The OpenAPI v3 document covering every documented endpoint."""
        paths: dict = {}
        for (method, route), (schema, docs) in sorted(self._docs.items()):
            entry = docs.generate_docs(method, schema)
            if entry is None:
                continue
            paths.setdefault(route, {})[method.lower()] = entry
        return {
            "openapi": "3.0.3",
            "info": {"title": "Pathway-TPU API", "version": "1.0.0"},
            "servers": [{"url": f"http://{self.host}:{self.port}"}],
            "paths": paths,
        }

    def _ensure_running(self) -> None:
        if self._thread is not None:
            return

        def serve() -> None:
            import aiohttp.web as web

            async def main() -> None:
                app = web.Application()

                async def dispatch(request: web.Request) -> web.Response:
                    handler = self._routes.get((request.method, request.path))
                    if handler is None:
                        response: web.Response = web.Response(
                            status=404, text="no such endpoint"
                        )
                    else:
                        response = await handler(request)
                    if tracing.TRACE_HEADER not in response.headers:
                        # the trace context echoes on EVERY route — including
                        # 404s and routes that did not open a span — so
                        # clients can always correlate a response
                        ctx = tracing.parse_trace_header(
                            request.headers.get(tracing.TRACE_HEADER)
                        ) or tracing.new_trace_context()
                        response.headers[tracing.TRACE_HEADER] = (
                            tracing.format_trace_header(ctx)
                        )
                    return response

                app.router.add_route("*", "/{tail:.*}", dispatch)
                runner = web.AppRunner(app)
                await runner.setup()
                self._runner = runner
                site = web.TCPSite(runner, self.host, self.port)
                await site.start()
                self._started.set()
                while True:
                    await asyncio.sleep(3600)

            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(main())
            except Exception:
                self._started.set()
                raise

        self._thread = threading.Thread(target=serve, daemon=True, name="pathway:webserver")
        self._thread.start()
        self._started.wait(timeout=10)


class RestServerSubject:
    def __init__(
        self,
        webserver: PathwayWebserver,
        route: str,
        methods: Sequence[str],
        schema: sch.SchemaMetaclass,
        delete_completed_queries: bool,
        request_validator: Any = None,
        documentation: "EndpointDocumentation | None" = None,
        max_pending: int = 0,
        shed_stage: str = "rest.shed",
        retry_after: Callable[[], float] | None = None,
        overload_probe: Callable[[], bool] | None = None,
    ):
        self.webserver = webserver
        self.route = route
        self.methods = methods
        self.schema = schema
        self.delete_completed_queries = delete_completed_queries
        self.request_validator = request_validator
        self.documentation = documentation
        self.futures: Dict[bytes, "asyncio.Future"] = {}
        # admission control: requests already pushed into the engine and not
        # yet answered. Past ``max_pending`` (0 = unbounded) new requests are
        # shed with 429 + Retry-After instead of queueing without bound —
        # first slice of the REST-plane backpressure story
        self.max_pending = max(0, int(max_pending))
        self.shed_stage = shed_stage
        self._retry_after = retry_after
        # secondary admission probe (e.g. the embed coalescer's row-queue cap):
        # sheds on downstream queue depth, not just this route's request count
        self._overload_probe = overload_probe
        self.shed_requests = 0
        # per-client shed attribution (X-Pathway-Client header): a noisy
        # neighbor's flood shows up HERE, not smeared over everyone. Only the
        # handler's event-loop thread mutates it. BOUNDED: the header is
        # attacker-controlled, so only the first _MAX_SHED_CLIENTS distinct
        # ids get their own counter — later ids fold into "other" (an id
        # rotation attack must not grow the stage-counter dict or /metrics
        # cardinality without bound)
        self.shed_by_client: Dict[str, int] = {}
        self._counter = 0
        self._lock = threading.Lock()

    def run(self, source: StreamingDataSource) -> None:
        async def handler(request: Any) -> Any:
            # the route's "rest" span: parented by the client's X-Pathway-Trace
            # context (or a fresh root), covering admission -> engine commit ->
            # future resolution, and echoed back with OUR span id so the
            # client can look the request up in the merged trace
            parent_ctx = tracing.parse_trace_header(
                request.headers.get(tracing.TRACE_HEADER)
            )
            with tracing.trace_span(
                "rest",
                f"{request.method} {self.route}",
                ctx=parent_ctx,
                attrs={"route": self.route},
            ) as span:
                response = await _handle(request, span)
                if span is not None:
                    span.attrs["status"] = response.status
                echo_ctx = (
                    span.context()
                    if span is not None
                    else (parent_ctx or tracing.new_trace_context())
                )
                response.headers[tracing.TRACE_HEADER] = (
                    tracing.format_trace_header(echo_ctx)
                )
            return response

        async def _handle(request: Any, span: Any) -> Any:
            import aiohttp.web as web

            if request.method in ("POST", "PUT", "PATCH"):
                try:
                    payload = await request.json()
                except json.JSONDecodeError:
                    payload = {}
            else:
                payload = dict(request.query)
            if self.request_validator is not None:
                try:
                    self.request_validator(payload)
                except Exception as e:
                    return web.Response(status=400, text=str(e))
            from pathway_tpu.engine.brownout import get_brownout, retry_after_int

            brownout = get_brownout()
            # quiesce window: a membership transition has the commit loop
            # paused — an admitted request would HANG until the cluster
            # resumes at C+1, so shed with the expected remaining pause as an
            # honest Retry-After instead (chaos-tested)
            quiesce_s = brownout.quiesce_retry_after()
            if quiesce_s is not None:
                from pathway_tpu.engine import telemetry

                telemetry.stage_add("rest.quiesce_shed")
                return web.Response(
                    status=429,
                    headers={"Retry-After": retry_after_int(quiesce_s)},
                    text=(
                        "resharding in progress (cluster quiesced at a commit "
                        "boundary); retry after the indicated delay"
                    ),
                )
            probe_hit = False
            if self._overload_probe is not None:
                try:
                    probe_hit = bool(self._overload_probe())
                except Exception:
                    probe_hit = False
            # brownout rung 1/2: the admission cap TIGHTENS before the
            # autoscaler spends a reshard pause — cheap degradation first
            effective_pending = self.max_pending
            brownout_level = 0
            if self.max_pending:
                scale = brownout.admission_scale()
                if scale < 1.0:
                    brownout_level = brownout.level()
                    effective_pending = max(1, int(self.max_pending * scale))
            if probe_hit or (
                effective_pending and len(self.futures) >= effective_pending
            ):
                # shed BEFORE pushing into the engine: an admitted request
                # costs an engine commit + an embed slot; a shed one costs
                # only this response
                self.shed_requests += 1
                from pathway_tpu.engine import telemetry

                telemetry.stage_add(self.shed_stage)
                client = _client_id(request)
                if client is not None:
                    if (
                        client not in self.shed_by_client
                        and len(self.shed_by_client) >= _MAX_SHED_CLIENTS
                    ):
                        client = "other"
                    self.shed_by_client[client] = (
                        self.shed_by_client.get(client, 0) + 1
                    )
                    telemetry.stage_add(f"{self.shed_stage}.client.{client}")
                retry_s = 1.0
                if self._retry_after is not None:
                    try:
                        retry_s = float(self._retry_after())
                    except Exception:
                        pass
                reason = (
                    "downstream embed queue full"
                    if probe_hit
                    else (
                        f"{len(self.futures)} requests in flight "
                        f"(cap {effective_pending}"
                        + (
                            f", tightened by brownout rung {brownout_level}"
                            if brownout_level
                            else ""
                        )
                        + ")"
                    )
                )
                return web.Response(
                    status=429,
                    headers={"Retry-After": retry_after_int(retry_s)},
                    text=(
                        f"overloaded: {reason}; retry after the indicated delay"
                    ),
                )
            with self._lock:
                self._counter += 1
                qid = self._counter
            key = pointer_from(qid, self.route, "rest")
            from pathway_tpu.internals.keys import pointers_to_keys

            kb = pointers_to_keys([key]).tobytes()
            loop = asyncio.get_running_loop()
            future: asyncio.Future = loop.create_future()
            self.futures[kb] = future
            row = {}
            for name, col in self.schema.columns().items():
                v = payload.get(name, col.default_value if col.has_default else None)
                if col.dtype.strip_optional() == dt.JSON and v is not None and not isinstance(v, Json):
                    v = Json(v)
                row[name] = v
            if span is not None:
                # causal handoff into the engine: the NEXT commit links this
                # query (take_commit_links in GraphRunner.step), and the
                # encoder tick that batches the query text links it too
                # (take_query_links keyed by text) — a coalesced batch ends
                # up linking all N parent query spans
                tracer = tracing.get_tracer()
                span_ctx = span.context()
                tracer.register_commit_link(span_ctx)
                for field in ("query", "text", "prompt"):
                    text = row.get(field)
                    if isinstance(text, str) and text:
                        tracer.register_query_link(text, span_ctx)
                        break
            t0 = time.perf_counter()
            source.push(row, key=key, diff=1)
            try:
                result = await future
                # the serving-path latency histogram (/metrics exports it next
                # to commit duration): push -> engine commit -> future resolution
                _histogram("pathway_rest_latency_seconds").observe(
                    time.perf_counter() - t0
                )
            finally:
                # a cancelled handler (client disconnect/timeout) must release
                # its admission slot and retract its query row — under the
                # max_pending check a leaked slot is a permanent 429 wedge,
                # not just a memory leak
                self.futures.pop(kb, None)
                if self.delete_completed_queries:
                    source.push(row, key=key, diff=-1)
            if isinstance(result, (dict, list)):
                return web.json_response(result)
            if isinstance(result, Json):
                return web.json_response(result.value)
            return web.json_response(result)

        self.webserver._register(self.route, self.methods, handler)
        # block forever: the server lives until the process exits
        threading.Event().wait()

    def resolve(self, key: Pointer, result: Any) -> None:
        from pathway_tpu.internals.keys import pointers_to_keys

        kb = pointers_to_keys([key]).tobytes()
        future = self.futures.get(kb)
        if future is not None and self.webserver._loop is not None:
            self.webserver._loop.call_soon_threadsafe(
                lambda: future.set_result(result) if not future.done() else None
            )


def rest_connector(
    host: str | None = None,
    port: int | None = None,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema: sch.SchemaMetaclass | None = None,
    methods: Sequence[str] = ("POST",),
    # serving path: a 1 ms commit tick makes per-request latency wake+commit.
    # Bursts still batch naturally — while one commit processes, arriving
    # requests queue and drain together in the next batch — so the tick only
    # throttles tiny-commit storms, it is not the batching mechanism (see
    # StreamingDataSource.next_batch).
    autocommit_duration_ms: int | None = 1,
    keep_queries: bool | None = None,
    delete_completed_queries: bool = False,
    request_validator: Any = None,
    documentation: "EndpointDocumentation | None" = None,
    max_pending: int = 0,
    shed_stage: str = "rest.shed",
    retry_after: "Callable[[], float] | None" = None,
    overload_probe: "Callable[[], bool] | None" = None,
) -> tuple[Table, Any]:
    """Expose an HTTP endpoint as a streaming table; returns (queries, response_writer).
    ``max_pending`` caps in-flight requests on the route (0 = unbounded): past
    it — or while the optional ``overload_probe`` callable reports a saturated
    downstream queue — requests are shed with 429 + ``Retry-After`` (estimated
    by the optional ``retry_after`` callable) and counted on stage counter
    ``shed_stage``."""
    if webserver is None:
        webserver = PathwayWebserver(host=host or "0.0.0.0", port=port or 8080)
    if schema is None:
        schema = sch.schema_from_types(query=str)
    subject = RestServerSubject(
        webserver, route, methods, schema, delete_completed_queries, request_validator,
        documentation=documentation, max_pending=max_pending, shed_stage=shed_stage,
        retry_after=retry_after, overload_probe=overload_probe,
    )
    webserver._register_docs(route, methods, schema, documentation)

    class _Runner:
        def run(self, source: StreamingDataSource) -> None:
            subject.run(source)

    source = StreamingDataSource(subject=_Runner(), autocommit_ms=autocommit_duration_ms)
    node = G.add_node(pg.InputNode(source=source, streaming=True, name=f"rest:{route}"))
    queries = Table(node, schema, name="rest_queries")

    def response_writer(result_table: Table, result_column: str = "result") -> None:
        def on_change(key: Pointer, row: dict, time: int, is_addition: bool) -> None:
            if is_addition:
                subject.resolve(key, _jsonable(row.get(result_column)))

        from pathway_tpu.io._subscribe import subscribe

        subscribe(result_table, on_change)

    return queries, response_writer


# distinct client ids tracked per route before attribution folds into "other"
_MAX_SHED_CLIENTS = 32


def _client_id(request: Any) -> "str | None":
    """Sanitized ``X-Pathway-Client`` header value for shed attribution
    (stage-counter-safe: alnum/dash/underscore, bounded length)."""
    try:
        raw = request.headers.get("X-Pathway-Client")
    except Exception:
        return None
    if not raw:
        return None
    cleaned = "".join(c for c in str(raw)[:32] if c.isalnum() or c in "-_")
    return cleaned or None


def _jsonable(v: Any) -> Any:
    from pathway_tpu.internals.json import jsonable_value

    return jsonable_value(v)
