"""S3 connector (parity: reference ``io/s3`` over ``scanner/s3.rs``).

No S3 client library is baked into this image; reads over ``s3://`` URIs raise a clear error,
while local paths (including mounted buckets) delegate to the fs connector so pipelines written
against this API run anywhere the data is reachable as files.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs


class AwsS3Settings:
    def __init__(
        self,
        *,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        region: str | None = None,
        endpoint: str | None = None,
        with_path_style: bool = False,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "plaintext",
    schema: Any = None,
    mode: str = "streaming",
    **kwargs: Any,
) -> Any:
    if str(path).startswith("s3://"):
        try:
            import boto3  # noqa: F401
        except ImportError:
            raise ImportError(
                "no S3 client library (boto3) in this environment; mount the bucket as a "
                "filesystem or pass a local path"
            )
    return fs.read(path, format=format, schema=schema, mode=mode, **kwargs)
