"""S3 connector (parity: reference ``io/s3`` over ``src/connectors/scanner/s3.rs``
and the S3 writer path in ``data_storage.rs``).

Real client code against the ``boto3``/S3 API: the reader scans the bucket prefix
(paginated ``list_objects_v2``), streams each object's bytes through the shared
wire-format parsers (``io/fs.py:parse_bytes``), tracks per-object ETags so changed
objects retract-and-replace and deleted objects retract (the fs scanner semantics over
object storage), and checkpoints per-object completion in-band for exact resume. The
writer uploads one part object per output commit. Client construction is injectable
(``_client_factory``) so unit tests run against an in-memory fake; local paths still
delegate to the fs connector.
"""

from __future__ import annotations

import json
import time as time_mod
from typing import Any, Callable, Dict, List

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import pointer_from
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.io import fs


class AwsS3Settings:
    def __init__(
        self,
        *,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        region: str | None = None,
        endpoint: str | None = None,
        with_path_style: bool = False,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style


def _default_client_factory(settings: AwsS3Settings | None) -> Any:
    try:
        import boto3
        from botocore.config import Config
    except ImportError as exc:
        raise ImportError(
            "no S3 client library (boto3) in this environment; pass "
            "_client_factory=... (any object with the boto3 S3 client "
            "list_objects_v2/get_object/put_object/delete_object surface), or mount "
            "the bucket as a filesystem and pass a local path"
        ) from exc
    kwargs: dict = {}
    if settings is not None:
        if settings.access_key:
            kwargs["aws_access_key_id"] = settings.access_key
        if settings.secret_access_key:
            kwargs["aws_secret_access_key"] = settings.secret_access_key
        if settings.region:
            kwargs["region_name"] = settings.region
        if settings.endpoint:
            kwargs["endpoint_url"] = settings.endpoint
        if settings.with_path_style:
            kwargs["config"] = Config(s3={"addressing_style": "path"})
    return boto3.client("s3", **kwargs)


def _split_uri(path: str, settings: AwsS3Settings | None) -> tuple[str, str]:
    assert path.startswith("s3://")
    rest = path[len("s3://"):]
    bucket, _, prefix = rest.partition("/")
    if not bucket and settings is not None and settings.bucket_name:
        bucket = settings.bucket_name
    if not bucket:
        raise ValueError(f"cannot determine bucket from {path!r}")
    return bucket, prefix


def _list_objects(client: Any, bucket: str, prefix: str) -> List[dict]:
    out: List[dict] = []
    token = None
    while True:
        kwargs = {"Bucket": bucket, "Prefix": prefix}
        if token:
            kwargs["ContinuationToken"] = token
        resp = client.list_objects_v2(**kwargs)
        out.extend(resp.get("Contents", []))
        if not resp.get("IsTruncated"):
            break
        token = resp.get("NextContinuationToken")
    return sorted(out, key=lambda o: o["Key"])


class _S3Subject:
    """Object-store scanner: the fs subject's segment semantics over S3 objects,
    keyed by ETag instead of mtime (reference ``scanner/s3.rs`` +
    ``cached_object_storage.rs`` replay-without-refetch)."""

    def __init__(
        self,
        client_factory: Callable[[AwsS3Settings | None], Any],
        settings: AwsS3Settings | None,
        bucket: str,
        prefix: str,
        format: str,
        schema: sch.SchemaMetaclass | None,
        mode: str,
        with_metadata: bool,
        refresh_interval: float = 1.0,
        csv_settings: Any = None,
    ):
        self.client_factory = client_factory
        self.settings = settings
        self.bucket = bucket
        self.prefix = prefix
        self.format = format
        self.schema = schema
        self.mode = mode
        self.with_metadata = with_metadata
        self.refresh_interval = refresh_interval
        self.csv_settings = csv_settings
        self.seen: Dict[str, str] = {}  # key -> etag
        self.emitted: Dict[str, List[dict]] = {}

    fold_state_deltas = staticmethod(fs._FsSubject.fold_state_deltas)

    def restore(self, state_deltas: list) -> None:
        for delta in state_deltas:
            key = delta["file"]
            if delta.get("deleted"):
                self.seen.pop(key, None)
                self.emitted.pop(key, None)
            else:
                self.seen[key] = delta["mtime"]  # mtime slot carries the etag
                self.emitted[key] = list(delta["rows"])

    def _process_object(self, source: StreamingDataSource, client: Any, obj: dict) -> None:
        key, etag = obj["Key"], obj.get("ETag", "")
        body = client.get_object(Bucket=self.bucket, Key=key)["Body"].read()
        rows = fs.parse_bytes(body, self.format, self.schema, self.csv_settings)
        if self.with_metadata:
            meta = Json(
                {
                    "path": f"s3://{self.bucket}/{key}",
                    "etag": etag,
                    "size": obj.get("Size"),
                    "modified_at": str(obj.get("LastModified", "")),
                }
            )
            for row in rows:
                row["_metadata"] = meta
        source.push_begin(key, etag)
        if key in self.emitted:
            for i, row in enumerate(self.emitted[key]):
                source.push(row, key=pointer_from(self.bucket, key, i, "s3"), diff=-1)
        for i, row in enumerate(rows):
            source.push(row, key=pointer_from(self.bucket, key, i, "s3"), diff=1)
        self.seen[key] = etag
        self.emitted[key] = rows
        source.push_state({"file": key, "mtime": etag, "rows": rows})

    def _process_deletion(self, source: StreamingDataSource, key: str) -> None:
        source.push_begin(key, ("deleted",))
        for i, row in enumerate(self.emitted.get(key, [])):
            source.push(row, key=pointer_from(self.bucket, key, i, "s3"), diff=-1)
        self.seen.pop(key, None)
        self.emitted.pop(key, None)
        source.push_state({"file": key, "deleted": True})

    def run(self, source: StreamingDataSource) -> None:
        from pathway_tpu.internals.config import get_pathway_config

        cfg = get_pathway_config()
        client = self.client_factory(self.settings)
        stop = False
        while not stop:
            objects = _list_objects(client, self.bucket, self.prefix)
            if cfg.processes > 1:
                # partitioned parallel read: each spawn process owns a hash-shard
                # of objects (reference parallel_readers)
                objects = [
                    o
                    for o in objects
                    if pointer_from(o["Key"]).lo % cfg.processes == cfg.process_id
                ]
            present = set()
            for obj in objects:
                key = obj["Key"]
                present.add(key)
                if self.seen.get(key) == obj.get("ETag", ""):
                    continue
                try:
                    self._process_object(source, client, obj)
                except client_missing_errors(client):
                    continue  # deleted between list and get; next pass retracts
            for gone in sorted(set(self.seen) - present):
                self._process_deletion(source, gone)
            source.push_barrier()
            if self.mode in ("static", "batch"):
                stop = True
            else:
                time_mod.sleep(self.refresh_interval)


def client_missing_errors(client: Any) -> tuple:
    exc = getattr(client, "exceptions", None)
    missing = getattr(exc, "NoSuchKey", None) if exc is not None else None
    return (missing,) if missing is not None else (FileNotFoundError,)


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "plaintext",
    schema: Any = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 100,
    name: str | None = None,
    _client_factory: Callable[[AwsS3Settings | None], Any] | None = None,
    **kwargs: Any,
) -> Table:
    if not str(path).startswith("s3://"):
        # mounted buckets / local paths run through the fs scanner unchanged
        return fs.read(
            path,
            format=format,
            schema=schema,
            mode=mode,
            csv_settings=csv_settings,
            with_metadata=with_metadata,
            autocommit_duration_ms=autocommit_duration_ms,
            name=name,
            **kwargs,
        )
    bucket, prefix = _split_uri(str(path), aws_s3_settings)
    if _client_factory is None:
        # fail at call time, not inside the connector thread
        try:
            import boto3  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "no S3 client library (boto3) in this environment; pass "
                "_client_factory=... or mount the bucket as a filesystem"
            ) from exc
    if schema is None:
        if format in ("plaintext", "plaintext_by_file"):
            schema = sch.schema_from_types(data=str)
        elif format in ("binary", "raw"):
            schema = sch.schema_from_types(data=bytes)
        else:
            raise ValueError(f"schema is required for format {format!r}")
    out_schema = schema
    if with_metadata:
        out_schema = sch.schema_from_columns(
            {**schema.columns(), "_metadata": sch.ColumnSchema("_metadata", dt.JSON)},
            name="s3",
        )
    subject = _S3Subject(
        _client_factory or _default_client_factory,
        aws_s3_settings,
        bucket,
        prefix,
        format,
        schema,
        mode,
        with_metadata,
        csv_settings=csv_settings,
    )
    source = StreamingDataSource(subject=subject, autocommit_ms=autocommit_duration_ms)
    node = G.add_node(
        pg.InputNode(source=source, streaming=mode == "streaming", name=name or "s3")
    )
    return Table(node, out_schema, name=name or "s3")


def write(
    table: Table,
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    format: str = "json",
    name: str | None = None,
    _client_factory: Callable[[AwsS3Settings | None], Any] | None = None,
    **kwargs: Any,
) -> None:
    """Upload the table's update stream as one part object per commit (jsonlines
    carrying the reference's ``diff``/``time`` fields)."""
    if not str(path).startswith("s3://"):
        return fs.write(table, path, format=format, **kwargs)
    bucket, prefix = _split_uri(str(path), aws_s3_settings)
    if _client_factory is None:
        try:
            import boto3  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "no S3 client library (boto3) in this environment; pass "
                "_client_factory=..."
            ) from exc
    factory = _client_factory or _default_client_factory
    box: list = [None, 0]  # client, part counter
    columns = table.column_names()

    def batch_callback(keys: Any, diffs: Any, cols: dict, time: int) -> None:
        if box[0] is None:
            box[0] = factory(aws_s3_settings)
        client = box[0]
        from pathway_tpu.io._utils import columns_to_pylists

        col_lists = columns_to_pylists(cols, columns)
        lines = []
        for i in range(len(keys)):
            row = {c: col_lists[c][i] for c in columns}
            row = {
                k: (v.value if isinstance(v, Json) else v) for k, v in row.items()
            }
            lines.append(json.dumps({**row, "diff": int(diffs[i]), "time": int(time)}))
        part = box[1]
        box[1] += 1
        key = f"{prefix.rstrip('/')}/part-{time:012d}-{part:06d}.jsonl".lstrip("/")
        client.put_object(Bucket=bucket, Key=key, Body=("\n".join(lines) + "\n").encode())

    G.add_node(pg.OutputNode(inputs=[table], batch_callback=batch_callback))
