"""Plaintext connector (parity: reference ``io/plaintext``)."""

from __future__ import annotations

from pathlib import Path
from typing import Any

from pathway_tpu.io import fs


def read(path: str | Path, *, mode: str = "streaming", **kwargs: Any):
    return fs.read(path, format="plaintext", mode=mode, **kwargs)
