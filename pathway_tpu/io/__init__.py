"""I/O connector namespaces.

Parity: reference ``python/pathway/io/`` — 27 connector namespaces. Connectors are host-side
(IO never belongs on the TPU); each ``read`` returns a Table backed by a DataSource, each
``write`` adds an output node. Namespaces needing absent client libraries degrade with a clear
ImportError at call time, not import time.
"""

from pathway_tpu.io import (
    airbyte,
    bigquery,
    csv,
    debezium,
    deltalake,
    elasticsearch,
    fs,
    gdrive,
    http,
    jsonlines,
    kafka,
    logstash,
    minio,
    mongodb,
    nats,
    null,
    plaintext,
    postgres,
    pubsub,
    pyfilesystem,
    python,
    redpanda,
    s3,
    s3_csv,
    slack,
    sqlite,
)
from pathway_tpu.io._subscribe import subscribe
from pathway_tpu.io.export_import import ExportedTable, export_table, import_table

__all__ = [
    "airbyte",
    "bigquery",
    "csv",
    "debezium",
    "deltalake",
    "elasticsearch",
    "fs",
    "gdrive",
    "http",
    "jsonlines",
    "kafka",
    "logstash",
    "minio",
    "mongodb",
    "nats",
    "null",
    "plaintext",
    "postgres",
    "pubsub",
    "pyfilesystem",
    "python",
    "redpanda",
    "s3",
    "s3_csv",
    "slack",
    "sqlite",
    "subscribe",
    "ExportedTable",
    "export_table",
    "import_table",
]
