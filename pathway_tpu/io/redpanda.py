"""Redpanda connector (parity: reference ``io/redpanda`` — Kafka-protocol compatible)."""

from __future__ import annotations

from pathway_tpu.io.kafka import read, read_from_iterable, write

__all__ = ["read", "write", "read_from_iterable"]
