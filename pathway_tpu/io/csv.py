"""CSV connector (parity: reference ``io/csv``)."""

from __future__ import annotations

from pathlib import Path
from typing import Any

from pathway_tpu.io import fs


class CsvParserSettings:
    def __init__(self, delimiter: str = ",", quote: str = '"', escape: str | None = None, **kw: Any):
        self.delimiter = delimiter
        self.quote = quote
        self.escape = escape


def read(path: str | Path, *, schema: Any = None, mode: str = "streaming", csv_settings: CsvParserSettings | None = None, **kwargs: Any):
    return fs.read(path, format="csv", schema=schema, mode=mode, csv_settings=csv_settings, **kwargs)


def write(table: Any, filename: str | Path, **kwargs: Any) -> None:
    import csv as _csv
    import threading

    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.internals.parse_graph import G

    f = open(str(filename), "w", newline="")
    names = table.column_names()
    writer = _csv.writer(f)
    writer.writerow(names + ["time", "diff"])
    lock = threading.Lock()

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        with lock:
            writer.writerow([row[n] for n in names] + [time, 1 if is_addition else -1])
            f.flush()

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=f.close))
