"""Null sink (parity: reference ``io/null`` — ``data_storage.rs:1395`` NullWriter).

The output delta is fully computed and delivered to the sink boundary, then dropped
without materializing per-row Python objects.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G


def write(table: Any, name: str | None = None) -> None:
    def batch_callback(keys: Any, diffs: Any, columns: dict, time: int) -> None:
        pass

    G.add_node(pg.OutputNode(inputs=[table], batch_callback=batch_callback))
