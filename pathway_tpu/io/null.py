"""Null sink (parity: reference ``io/null``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G


def write(table: Any, name: str | None = None) -> None:
    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        pass

    G.add_node(pg.OutputNode(inputs=[table], callback=callback))
