"""PostgreSQL sink.

Parity: reference ``io/postgres`` over the Psql writer (``src/connectors/data_storage.rs:1080``)
with the ``PsqlUpdates``/``PsqlSnapshot`` formatters (``data_format.rs:1625,1684``).
Statement generation is pure (testable without a server); execution needs psycopg2/pg8000
or an injected ``_connection_factory`` (any DB-API connection).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _sql_value(v: Any) -> Any:
    from pathway_tpu.internals.json import Json

    if isinstance(v, Json):
        import json as _json

        return _json.dumps(v.value)
    if hasattr(v, "item"):
        return v.item()
    if type(v).__name__ == "Pointer":
        return repr(v)
    return v


def updates_statement(table_name: str, row: dict, time: int, diff: int) -> tuple[str, Sequence[Any]]:
    """INSERT carrying (time, diff) — the ``PsqlUpdates`` wire format."""
    cols = [*row.keys(), "time", "diff"]
    placeholders = ", ".join(["%s"] * len(cols))
    sql = f'INSERT INTO {table_name} ({", ".join(cols)}) VALUES ({placeholders})'
    return sql, [*(_sql_value(v) for v in row.values()), time, diff]


def snapshot_statement(
    table_name: str, primary_key: Sequence[str], row: dict, time: int, diff: int
) -> tuple[str, Sequence[Any]]:
    """Upsert/delete keeping only the current snapshot — the ``PsqlSnapshot``
    format (reference ``data_format.rs:1684``: inserts carry (time, diff) and
    upsert on the primary key; deletions remove the key's row)."""
    if diff > 0:
        cols = [*row.keys(), "time", "diff"]
        placeholders = ", ".join(["%s"] * len(cols))
        updates = ", ".join(
            f"{c}=EXCLUDED.{c}" for c in cols if c not in primary_key
        )
        sql = (
            f'INSERT INTO {table_name} ({", ".join(cols)}) VALUES ({placeholders}) '
            f'ON CONFLICT ({", ".join(primary_key)}) DO UPDATE SET {updates}'
        )
        return sql, [*(_sql_value(v) for v in row.values()), time, diff]
    conds = " AND ".join(f"{c}=%s" for c in primary_key)
    sql = f"DELETE FROM {table_name} WHERE {conds}"
    return sql, [_sql_value(row[c]) for c in primary_key]


def _connect(postgres_settings: dict) -> Any:
    try:
        import psycopg2

        return psycopg2.connect(**postgres_settings)
    except ImportError:
        pass
    try:
        import pg8000.dbapi

        return pg8000.dbapi.connect(**postgres_settings)
    except ImportError as exc:
        raise ImportError(
            "no PostgreSQL driver (psycopg2 / pg8000) is available in this "
            "environment; pass _connection_factory=... (any DB-API connection)"
        ) from exc


_PG_TYPES = {
    "INT": "BIGINT",
    "FLOAT": "DOUBLE PRECISION",
    "BOOL": "BOOLEAN",
    "STR": "TEXT",
    "JSON": "JSONB",
    "DATE_TIME_NAIVE": "TIMESTAMP",
    "DATE_TIME_UTC": "TIMESTAMPTZ",
}


def create_table_statement(table: Table, table_name: str, *, extra_columns: Sequence[str] = (), primary_key: Sequence[str] = ()) -> str:
    cols = []
    for name, column in table.schema.columns().items():
        base = column.dtype.strip_optional()
        cols.append(f"{name} {_PG_TYPES.get(repr(base).upper(), 'TEXT')}")
    cols.extend(extra_columns)
    if primary_key:
        cols.append(f'PRIMARY KEY ({", ".join(primary_key)})')
    return f'CREATE TABLE IF NOT EXISTS {table_name} ({", ".join(cols)})'


def _apply_init_mode(
    connection: Any,
    cursor: Any,
    table: Table,
    table_name: str,
    init_mode: str,
    extra: Sequence[str],
    primary_key: Sequence[str] = (),
) -> None:
    if init_mode == "default":
        return
    if init_mode not in ("create_if_not_exists", "replace"):
        raise ValueError(f"unsupported init_mode {init_mode!r}")
    if init_mode == "replace":
        cursor.execute(f"DROP TABLE IF EXISTS {table_name}")
    cursor.execute(
        create_table_statement(
            table, table_name, extra_columns=extra, primary_key=primary_key
        )
    )
    connection.commit()


class _BatchingExecutor:
    """Commit every ``max_batch_size`` statements (reference
    ``max_batch_size``: bounds entries per transaction); ``flush`` commits the
    tail at stream end."""

    def __init__(self, connection: Any, max_batch_size: int | None):
        self.connection = connection
        self.cursor = connection.cursor()
        self.max_batch_size = max_batch_size
        self._pending = 0

    def execute(self, sql: str, params: Sequence[Any]) -> None:
        self.cursor.execute(sql, params)
        self._pending += 1
        if self.max_batch_size is None or self._pending >= self.max_batch_size:
            self.connection.commit()
            self._pending = 0

    def close(self) -> None:
        if self._pending:
            self.connection.commit()
            self._pending = 0
        self.connection.close()


def write(
    table: Table,
    postgres_settings: dict,
    table_name: str,
    *,
    max_batch_size: int | None = None,
    init_mode: str = "default",
    _connection_factory: Callable[[dict], Any] | None = None,
    **kwargs: Any,
) -> None:
    """Stream updates as ``(…, time, diff)`` INSERTs (reference ``io/postgres.write``)."""
    connection = (_connection_factory or _connect)(postgres_settings)
    executor = _BatchingExecutor(connection, max_batch_size)
    _apply_init_mode(
        connection, executor.cursor, table, table_name, init_mode, ("time BIGINT", "diff BIGINT")
    )

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        sql, params = updates_statement(table_name, row, time, 1 if is_addition else -1)
        executor.execute(sql, params)

    G.add_node(
        pg.OutputNode(inputs=[table], callback=callback, on_end=executor.close)
    )


def write_snapshot(
    table: Table,
    postgres_settings: dict,
    table_name: str,
    primary_key: Sequence[str],
    *,
    max_batch_size: int | None = None,
    init_mode: str = "default",
    _connection_factory: Callable[[dict], Any] | None = None,
    **kwargs: Any,
) -> None:
    """Maintain the current snapshot via upserts/deletes (reference
    ``write_snapshot`` over the ``PsqlSnapshot`` formatter)."""
    missing = [c for c in primary_key if c not in table.column_names()]
    if missing:
        raise ValueError(
            f"write_snapshot: primary key column(s) {missing} not in table "
            f"columns {table.column_names()}"
        )
    connection = (_connection_factory or _connect)(postgres_settings)
    executor = _BatchingExecutor(connection, max_batch_size)
    _apply_init_mode(
        connection,
        executor.cursor,
        table,
        table_name,
        init_mode,
        ("time BIGINT", "diff BIGINT"),
        primary_key=primary_key,
    )

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        sql, params = snapshot_statement(
            table_name, primary_key, row, time, 1 if is_addition else -1
        )
        executor.execute(sql, params)

    G.add_node(
        pg.OutputNode(inputs=[table], callback=callback, on_end=executor.close)
    )
