"""Google Pub/Sub sink (parity: reference ``io/pubsub`` — pure-Python publisher).
Requires google-cloud-pubsub; degrades with a clear error."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def write(table: Table, publisher: Any, project_id: str, topic_id: str, **kwargs: Any) -> None:
    if publisher is None:
        try:
            from google.cloud import pubsub_v1

            publisher = pubsub_v1.PublisherClient()
        except ImportError as exc:
            raise ImportError("google-cloud-pubsub is not available in this environment") from exc
    topic_path = publisher.topic_path(project_id, topic_id)
    futures: list[Any] = []

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        import json

        from pathway_tpu.io._utils import plain_row

        data = json.dumps({**plain_row(row), "time": time, "diff": 1 if is_addition else -1})
        futures.append(publisher.publish(topic_path, data.encode()))

    def flush() -> None:
        # publish() only enqueues into the client's batcher; block until delivered
        for future in futures:
            future.result(timeout=60)

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=flush))
