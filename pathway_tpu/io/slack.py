"""Slack sink (parity: reference ``io/slack`` — ``send_alerts`` posting one message per
row to a channel via chat.postMessage)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table

_API_URL = "https://slack.com/api/chat.postMessage"


def send_alerts(
    alerts: Any,
    slack_channel_id: str,
    slack_token: str,
    *,
    api_url: str = _API_URL,
    **kwargs: Any,
) -> None:
    """Post each new value of ``alerts`` (a column reference or single-column table)."""
    import requests

    from pathway_tpu.internals.expression import ColumnReference

    if isinstance(alerts, ColumnReference):
        table: Table = alerts.table
        name = alerts.name
    else:
        table = alerts
        name = table.column_names()[0]
    session = requests.Session()

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        if not is_addition:
            return
        response = session.post(
            api_url,
            headers={"Authorization": f"Bearer {slack_token}"},
            json={"channel": slack_channel_id, "text": str(row[name])},
            timeout=10,
        )
        response.raise_for_status()

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=session.close))
