"""Delta Lake connector (parity: reference ``io/deltalake`` over
``data_storage.rs:1924,1621``). Requires the deltalake package; degrades with a clear
error pointing at the fs/csv surface."""

from __future__ import annotations

from typing import Any


def _no_client() -> None:
    raise ImportError(
        "the deltalake package is not available in this environment; export the table "
        "to parquet/csv and use pw.io.fs.read, or install deltalake"
    )


def read(uri: str, *, schema: Any = None, mode: str = "streaming", autocommit_duration_ms: int | None = 1500, **kwargs: Any) -> Any:
    try:
        import deltalake  # noqa: F401
    except ImportError:
        _no_client()


def write(table: Any, uri: str, *, min_commit_frequency: int | None = 60_000, **kwargs: Any) -> None:
    try:
        import deltalake  # noqa: F401
    except ImportError:
        _no_client()
