"""Delta Lake connector.

Parity: reference ``io/deltalake`` over ``data_storage.rs:1924`` (reader) and ``:1621``
(writer). Implemented against the ``deltalake`` Python package (absent from this image —
the code paths are exercised only where the package is installed): the reader polls table
versions and emits row-level diffs between snapshots; the writer appends update batches
with ``time``/``diff`` columns.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _require() -> Any:
    try:
        import deltalake

        return deltalake
    except ImportError as exc:
        raise ImportError(
            "the deltalake package is not available in this environment; export the "
            "table to parquet/csv and use pw.io.fs.read, or install deltalake"
        ) from exc


def read(
    uri: str,
    *,
    schema: sch.SchemaMetaclass,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    refresh_interval_s: float = 5.0,
    **kwargs: Any,
) -> Table:
    deltalake = _require()

    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    names = schema.column_names()

    class _DeltaSubject(ConnectorSubject):
        def run(self) -> None:
            import time as _time

            emitted: dict[tuple, int] = {}  # row tuple -> multiplicity
            last_version = -1
            while True:
                table = deltalake.DeltaTable(uri)
                version = table.version()
                if version != last_version:
                    last_version = version
                    current: dict[tuple, int] = {}
                    for batch in table.to_pyarrow_dataset().to_batches():
                        for record in batch.to_pylist():
                            token = tuple(record.get(n) for n in names)
                            current[token] = current.get(token, 0) + 1
                    # diff snapshots: retract vanished rows, add new ones
                    for token, count in emitted.items():
                        delta = current.get(token, 0) - count
                        for _ in range(-delta if delta < 0 else 0):
                            self._emit(dict(zip(names, token)), diff=-1)
                    for token, count in current.items():
                        delta = count - emitted.get(token, 0)
                        for _ in range(delta if delta > 0 else 0):
                            self._emit(dict(zip(names, token)))
                    emitted = current
                if mode in ("static", "batch"):
                    break
                _time.sleep(refresh_interval_s)

    return py_read(
        _DeltaSubject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms
    )


def write(
    table: Table,
    uri: str,
    *,
    min_commit_frequency: int | None = 60_000,
    **kwargs: Any,
) -> None:
    deltalake = _require()
    import pyarrow as pa

    from pathway_tpu.io._utils import plain_row

    batch: list[dict] = []

    def flush() -> None:
        if not batch:
            return
        rows, batch[:] = list(batch), []
        deltalake.write_deltalake(uri, pa.Table.from_pylist(rows), mode="append")

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        batch.append({**plain_row(row), "time": time, "diff": 1 if is_addition else -1})
        if len(batch) >= 10_000:
            flush()

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=flush))
