"""PyFilesystem connector (parity: reference ``io/pyfilesystem`` — reads any fs.FS).

The ``fs`` package is optional; when absent this degrades to a clear error. Local
directories are served by ``pw.io.fs`` instead.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import schema as sch


def read(
    source: Any,
    *,
    path: str = "/",
    format: str = "binary",
    mode: str = "streaming",
    with_metadata: bool = False,
    refresh_interval: float = 30.0,
    **kwargs: Any,
) -> Any:
    """Read files from a PyFilesystem ``FS`` object (zip, tar, ftp, mem, …)."""
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    if not hasattr(source, "walk") or not hasattr(source, "readbytes"):
        raise TypeError("pw.io.pyfilesystem.read expects a PyFilesystem FS object")

    import time as _time

    schema = sch.schema_from_types(data=bytes, path=str)

    class _FsSubject(ConnectorSubject):
        def run(self) -> None:
            seen: dict[str, bytes] = {}
            while True:
                for file_path in source.walk.files(path):
                    data = source.readbytes(file_path)
                    if seen.get(file_path) == data:
                        continue
                    if file_path in seen:
                        self._emit({"data": seen[file_path], "path": file_path}, diff=-1)
                    self._emit({"data": data, "path": file_path})
                    seen[file_path] = data
                if mode in ("static", "batch"):
                    break
                _time.sleep(refresh_interval)

    return py_read(_FsSubject(), schema=schema)
