"""Logstash sink (parity: reference ``io/logstash`` — HTTP input plugin).

Posts one JSON document per update to a Logstash HTTP input endpoint via ``requests``.
"""

from __future__ import annotations

import json
from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._utils import plain_row
from pathway_tpu.internals.table import Table


def write(
    table: Table,
    endpoint: str,
    n_retries: int = 0,
    retry_policy: Any = None,
    connect_timeout_ms: int | None = None,
    request_timeout_ms: int | None = None,
    **kwargs: Any,
) -> None:
    import requests

    session = requests.Session()
    timeout = (request_timeout_ms or 10_000) / 1000.0

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        doc = {**plain_row(row), "time": time, "diff": 1 if is_addition else -1}
        last_error: Exception | None = None
        for _attempt in range(n_retries + 1):
            try:
                response = session.post(
                    endpoint,
                    data=json.dumps(doc).encode(),
                    headers={"Content-Type": "application/json"},
                    timeout=timeout,
                )
                response.raise_for_status()
                return
            except Exception as exc:  # retry per policy
                last_error = exc
        if last_error is not None:
            raise last_error

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=session.close))
