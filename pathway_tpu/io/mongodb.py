"""MongoDB sink (parity: reference ``io/mongodb`` over ``data_storage.rs:2232`` with the
Bson formatter ``data_format.rs:1975``). Requires pymongo."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._utils import plain_row
from pathway_tpu.internals.table import Table


def write(table: Table, connection_string: str, database: str, collection: str, **kwargs: Any) -> None:
    try:
        import pymongo
    except ImportError:
        raise ImportError("pymongo is not available in this environment")

    client = pymongo.MongoClient(connection_string)
    coll = client[database][collection]

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        coll.insert_one({**plain_row(row), "time": time, "diff": 1 if is_addition else -1})

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=client.close))
