"""MongoDB sink (parity: reference ``io/mongodb`` over ``data_storage.rs:2232`` with the
Bson formatter ``data_format.rs:1975``).

Real client code against the ``pymongo`` API: rows batch per commit and write with
``insert_many`` (the reference's Mongo writer batches documents per output batch).
Client construction is injectable (``_client``) so unit tests run against fakes in
environments without a server or client library.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._utils import add_batched_sink
from pathway_tpu.internals.table import Table


def write(
    table: Table,
    connection_string: str,
    database: str,
    collection: str,
    *,
    max_batch_size: int | None = None,
    _client: Any = None,
    **kwargs: Any,
) -> None:
    """Write ``table``'s update stream into a MongoDB collection.

    Each document carries the row's columns plus ``time``/``diff`` (reference Bson
    formatter fields, ``data_format.rs:1975``). ``_client``: any object with the
    pymongo ``client[db][coll].insert_many`` surface (tests inject fakes).
    """
    if _client is None:
        try:
            import pymongo
        except ImportError as exc:
            raise ImportError(
                "no MongoDB client library (pymongo) is available in this "
                "environment; pass _client=... (any object with the pymongo "
                "MongoClient surface)"
            ) from exc
        _client = pymongo.MongoClient(connection_string)
    coll = _client[database][collection]
    add_batched_sink(
        table,
        coll.insert_many,
        max_batch_size=int(max_batch_size or 1024),
        client=_client,
    )
