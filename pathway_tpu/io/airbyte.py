"""Airbyte connector (parity: reference ``io/airbyte`` + vendored airbyte_serverless).
Runs Airbyte sources via docker or a local venv; neither is available in this image, so
the surface degrades with a clear error."""

from __future__ import annotations

from typing import Any


def read(
    config_file_path: str,
    streams: list[str],
    *,
    mode: str = "streaming",
    execution_type: str = "local",
    env_vars: dict | None = None,
    refresh_interval_ms: int = 60_000,
    **kwargs: Any,
) -> Any:
    raise ImportError(
        "the Airbyte runtime (docker or airbyte-serverless) is not available in this "
        "environment; materialize the Airbyte stream to files and use pw.io.fs / "
        "pw.io.jsonlines, or feed records through pw.io.python.ConnectorSubject"
    )
