"""Airbyte source connector (parity: reference ``io/airbyte`` + vendored
``airbyte_serverless`` executor).

Real protocol code: the connector launches an Airbyte source (a local executable, or
a docker image when docker exists) and speaks the `Airbyte protocol
<https://docs.airbyte.com/understanding-airbyte/airbyte-protocol>`_ over its stdout —
``RECORD`` messages become rows of a ``data`` (Json) column, ``STATE`` messages
checkpoint into the engine's offset state so a restart resumes incrementally (the
reference folds STATE blobs the same way, ``io/airbyte/logic.py``). Process creation
is injectable (``_process_factory``) so unit tests drive the protocol with scripted
fakes in environments without docker or connector packages.
"""

from __future__ import annotations

import json
import os
import shlex
import tempfile
import time as time_mod
from typing import Any, Callable, Sequence

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _default_process_factory(cmd: list[str], env: dict | None) -> Any:
    import subprocess

    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,  # tail included in failure diagnostics
        env=full_env,
        text=True,
    )


def _load_source_config(path: str) -> dict:
    """Parse an airbyte-serverless style config file (YAML or JSON); returns the
    ``source`` section: {docker_image | executable, config: {...}}."""
    with open(path) as f:
        text = f.read()
    try:
        loaded = json.loads(text)
    except ValueError:
        import yaml

        loaded = yaml.safe_load(text)
    if not isinstance(loaded, dict):
        raise ValueError(f"airbyte config {path!r} must be a mapping")
    source = loaded.get("source", loaded)
    if not isinstance(source, dict):
        raise ValueError(f"airbyte config {path!r} has no usable 'source' section")
    return source


def _build_command(source_cfg: dict, config_path: str, catalog_path: str,
                   state_path: str | None, env_vars: dict | None = None) -> list[str]:
    tail = ["read", "--config", config_path, "--catalog", catalog_path]
    if state_path is not None:
        tail += ["--state", state_path]
    executable = source_cfg.get("executable")
    if executable:
        # env_vars reach a local executable via the process environment
        return shlex.split(str(executable)) + tail
    image = source_cfg.get("docker_image")
    if image:
        mount_dir = os.path.dirname(os.path.abspath(config_path))
        env_flags: list[str] = []
        for k in sorted(env_vars or {}):
            # forwarded INTO the container (the host-side docker CLI's
            # environment is invisible to the connector)
            env_flags += ["-e", k]
        return [
            "docker", "run", "--rm", "-i",
            *env_flags,
            "-v", f"{mount_dir}:{mount_dir}:ro",
            str(image),
        ] + tail
    raise ValueError(
        "airbyte source config needs an 'executable' (local command speaking the "
        "Airbyte protocol) or a 'docker_image'"
    )


class _AirbyteSubject:
    """Airbyte read-process loop -> engine events, with STATE checkpointing."""

    def __init__(
        self,
        process_factory: Callable[[list[str], dict | None], Any],
        source_cfg: dict,
        streams: Sequence[str],
        mode: str,
        refresh_interval_s: float,
        env_vars: dict | None,
    ):
        self.process_factory = process_factory
        self.source_cfg = source_cfg
        self.streams = set(streams)
        self.mode = mode
        self.refresh_interval_s = refresh_interval_s
        self.env_vars = env_vars
        self.state: Any = None  # latest Airbyte STATE payload
        self._stop = False

    # -- persistence hooks (engine folds markers like kafka offsets) ---------

    @staticmethod
    def fold_state_deltas(state_deltas: list) -> list:
        return state_deltas[-1:]  # only the latest STATE matters

    def restore(self, state_deltas: list) -> None:
        if state_deltas:
            self.state = state_deltas[-1]["state"]

    def stop(self) -> None:
        self._stop = True
        # a silent/hung connector never wakes the stdout loop; terminating the
        # child delivers EOF to the reader so _one_sync can unwind
        proc = getattr(self, "_proc", None)
        if proc is not None:
            for meth in ("terminate", "kill"):
                stop_fn = getattr(proc, meth, None)
                if stop_fn is not None:
                    try:
                        stop_fn()
                    except Exception:
                        pass
                    break

    # -- protocol loop -------------------------------------------------------

    def _one_sync(self, source: StreamingDataSource, workdir: str) -> None:
        config_path = os.path.join(workdir, "config.json")
        catalog_path = os.path.join(workdir, "catalog.json")
        with open(config_path, "w") as f:
            json.dump(self.source_cfg.get("config", {}), f)
        catalog = {
            "streams": [
                {
                    "stream": {
                        "name": s,
                        "json_schema": {},
                        "supported_sync_modes": ["full_refresh", "incremental"],
                    },
                    "sync_mode": "incremental",
                    "destination_sync_mode": "append",
                }
                for s in sorted(self.streams)
            ]
        }
        with open(catalog_path, "w") as f:
            json.dump(catalog, f)
        state_path = None
        if self.state is not None:
            state_path = os.path.join(workdir, "state.json")
            with open(state_path, "w") as f:
                json.dump(self.state, f)
        cmd = _build_command(
            self.source_cfg, config_path, catalog_path, state_path, self.env_vars
        )
        proc = self.process_factory(cmd, self.env_vars)
        self._proc = proc  # stop() terminates it so a silent child can't block shutdown
        # stderr drains on a side thread so a chatty source can't block on a full
        # pipe; its tail feeds failure diagnostics
        stderr_tail: list[str] = []
        stderr = getattr(proc, "stderr", None)
        if stderr is not None:
            import threading

            def _drain() -> None:
                for err_line in stderr:
                    stderr_tail.append(err_line)
                    del stderr_tail[:-50]

            threading.Thread(target=_drain, daemon=True).start()
        failed = False
        stopped = False
        try:
            for line in proc.stdout:
                if self._stop:
                    # shutdown requested mid-sync: a long or hung connector read
                    # must not block graph teardown indefinitely
                    stopped = True
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue  # connectors may emit free-form logs on stdout
                mtype = msg.get("type")
                if mtype == "RECORD":
                    record = msg.get("record") or {}
                    if record.get("stream") in self.streams:
                        source.push({"data": Json(record.get("data"))})
                elif mtype == "STATE":
                    self.state = msg.get("state")
                    # a STATE marker commits everything before it (at-least-once)
                    source.push_state({"state": self.state})
                elif mtype == "TRACE":
                    trace = msg.get("trace") or {}
                    if trace.get("type") == "ERROR":
                        err = (trace.get("error") or {}).get("message", "airbyte error")
                        failed = True
                        raise RuntimeError(f"airbyte source error: {err}")
                # LOG / CATALOG / CONNECTION_STATUS messages are ignored here
        finally:
            # stop() may have terminated the child while the read loop was
            # blocked: EOF ends the loop without executing its _stop check
            stopped = stopped or self._stop
            if failed or stopped:
                # stop reading mid-stream: kill the child or wait() deadlocks on
                # its blocked stdout writes (and a docker container would leak)
                for meth in ("terminate", "kill"):
                    stop = getattr(proc, meth, None)
                    if stop is not None:
                        stop()
                        break
            rc = proc.wait()
        if stopped:
            return
        if rc not in (0, None) and not failed:
            tail = "".join(stderr_tail[-10:]).strip()
            raise RuntimeError(
                f"airbyte source exited with code {rc}"
                + (f"; stderr tail:\n{tail}" if tail else "")
            )

    def run(self, source: StreamingDataSource) -> None:
        while True:
            with tempfile.TemporaryDirectory(prefix="pw-airbyte-") as workdir:
                self._one_sync(source, workdir)
            if self.mode != "streaming" or self._stop:
                return
            deadline = time_mod.monotonic() + self.refresh_interval_s
            while time_mod.monotonic() < deadline:
                if self._stop:
                    return
                time_mod.sleep(min(0.1, self.refresh_interval_s))


def read(
    config_file_path: os.PathLike | str,
    streams: Sequence[str],
    *,
    mode: str = "streaming",
    execution_type: str = "local",
    env_vars: dict[str, str] | None = None,
    refresh_interval_ms: int = 60_000,
    autocommit_duration_ms: int | None = 1500,
    name: str | None = None,
    _process_factory: Callable[[list[str], dict | None], Any] | None = None,
    **kwargs: Any,
) -> Table:
    """Run an Airbyte source and ingest its records (reference ``io/airbyte.read``).

    Returns a table with one ``data`` (Json) column per record, matching the
    reference's ``_AirbyteRecordSchema``. The config file is airbyte-serverless
    style; its ``source`` section must carry ``executable`` (a local command
    speaking the Airbyte protocol) or ``docker_image``.
    """
    if execution_type != "local":
        raise NotImplementedError(
            f"execution_type={execution_type!r}: only 'local' execution is "
            "supported (the reference's 'remote' type runs Google Cloud jobs)"
        )
    source_cfg = _load_source_config(os.fspath(config_file_path))
    from pathway_tpu.io.python import _NoopRunner, _runs_on_this_process

    subject: Any = _AirbyteSubject(
        _process_factory or _default_process_factory,
        source_cfg,
        list(streams),
        mode,
        refresh_interval_ms / 1000.0,
        env_vars,
    )
    if not _runs_on_this_process(subject):
        # one sync process per connection (reference parallel-reader placement);
        # peer processes receive rows through the exchange
        subject = _NoopRunner()
    schema = sch.schema_from_types(data=dt.JSON)
    source = StreamingDataSource(subject=subject, autocommit_ms=autocommit_duration_ms)
    node = G.add_node(
        pg.InputNode(source=source, streaming=mode == "streaming", name=name or "airbyte")
    )
    return Table(node, schema, name=name or "airbyte")
