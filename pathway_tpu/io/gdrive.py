"""Google Drive connector (parity: reference ``io/gdrive`` — 401 LoC pure-Python reader
polling Drive objects). Requires google-api-python-client; degrades with a clear error."""

from __future__ import annotations

from typing import Any


def read(
    object_id: str,
    *,
    mode: str = "streaming",
    object_size_limit: int | None = None,
    refresh_interval: int = 30,
    service_user_credentials_file: str,
    with_metadata: bool = False,
    file_name_pattern: str | list | None = None,
    **kwargs: Any,
) -> Any:
    try:
        from googleapiclient.discovery import build  # noqa: F401
        from google.oauth2.service_account import Credentials
    except ImportError as exc:
        raise ImportError(
            "google-api-python-client is not available in this environment; "
            "sync the Drive folder to disk and use pw.io.fs.read instead"
        ) from exc

    from pathway_tpu.internals import schema as sch
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    import time as _time

    credentials = Credentials.from_service_account_file(
        service_user_credentials_file, scopes=["https://www.googleapis.com/auth/drive.readonly"]
    )
    service = build("drive", "v3", credentials=credentials)
    schema = sch.schema_from_types(data=bytes)

    class _DriveSubject(ConnectorSubject):
        def run(self) -> None:
            seen: dict[str, str] = {}
            emitted: dict[str, bytes] = {}
            while True:
                query = f"'{object_id}' in parents and trashed=false"
                files: list[dict] = []
                page_token = None
                while True:
                    listing = (
                        service.files()
                        .list(
                            q=query,
                            fields="nextPageToken, files(id,name,version,size)",
                            pageToken=page_token,
                        )
                        .execute()
                    )
                    files.extend(listing.get("files", []))
                    page_token = listing.get("nextPageToken")
                    if not page_token:
                        break
                for f in files:
                    if object_size_limit and int(f.get("size", 0)) > object_size_limit:
                        continue
                    version = f.get("version", "")
                    if seen.get(f["id"]) == version:
                        continue
                    blob = service.files().get_media(fileId=f["id"]).execute()
                    if f["id"] in emitted:
                        self._emit({"data": emitted[f["id"]]}, diff=-1)
                    self._emit({"data": blob})
                    seen[f["id"]] = version
                    emitted[f["id"]] = blob
                if mode in ("static", "batch"):
                    break
                _time.sleep(refresh_interval)

    return py_read(_DriveSubject(), schema=schema)
