"""Cross-graph table handoff (parity: reference ``trait ExportedTable``
``src/engine/graph.rs:630`` + ``src/engine/dataflow/export.rs``; Python side
``internals/datasource.py:105`` ``ImportDataSource``).

``export_table`` attaches a live handle to a table of one dataflow graph;
``import_table`` mounts that handle as a streaming source of ANOTHER graph —
the importing graph first receives the exported table's current snapshot, then
every subsequent update, with original row keys preserved. The exporting and
importing graphs typically run on different threads (the reference's
interactive LiveTable pattern: one long-running background graph feeding
short-lived foreground graphs).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G


class ExportedTable:
    """Live handle over an exported table: frontier + snapshot + subscriptions
    (reference ``ExportedTable``: ``frontier()``, ``snapshot_at()``, callbacks)."""

    def __init__(self, column_names: List[str], schema: Any):
        self.column_names = list(column_names)
        self.schema = schema
        # reentrant: listeners run under this lock and may call back into the
        # public API (frontier/failed/subscribe, and snapshot_at of an
        # already-reached frontier). A snapshot_at that would have to WAIT from
        # inside a listener raises instead (the listener runs on the only
        # producing thread — waiting there could never be satisfied).
        self._lock = threading.RLock()
        self._advanced = threading.Condition(self._lock)
        self._dispatching: int | None = None  # thread id during listener dispatch
        self._rows: Dict[bytes, tuple] = {}  # kb -> (Pointer, row dict)
        self._frontier = -1
        self._closed = False
        self._failed: Optional[BaseException] = None
        self._listeners: List[Callable] = []

    # -- exporting-graph side ------------------------------------------------

    def _on_batch(self, keys: Any, diffs: Any, columns: Dict[str, Any], time: int) -> None:
        from pathway_tpu.internals.keys import key_bytes, keys_to_pointers

        ptrs = keys_to_pointers(keys)
        kbs = key_bytes(keys)
        rows = [
            {c: columns[c][i] for c in self.column_names} for i in range(len(ptrs))
        ]
        dlist = [int(d) for d in diffs]
        # listeners are invoked UNDER the export lock: a concurrent subscribe()
        # then cannot observe a batch before (or interleaved with) its snapshot
        # delivery, and listeners never see two batches concurrently. Iterating
        # a COPY keeps a listener subscribed from inside this dispatch (it got
        # a snapshot that already includes this batch) from hearing the batch
        # a second time.
        with self._advanced:
            for kb, ptr, row, d in zip(kbs, ptrs, rows, dlist):
                if d > 0:
                    self._rows[kb] = (ptr, row)
                else:
                    self._rows.pop(kb, None)
            self._frontier = time
            self._advanced.notify_all()
            self._dispatching = threading.get_ident()
            try:
                for listener in list(self._listeners):
                    listener(list(zip(ptrs, rows, dlist)), time)
            finally:
                self._dispatching = None

    def _close(self) -> None:
        with self._advanced:
            if self._closed:
                return
            self._closed = True
            self._advanced.notify_all()
            self._dispatching = threading.get_ident()
            try:
                for listener in list(self._listeners):
                    listener(None, self._frontier)  # None batch = stream end
            finally:
                self._dispatching = None

    def _fail(self, exc: BaseException) -> None:
        with self._advanced:
            self._failed = exc
        self._close()

    # -- importing-graph / user side -----------------------------------------

    def frontier(self) -> int:
        with self._lock:
            return self._frontier

    def failed(self) -> bool:
        with self._lock:
            return self._failed is not None

    def snapshot_at(self, frontier: int | None = None, timeout: float | None = None) -> list:
        """(Pointer, row) pairs once the export has advanced to ``frontier``
        (reference ``snapshot_at``); None waits for whatever is current.
        Raises when the exporting graph failed, or closed before reaching the
        requested frontier — a crashed export must not read as a small table."""
        with self._advanced:
            if frontier is not None:
                need_wait = self._frontier < frontier and not self._closed
                if need_wait and self._dispatching == threading.get_ident():
                    raise RuntimeError(
                        "snapshot_at of a future frontier called from inside an "
                        "ExportedTable listener would deadlock the exporting "
                        "thread; listeners may only snapshot frontiers already "
                        "reached"
                    )
                ok = self._advanced.wait_for(
                    lambda: self._frontier >= frontier or self._closed,
                    timeout=timeout,
                )
                if not ok:
                    raise TimeoutError(
                        f"exported table did not reach frontier {frontier}"
                    )
            if self._failed is not None:
                raise RuntimeError("exporting graph failed") from self._failed
            if frontier is not None and self._frontier < frontier:
                raise RuntimeError(
                    f"export closed at frontier {self._frontier} before "
                    f"reaching {frontier}"
                )
            return [(ptr, dict(row)) for ptr, row in self._rows.values()]

    def subscribe(self, listener: Callable) -> None:
        """Register ``listener(events, time)`` — called with the CURRENT snapshot
        first (as inserts), then with every subsequent update batch; a ``None``
        events value signals stream end. Snapshot delivery, registration, and
        every later batch delivery all happen under the export lock, so the
        listener can never see a batch before (or interleaved with) its
        snapshot."""
        with self._advanced:
            snapshot = [
                (ptr, dict(row), 1) for ptr, row in self._rows.values()
            ]
            if snapshot:
                listener(snapshot, self._frontier)
            if self._closed:
                listener(None, self._frontier)
            else:
                self._listeners.append(listener)

    def unsubscribe(self, listener: Callable) -> None:
        with self._advanced:
            try:
                self._listeners.remove(listener)
            except ValueError:
                pass


def export_table(table: Any) -> ExportedTable:
    """Attach a live export handle to ``table`` (reference ``Graph::export_table``)."""
    exported = ExportedTable(table.column_names(), table._schema)
    G.add_node(
        pg.OutputNode(
            inputs=[table],
            batch_callback=exported._on_batch,
            on_end=exported._close,
            on_error=exported._fail,
        )
    )
    return exported


class _ImportSubject:
    """Streams an ExportedTable into a fresh graph, original keys preserved."""

    def __init__(self, exported: ExportedTable):
        self.exported = exported
        self._done = threading.Event()
        self._listener: Any = None

    def run(self, source: Any) -> None:
        def listener(events: Any, time: int) -> None:
            if events is None:
                self._done.set()
                return
            if self._done.is_set():
                return  # stopped importer: drop late batches instead of pushing
            for ptr, row, diff in events:
                source.push(dict(row), key=ptr, diff=diff)

        self._listener = listener
        self.exported.subscribe(listener)
        self._done.wait()
        if self.exported.failed():
            raise RuntimeError("exporting graph failed") from self.exported._failed

    def stop(self) -> None:
        """Graceful-shutdown hook (``GraphRunner.finish``): without it the import
        thread parks forever on ``_done.wait()`` whenever the exporting graph
        never closes."""
        self._done.set()
        if self._listener is not None:
            self.exported.unsubscribe(self._listener)


def import_table(exported: ExportedTable, *, autocommit_duration_ms: int | None = 50) -> Any:
    """Mount an :class:`ExportedTable` as a source of the CURRENT graph
    (reference ``Scope::import_table``, ``operator_handler.py:155``)."""
    from pathway_tpu.engine.datasource import StreamingDataSource
    from pathway_tpu.internals.table import Table

    source = StreamingDataSource(
        subject=_ImportSubject(exported), autocommit_ms=autocommit_duration_ms
    )
    node = G.add_node(pg.InputNode(source=source, streaming=True, name="import"))
    return Table(node, exported.schema, name="import")
