"""Kafka connector (parity: reference ``io/kafka`` over ``data_storage.rs:692``).

The execution image has no Kafka client library; the connector raises a clear error at call
time. ``read_from_iterable`` offers the same Table surface fed from any message iterator, which
is what the streaming benchmarks use.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

from pathway_tpu.internals import schema as sch


def _no_client() -> None:
    raise ImportError(
        "no Kafka client library (confluent_kafka / kafka-python) is available in this "
        "environment; use pw.io.kafka.read_from_iterable(...) or pw.io.python.read(...) "
        "to feed messages from your own consumer"
    )


def read(
    rdkafka_settings: dict,
    topic: str | None = None,
    *,
    schema: Any = None,
    format: str = "raw",
    autocommit_duration_ms: int | None = 1500,
    **kwargs: Any,
) -> Any:
    try:
        import confluent_kafka  # noqa: F401
    except ImportError:
        _no_client()


def write(table: Any, rdkafka_settings: dict, topic_name: str | None = None, **kwargs: Any) -> None:
    try:
        import confluent_kafka  # noqa: F401
    except ImportError:
        _no_client()


def read_from_iterable(
    messages: Iterable[bytes | str | dict],
    *,
    schema: Any = None,
    format: str = "json",
    autocommit_duration_ms: int | None = 100,
) -> Any:
    """Feed a Kafka-shaped message stream from any iterable (tests/benchmarks)."""
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    if schema is None:
        schema = sch.schema_from_types(data=str)

    class _IterSubject(ConnectorSubject):
        def run(self) -> None:
            for msg in messages:
                if isinstance(msg, dict):
                    self.next(**msg)
                elif format == "json":
                    rec = json.loads(msg)
                    self.next(**{k: rec.get(k) for k in schema.column_names()})
                else:
                    self.next(data=msg if isinstance(msg, str) else msg.decode())

    return py_read(_IterSubject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms)
