"""Kafka connector (parity: reference ``io/kafka`` over the Rust reader/writer at
``src/connectors/data_storage.rs:692`` (KafkaReader) and ``:1258`` (KafkaWriter)).

Real client code against the ``confluent_kafka`` API: the reader owns a consumer,
seeks restored offsets, polls message batches into the engine's streaming source
(offsets checkpoint in-band as segment state so persistence resumes exactly), and
commits consumer offsets after the engine accepted each batch (at-least-once). The
writer formats each output batch (json/dsv/raw, with the reference's ``diff``/``time``
fields) and produces per commit. Client construction is injectable
(``_consumer_factory``/``_producer_factory``) so unit tests run against fakes in
environments without a broker or client library.
"""

from __future__ import annotations

import json
import time as time_mod
from typing import Any, Callable, Iterable

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import pointer_from
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _default_consumer_factory(settings: dict) -> Any:
    try:
        from confluent_kafka import Consumer
    except ImportError as exc:
        raise ImportError(
            "no Kafka client library (confluent_kafka) is available in this "
            "environment; pass _consumer_factory=... (any object with the "
            "confluent_kafka.Consumer poll/assign/commit surface), or use "
            "pw.io.kafka.read_from_iterable(...)"
        ) from exc
    return Consumer(settings)


def _default_producer_factory(settings: dict) -> Any:
    try:
        from confluent_kafka import Producer
    except ImportError as exc:
        raise ImportError(
            "no Kafka client library (confluent_kafka) is available in this "
            "environment; pass _producer_factory=... (any object with the "
            "confluent_kafka.Producer produce/poll/flush surface)"
        ) from exc
    return Producer(settings)


class _KafkaSubject:
    """Consumer loop -> engine events, with per-batch offset segments.

    Mirrors the reference ``KafkaReader``: one consumer per connector, messages
    parsed by wire format, positions exposed as ``OffsetValue``-style state
    (``src/connectors/offset.rs:37``) through the in-band segment markers.
    """

    def __init__(
        self,
        consumer_factory: Callable[[dict], Any],
        settings: dict,
        topics: list[str],
        format: str,
        schema: sch.SchemaMetaclass | None,
        with_metadata: bool,
        poll_timeout_s: float = 0.2,
        commit_every_s: float = 1.5,
        mode: str = "streaming",
    ):
        self.consumer_factory = consumer_factory
        self.settings = dict(settings)
        self.topics = topics
        self.format = format
        self.schema = schema
        self.with_metadata = with_metadata
        self.poll_timeout_s = poll_timeout_s
        self.commit_every_s = commit_every_s
        self.mode = mode
        # (topic, partition) -> NEXT offset to consume (restored from checkpoints)
        self.offsets: dict[tuple[str, int], int] = {}

    # -- persistence hooks ----------------------------------------------------

    @staticmethod
    def fold_state_deltas(state_deltas: list) -> list:
        latest: dict[tuple[str, int], dict] = {}
        for delta in state_deltas:
            latest[(delta["topic"], delta["partition"])] = delta
        return [latest[k] for k in sorted(latest)]

    def restore(self, state_deltas: list) -> None:
        for delta in state_deltas:
            self.offsets[(delta["topic"], delta["partition"])] = delta["next_offset"]

    # -- message decoding -------------------------------------------------------

    def _decode(self, msg: Any) -> dict | None:
        value = msg.value()
        if value is None:
            return None
        if self.format in ("raw", "binary"):
            row: dict = {"data": value}
        elif self.format == "plaintext":
            row = {"data": value.decode("utf-8", "replace")}
        elif self.format == "json":
            rec = json.loads(value)
            dtypes = self.schema.dtypes() if self.schema else {k: dt.ANY for k in rec}
            row = {}
            for name, dtype in dtypes.items():
                v = rec.get(name)
                if dtype.strip_optional() == dt.JSON and v is not None:
                    v = Json(v)
                row[name] = v
        else:
            raise ValueError(f"unknown kafka format {self.format!r}")
        if self.with_metadata:
            key = msg.key()
            row["_metadata"] = Json(
                {
                    "topic": msg.topic(),
                    "partition": msg.partition(),
                    "offset": msg.offset(),
                    "key": key.decode("utf-8", "replace") if key else None,
                }
            )
        return row

    def _decode_events(self, msg: Any) -> list:
        """(row, diff, key) events for one message; subclasses override for wire
        formats carrying their own change semantics (Debezium envelopes)."""
        row = self._decode(msg)
        if row is None:
            return []
        key = pointer_from(msg.topic(), msg.partition(), msg.offset(), "kafka")
        return [(row, 1, key)]

    def _marker_extra(self) -> dict:
        """Extra resumable state to ride the next offset marker (subclass hook)."""
        return {}

    # -- consumer loop ------------------------------------------------------------

    def run(self, source: StreamingDataSource) -> None:
        settings = dict(self.settings)
        if self.mode in ("static", "batch"):
            # static termination relies on per-partition EOF events (librdkafka
            # default is off)
            settings.setdefault("enable.partition.eof", True)
        consumer = self.consumer_factory(settings)
        restored = dict(self.offsets)

        def on_assign(cons: Any, partitions: list) -> None:
            # resume checkpointed positions WITHOUT dropping partitions that had
            # no messages before the checkpoint (reference KafkaReader::seek)
            for p in partitions:
                off = restored.get((p.topic, p.partition))
                if off is not None:
                    p.offset = off
            cons.assign(partitions)

        try:
            consumer.subscribe(list(self.topics), on_assign=on_assign)
        except TypeError:
            # simple fakes/clients without rebalance callbacks
            consumer.subscribe(list(self.topics))
            if restored:
                try:
                    from confluent_kafka import TopicPartition

                    consumer.assign(
                        [TopicPartition(t, p, off) for (t, p), off in restored.items()]
                    )
                except ImportError:
                    consumer.assign([(t, p, off) for (t, p), off in restored.items()])
        eof_partitions: set[tuple[str, int]] = set()
        last_commit = time_mod.monotonic()
        dirty: dict[tuple[str, int], int] = {}  # offsets advanced since last marker

        def flush_markers() -> None:
            # offset markers ride in-band AFTER the rows they cover, one per
            # touched partition per batch (a marker ends the engine batch, so
            # they flush at batch boundaries, not per message). Subclasses may
            # piggyback extra resumable state on the first marker of a batch
            # (the Debezium upsert cache).
            extra = self._marker_extra()
            for (t, p), off in sorted(dirty.items()):
                marker = {"topic": t, "partition": p, "next_offset": off}
                if extra:
                    marker.update(extra)
                    extra = {}
                source.push_state(marker)
            dirty.clear()

        def all_partitions_eof() -> bool:
            # static mode finishes only once EVERY assigned partition reported
            # EOF (a partial set would drop the slower partitions' tail)
            if not eof_partitions:
                return False
            assigned = getattr(consumer, "assignment", lambda: None)()
            if assigned is None:
                return True  # client can't report assignment; best effort
            return len(eof_partitions) >= len(assigned)

        try:
            while True:
                msg = consumer.poll(self.poll_timeout_s)
                if msg is None:
                    if dirty:
                        flush_markers()
                    if self.mode in ("static", "batch") and all_partitions_eof():
                        break
                    continue
                err = msg.error()
                if err is not None:
                    if getattr(err, "code", lambda: None)() == _partition_eof_code():
                        eof_partitions.add((msg.topic(), msg.partition()))
                        if self.mode in ("static", "batch") and all_partitions_eof():
                            break
                        continue
                    raise RuntimeError(f"kafka consumer error: {err}")
                events = self._decode_events(msg)
                tp = (msg.topic(), msg.partition())
                next_offset = msg.offset() + 1
                self.offsets[tp] = next_offset
                dirty[tp] = next_offset
                for row, diff, key in events:
                    source.push(row, key=key, diff=diff)
                now = time_mod.monotonic()
                if now - last_commit >= self.commit_every_s:
                    last_commit = now
                    flush_markers()
                    try:
                        consumer.commit(asynchronous=True)
                    except Exception:
                        pass  # commit is an optimization; checkpoints own resume
        finally:
            flush_markers()
            try:
                consumer.commit(asynchronous=False)
            except Exception:
                pass
            consumer.close()


def _partition_eof_code() -> Any:
    try:
        from confluent_kafka import KafkaError

        return KafkaError._PARTITION_EOF
    except ImportError:
        return "_PARTITION_EOF"


def read(
    rdkafka_settings: dict,
    topic: str | None = None,
    *,
    schema: sch.SchemaMetaclass | None = None,
    format: str = "raw",
    autocommit_duration_ms: int | None = 1500,
    topic_names: list[str] | None = None,
    with_metadata: bool = False,
    mode: str = "streaming",
    name: str | None = None,
    _consumer_factory: Callable[[dict], Any] | None = None,
    **kwargs: Any,
) -> Table:
    """Consume ``topic`` into a Table (reference ``io/kafka.read``)."""
    topics = [topic] if topic else list(topic_names or [])
    if not topics:
        raise ValueError("kafka.read requires a topic (or topic_names)")
    from pathway_tpu.internals.config import get_pathway_config

    if get_pathway_config().processes > 1 and "group.id" not in rdkafka_settings:
        # parallel read correctness rides Kafka consumer groups: same group ->
        # the broker assigns DISJOINT partitions per process (the reference's
        # parallel_readers split); without one every process would re-consume
        # the full topic
        raise ValueError(
            "multi-process kafka.read requires rdkafka_settings['group.id'] so "
            "the broker splits partitions across the spawned processes"
        )
    if _consumer_factory is None:
        # fail at call time, not inside the connector thread
        try:
            import confluent_kafka  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "no Kafka client library (confluent_kafka) is available in this "
                "environment; pass _consumer_factory=... or use "
                "pw.io.kafka.read_from_iterable(...)"
            ) from exc
    if schema is None:
        if format in ("raw", "binary"):
            schema = sch.schema_from_types(data=bytes)
        elif format == "plaintext":
            schema = sch.schema_from_types(data=str)
        else:
            raise ValueError(f"schema is required for format {format!r}")
    out_schema = schema
    if with_metadata:
        out_schema = sch.schema_from_columns(
            {**schema.columns(), "_metadata": sch.ColumnSchema("_metadata", dt.JSON)},
            name="kafka",
        )
    subject = _KafkaSubject(
        _consumer_factory or _default_consumer_factory,
        rdkafka_settings,
        topics,
        format,
        schema,
        with_metadata,
        mode=mode,
    )
    source = StreamingDataSource(subject=subject, autocommit_ms=autocommit_duration_ms)
    node = G.add_node(
        pg.InputNode(source=source, streaming=mode == "streaming", name=name or "kafka")
    )
    return Table(node, out_schema, name=name or "kafka")


def write(
    table: Table,
    rdkafka_settings: dict,
    topic_name: str | None = None,
    *,
    format: str = "json",
    key: Any = None,
    delimiter: str = ",",
    name: str | None = None,
    _producer_factory: Callable[[dict], Any] | None = None,
    **kwargs: Any,
) -> None:
    """Produce the table's update stream to ``topic_name`` (reference KafkaWriter:
    one message per row update, json payloads carrying ``diff`` and ``time``)."""
    if topic_name is None:
        raise ValueError("kafka.write requires topic_name")
    if _producer_factory is None:
        try:
            import confluent_kafka  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "no Kafka client library (confluent_kafka) is available in this "
                "environment; pass _producer_factory=..."
            ) from exc
    factory = _producer_factory or _default_producer_factory
    producer_box: list = [None]
    key_name = key.name if hasattr(key, "name") else key
    columns = table.column_names()

    def _producer() -> Any:
        if producer_box[0] is None:
            producer_box[0] = factory(rdkafka_settings)
        return producer_box[0]

    def batch_callback(keys: Any, diffs: Any, cols: dict, time: int) -> None:
        producer = _producer()
        n = len(keys)
        from pathway_tpu.io._utils import columns_to_pylists

        col_lists = columns_to_pylists(cols, columns)
        for i in range(n):
            row = {c: col_lists[c][i] for c in columns}
            msg_key = None
            if key_name is not None:
                msg_key = str(row.get(key_name, "")).encode()
            if format == "json":
                payload = json.dumps(
                    {**_jsonable(row), "diff": int(diffs[i]), "time": int(time)}
                ).encode()
            elif format in ("dsv", "csv"):
                payload = delimiter.join(str(row[c]) for c in columns).encode()
            elif format in ("raw", "plaintext"):
                data = row.get("data", "")
                payload = data if isinstance(data, bytes) else str(data).encode()
            else:
                raise ValueError(f"unknown kafka write format {format!r}")
            producer.produce(topic_name, value=payload, key=msg_key)
        producer.poll(0)

    def on_end() -> None:
        if producer_box[0] is not None:
            producer_box[0].flush()

    G.add_node(
        pg.OutputNode(inputs=[table], batch_callback=batch_callback, on_end=on_end)
    )


def _jsonable(row: dict) -> dict:
    out = {}
    for k, v in row.items():
        if isinstance(v, Json):
            out[k] = v.value
        elif isinstance(v, bytes):
            out[k] = v.decode("utf-8", "replace")
        else:
            out[k] = v
    return out


def read_from_iterable(
    messages: Iterable[bytes | str | dict],
    *,
    schema: Any = None,
    format: str = "json",
    autocommit_duration_ms: int | None = 100,
) -> Any:
    """Feed a Kafka-shaped message stream from any iterable (tests/benchmarks)."""
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    if schema is None:
        schema = sch.schema_from_types(data=str)

    class _IterSubject(ConnectorSubject):
        def run(self) -> None:
            for msg in messages:
                if isinstance(msg, dict):
                    self.next(**msg)
                elif format == "json":
                    rec = json.loads(msg)
                    self.next(**{k: rec.get(k) for k in schema.column_names()})
                else:
                    self.next(data=msg if isinstance(msg, str) else msg.decode())

    return py_read(_IterSubject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms)
