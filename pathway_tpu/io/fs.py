"""Filesystem connector (parity: reference ``io/fs`` + ``src/connectors/scanner/filesystem.rs``).

Supports static and streaming modes over csv / json(lines) / plaintext / binary formats, with
the ``_metadata`` column like the reference's metadata support (``src/connectors/metadata.rs``).
"""

from __future__ import annotations

import csv as _csv
import glob
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import pointer_from
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _coerce(value: str, dtype: dt.DType) -> Any:
    """Parse a raw CSV field per schema dtype; malformed fields poison the cell with
    ``Error`` (reference ``Value::Error`` semantics, ``data_format.rs`` Dsv parser) so bad
    input stays distinguishable from a genuine null."""
    from pathway_tpu.engine.columnar import ERROR

    base = dtype.strip_optional()
    if value is None:
        return None
    try:
        if base == dt.INT:
            return int(value)
        if base == dt.FLOAT:
            return float(value)
        if base == dt.BOOL:
            if value in ("true", "True", "1"):
                return True
            if value in ("false", "False", "0"):
                return False
            return ERROR
        if base == dt.JSON:
            return Json.parse(value)
    except (ValueError, TypeError):
        return ERROR
    return value


def _parse_dsv_bytes_native(
    data: bytes, delimiter: str, dtypes: Dict[str, dt.DType], has_schema: bool
) -> List[dict] | None:
    """Fused native CSV parse (split + coercion + row dicts in C++); None → fallback.

    Mirrors the reference's native Dsv parser (``data_format.rs:500``): typed coercion
    happens inside the parser, malformed fields poison cells with ``Error``. JSON-typed
    columns are post-coerced in Python (rare). THE single native-DSV dispatch — every
    connector (fs, s3, kafka) parses through here so semantics cannot drift."""
    from pathway_tpu import native
    from pathway_tpu.engine.columnar import ERROR

    # without a schema the wanted-column set is the header itself, which only the
    # DictReader fallback computes naturally
    if not has_schema or native.get_lib() is None or len(delimiter.encode()) != 1:
        return None
    _TAGS = {dt.INT: 1, dt.FLOAT: 2, dt.BOOL: 3}
    selected = []
    json_cols = []
    for name, dtype in dtypes.items():
        base = dtype.strip_optional()
        selected.append((name, _TAGS.get(base, 0)))
        if base == dt.JSON:
            json_cols.append(name)
    rows = native.parse_dsv_rows(data, selected, delimiter, ERROR)
    if rows is None:
        return None
    for name in json_cols:
        for row in rows:
            v = row.get(name)
            if isinstance(v, str):
                try:
                    row[name] = Json.parse(v)
                except Exception:
                    row[name] = ERROR
    return rows


def _parse_csv_native(
    filepath: str, delimiter: str, dtypes: Dict[str, dt.DType], has_schema: bool
) -> List[dict] | None:
    if not has_schema:
        return None
    with open(filepath, "rb") as f:
        data = f.read()
    return _parse_dsv_bytes_native(data, delimiter, dtypes, has_schema)


def _iter_files(path: str, object_pattern: str = "*") -> List[str]:
    p = Path(path)
    if p.is_dir():
        return sorted(str(f) for f in p.rglob(object_pattern) if f.is_file())
    return sorted(glob.glob(path)) or ([str(p)] if p.exists() else [])


def _metadata_for(filepath: str) -> Json:
    st = os.stat(filepath)
    return Json(
        {
            "path": str(Path(filepath).resolve()),
            "size": st.st_size,
            "seen_at": int(time.time()),
            "modified_at": int(st.st_mtime),
            "owner": str(st.st_uid),
        }
    )


def parse_bytes(
    data: bytes,
    format: str,
    schema: sch.SchemaMetaclass | None,
    csv_settings: Any = None,
) -> List[dict]:
    """Wire-format bytes -> row dicts (reference ``data_format.rs`` parsers);
    shared by every object/message connector (fs, s3, kafka)."""
    rows: List[dict] = []
    if format == "plaintext_by_file":
        rows.append({"data": data.decode("utf-8", "replace")})
    elif format == "plaintext":
        text = data.decode("utf-8", "replace")
        for line in text.splitlines():
            rows.append({"data": line})
    elif format in ("binary", "raw"):
        rows.append({"data": data})
    elif format == "csv":
        delimiter = getattr(csv_settings, "delimiter", ",") if csv_settings else ","
        dtypes = schema.dtypes() if schema else {}
        native_rows = _parse_dsv_bytes_native(data, delimiter, dtypes, bool(schema))
        if native_rows is not None:
            rows.extend(native_rows)
        else:
            import io as _io

            reader = _csv.DictReader(
                _io.StringIO(data.decode("utf-8", "replace")), delimiter=delimiter
            )
            for rec in reader:
                rows.append(
                    {
                        k: _coerce(v, dtypes.get(k, dt.STR))
                        for k, v in rec.items()
                        if k in dtypes or not schema
                    }
                )
    elif format in ("json", "jsonlines"):
        dtypes = schema.dtypes() if schema else {}
        for line in data.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            row = {}
            for name, dtype in (dtypes or {k: dt.ANY for k in rec}).items():
                v = rec.get(name)
                if dtype.strip_optional() == dt.JSON and v is not None:
                    v = Json(v)
                row[name] = v
            rows.append(row)
    else:
        raise ValueError(f"unknown format {format!r}")
    return rows


def _parse_file(
    filepath: str,
    format: str,
    schema: sch.SchemaMetaclass | None,
    with_metadata: bool,
    csv_settings: Any = None,
) -> List[dict]:
    rows: List[dict] = []
    if format == "csv":
        # native fused path reads the file itself (mmap-friendly)
        delimiter = getattr(csv_settings, "delimiter", ",") if csv_settings else ","
        dtypes = schema.dtypes() if schema else {}
        native_rows = _parse_csv_native(filepath, delimiter, dtypes, bool(schema))
        if native_rows is not None:
            rows.extend(native_rows)
        else:
            with open(filepath, "rb") as f:
                rows.extend(parse_bytes(f.read(), format, schema, csv_settings))
    else:
        with open(filepath, "rb") as f:
            rows.extend(parse_bytes(f.read(), format, schema, csv_settings))
    if with_metadata:
        meta = _metadata_for(filepath)
        for row in rows:
            row["_metadata"] = meta
    return rows


class _FsSubject:
    def __init__(
        self,
        path: str,
        format: str,
        schema: sch.SchemaMetaclass | None,
        mode: str,
        with_metadata: bool,
        object_pattern: str,
        refresh_interval: float = 0.5,
        csv_settings: Any = None,
    ):
        self.path = path
        self.format = format
        self.schema = schema
        self.mode = mode
        self.with_metadata = with_metadata
        self.object_pattern = object_pattern
        self.refresh_interval = refresh_interval
        self.csv_settings = csv_settings
        self.seen: Dict[str, float] = {}
        self.emitted: Dict[str, List[dict]] = {}
        # (file, mtime) -> rows AS PUSHED for that exact version (shared list
        # refs with emitted — no copy). Checkpoint hydration must pair a
        # drained marker with ITS OWN version's rows, never with whatever the
        # scanner has since re-read: the engine may checkpoint while the
        # scanner is a version ahead. Last two versions per file are kept
        # (the drained history can trail the scanner by at most one segment).
        self._pushed: Dict[tuple, List[dict]] = {}
        # elastic membership: file ownership is hash(path) mod n, so a
        # grow/shrink re-partitions the scan. The engine freezes the scanner
        # at a file boundary, exports/removes moved entries under the lock,
        # and bumps the generation so an interrupted pass abandons its stale
        # ownership filter instead of re-ingesting or retracting moved files.
        self._reshard_lock = threading.Lock()
        self._reshard_gen = 0
        self._freeze = threading.Event()
        self._idle = threading.Event()

    # -- persistence: the scanner's seen/emitted maps are the analogue of the
    # reference's cached_object_storage (replay without re-reading unchanged files).
    # Each file's completion is checkpointed *in-band* as a per-file state DELTA
    # (push_state after that file's events), bracketed by push_begin markers carrying a
    # (mtime, size) fingerprint — so the engine can dedup a crash-straddled file's
    # re-push when the file is unchanged, and retract its journaled partial rows when
    # it changed or vanished while the pipeline was down.

    @staticmethod
    def fold_state_deltas(state_deltas: list) -> list:
        """Collapse a marker-delta history to one delta per live file (bounds the
        checkpoint payload; called on the engine thread over drained markers only)."""
        seen: Dict[str, dict] = {}
        for delta in state_deltas:
            if delta.get("deleted"):
                seen.pop(delta["file"], None)
            else:
                seen[delta["file"]] = delta
        return [seen[f] for f in sorted(seen)]

    def restore(self, state_deltas: list) -> None:
        """Fold journaled per-file deltas back into the scan state (called before the
        scanner thread starts). Deltas arrive WITH rows: checkpoint/fragment
        exports carry them directly, journal-frame markers are rehydrated from
        their frames' input deltas by the runner before reaching here."""
        for delta in state_deltas:
            filepath = delta["file"]
            if delta.get("deleted"):
                self.seen.pop(filepath, None)
                self.emitted.pop(filepath, None)
            else:
                if "rows" not in delta:
                    raise ValueError(
                        f"fs scan-state delta for {filepath!r} reached restore "
                        "without rows: the journal frame that carried it lost "
                        "its input deltas (corrupt journal) — clear the "
                        "persistence directory to start fresh"
                    )
                self.seen[filepath] = delta["mtime"]
                self.emitted[filepath] = list(delta["rows"])
                self._pushed[(filepath, delta["mtime"])] = self.emitted[filepath]

    def hydrate_state_deltas(self, state_deltas: list) -> list:
        """Attach row payloads for the checkpoint export (journal frames ≤
        the checkpoint get compacted away, so the blob must be
        self-contained). Rows come from the VERSION-EXACT push record — a
        drained marker must pair with its own version's rows even when the
        scanner has already re-read the file (the engine may checkpoint one
        segment behind)."""
        out = []
        for delta in state_deltas:
            if delta.get("deleted") or "rows" in delta:
                out.append(delta)
                continue
            rows = self._pushed.get((delta["file"], delta["mtime"]))
            if rows is None:
                # fallback: the live rows, valid only when the versions agree
                # (a miss here means the marker trails by >1 version — the
                # next drained marker supersedes it at the following fold)
                rows = self.emitted.get(delta["file"], [])
            out.append({**delta, "rows": list(rows)})
        return out

    @staticmethod
    def rehydrate_state_deltas(state_deltas: list, row_values: dict) -> list:
        """Re-derive the marker rows of journaled deltas from their frames'
        input deltas (``row_values``: row-key bytes -> values dict, built by
        the runner over the frames up to each marker). Row keys are
        content-addressed ``(file, index)``, so the lookup is exact."""
        from pathway_tpu.internals.keys import pointers_to_keys

        out = []
        for delta in state_deltas:
            if delta.get("deleted") or "rows" in delta:
                out.append(delta)
                continue
            filepath = delta["file"]
            n = int(delta.get("n_rows", 0))
            keys = pointers_to_keys(
                [pointer_from(filepath, i, "fs") for i in range(n)]
            )
            rows = []
            for i in range(n):
                got = row_values.get(keys[i].tobytes())
                if got is None:
                    raise ValueError(
                        f"fs scan-state marker for {filepath!r} names {n} "
                        f"row(s) but row {i} is absent from the journal "
                        "frames (corrupt journal) — clear the persistence "
                        "directory to start fresh"
                    )
                rows.append(got)
            out.append({**delta, "rows": rows})
        return out

    def _process_file(self, source: StreamingDataSource, filepath: str) -> None:
        st = os.stat(filepath)
        # read before pushing anything: a concurrent deletion then raises while the
        # event stream is still untouched (no dangling begin/retractions)
        rows = _parse_file(
            filepath, self.format, self.schema, self.with_metadata, self.csv_settings
        )
        source.push_begin(filepath, (st.st_mtime, st.st_size))
        # row keys are content-addressed (file, row-index) so a later retraction of
        # this file's rows re-derives the exact same keys
        if filepath in self.emitted:
            for i, row in enumerate(self.emitted[filepath]):
                source.push(row, key=pointer_from(filepath, i, "fs"), diff=-1)
        for i, row in enumerate(rows):
            source.push(row, key=pointer_from(filepath, i, "fs"), diff=1)
        self.seen[filepath] = st.st_mtime
        self.emitted[filepath] = rows
        stale = [
            k for k in self._pushed
            if k[0] == filepath and k[1] != st.st_mtime
        ][:-1]  # keep the immediately-previous version for in-flight markers
        for k in stale:
            self._pushed.pop(k, None)
        self._pushed[(filepath, st.st_mtime)] = rows
        # the journaled marker carries NO row payload: the frame it rides in
        # already holds this file's rows as input deltas, and the restore path
        # re-derives them (rehydrate_state_deltas) — journaling both doubled
        # the journal size. Checkpoint exports hydrate rows back in
        # (hydrate_state_deltas) because compaction drops the frames.
        source.push_state(
            {"file": filepath, "mtime": st.st_mtime, "n_rows": len(rows)}
        )

    def _process_deletion(self, source: StreamingDataSource, filepath: str) -> None:
        source.push_begin(filepath, ("deleted",))
        for i, row in enumerate(self.emitted.get(filepath, [])):
            source.push(row, key=pointer_from(filepath, i, "fs"), diff=-1)
        self.seen.pop(filepath, None)
        self.emitted.pop(filepath, None)
        for k in [k for k in self._pushed if k[0] == filepath]:
            self._pushed.pop(k, None)
        source.push_state({"file": filepath, "deleted": True})

    # -- elastic membership (reshard protocol; see parallel/membership.py) ---

    def _freeze_point(self) -> None:
        """Scanner-side park at a file boundary while the engine reshards."""
        if not self._freeze.is_set():
            return
        self._idle.set()
        while self._freeze.is_set():
            time.sleep(0.05)
        self._idle.clear()

    def reshard_pause(self) -> None:
        self._freeze.set()

    def reshard_resume(self) -> None:
        self._freeze.clear()

    def reshard_idle(self, timeout: float) -> bool:
        """True once the scanner parked at a file boundary (engine side)."""
        return self._idle.wait(timeout)

    def reshard_exports(self, new_n: int) -> Dict[int, List[dict]]:
        """Complete partition of the live scan state by NEW file owner —
        {rank: [per-file state deltas]} (including this rank's keepers: the
        fragments double as the new topology's checkpoint)."""
        out: Dict[int, List[dict]] = {}
        with self._reshard_lock:
            for f in sorted(self.emitted):
                dest = int(pointer_from(f).lo % new_n)
                out.setdefault(dest, []).append(
                    {
                        "file": f,
                        "mtime": self.seen.get(f),
                        "rows": list(self.emitted[f]),
                    }
                )
        return out

    def reshard_key_owners(self, new_n: int) -> List[tuple]:
        """(row-key bytes, new owner) for every emitted row — drives the
        partition of ingest-placed downstream state tables (row keys are
        content-addressed (file, index), derivable from the scan state)."""
        from pathway_tpu.internals.keys import pointers_to_keys

        out: List[tuple] = []
        with self._reshard_lock:
            for f, rows in self.emitted.items():
                if not rows:
                    continue
                dest = int(pointer_from(f).lo % new_n)
                keys = pointers_to_keys(
                    [pointer_from(f, i, "fs") for i in range(len(rows))]
                )
                out.extend((keys[i].tobytes(), dest) for i in range(len(keys)))
        return out

    def reshard_apply(self, new_n: int, me: int) -> None:
        """Adopt the new topology: drop entries whose files now belong to
        another rank (WITHOUT retracting — the new owner carries them on) and
        invalidate any in-flight scan pass."""
        with self._reshard_lock:
            for f in [
                f for f in list(self.seen)
                if int(pointer_from(f).lo % new_n) != me
            ]:
                self.seen.pop(f, None)
                self.emitted.pop(f, None)
            self._reshard_gen += 1

    def reshard_keeps(self, delta: dict, new_n: int, me: int) -> bool:
        """Does this journal/checkpoint state delta still belong here?"""
        f = delta.get("file")
        return f is None or int(pointer_from(f).lo % new_n) == me

    def run(self, source: StreamingDataSource) -> None:
        from pathway_tpu.internals.config import get_pathway_config

        stop = False
        while not stop:
            self._freeze_point()
            gen = self._reshard_gen
            cfg = get_pathway_config()  # re-read: membership changes flip it
            present = _iter_files(self.path, self.object_pattern)
            if cfg.processes > 1:
                # partitioned parallel read (reference parallel_readers,
                # dataflow.rs:3317): each spawned process owns a hash-shard of files
                present = [
                    f
                    for f in present
                    if pointer_from(f).lo % cfg.processes == cfg.process_id
                ]
            aborted = False
            for filepath in present:
                self._freeze_point()
                if self._reshard_gen != gen:
                    # ownership changed mid-pass: this pass's file list was
                    # filtered with the OLD topology — abandon it (the next
                    # pass re-lists under the new one)
                    aborted = True
                    break
                try:
                    if self.seen.get(filepath) == os.stat(filepath).st_mtime:
                        continue
                    with self._reshard_lock:
                        self._process_file(source, filepath)
                except FileNotFoundError:
                    # deleted between listing and read; the next pass retracts it
                    continue
            if not aborted and self._reshard_gen == gen:
                for gone in sorted(set(self.seen) - set(present)):
                    self._process_deletion(source, gone)
                # one full pass done: a crash-straddled file absent from this
                # pass is gone
                source.push_barrier()
            if self.mode in ("static", "batch"):
                stop = True
            else:
                time.sleep(self.refresh_interval)


def read(
    path: str | Path,
    *,
    format: str = "plaintext",
    schema: sch.SchemaMetaclass | None = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    json_field_paths: dict | None = None,
    object_pattern: str = "*",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 100,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    path = str(path)
    if schema is None:
        if format in ("plaintext", "plaintext_by_file"):
            schema = sch.schema_from_types(data=str)
        elif format == "binary":
            schema = sch.schema_from_types(data=bytes)
        else:
            raise ValueError(f"schema is required for format {format!r}")
    out_schema = schema
    if with_metadata:
        out_schema = sch.schema_from_columns(
            {**schema.columns(), "_metadata": sch.ColumnSchema("_metadata", dt.JSON)},
            name="fs",
        )
    subject = _FsSubject(
        path, format, schema, mode, with_metadata, object_pattern, csv_settings=csv_settings
    )

    source = StreamingDataSource(subject=subject, autocommit_ms=autocommit_duration_ms)
    node = G.add_node(pg.InputNode(source=source, streaming=mode == "streaming", name=name or "fs"))
    return Table(node, out_schema, name=name or "fs")


class _FileWriter:
    def __init__(self, filename: str, format: str):
        self.filename = filename
        self.format = format
        self.file = open(filename, "w")
        self.lock = threading.Lock()

    def write_row(self, row: dict, time_: int, diff: int) -> None:
        with self.lock:
            if self.format == "json":
                rec = {**_plain(row), "time": time_, "diff": diff}
                self.file.write(json.dumps(rec) + "\n")
            else:
                values = [str(v) for v in _plain(row).values()] + [str(time_), str(diff)]
                self.file.write(",".join(values) + "\n")
            self.file.flush()

    def close(self) -> None:
        self.file.close()


def _plain(row: dict) -> dict:
    out = {}
    for k, v in row.items():
        if isinstance(v, Json):
            out[k] = v.value
        elif hasattr(v, "as_int") and type(v).__name__ == "Pointer":
            out[k] = repr(v)
        elif isinstance(v, bytes):
            out[k] = v.decode(errors="replace")
        else:
            out[k] = v
    return out


def write(table: Table, filename: str | Path, *, format: str = "json", name: str | None = None, **kwargs: Any) -> None:
    writer = _FileWriter(str(filename), format)

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        writer.write_row(row, time, 1 if is_addition else -1)

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=writer.close))
