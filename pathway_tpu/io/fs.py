"""Filesystem connector (parity: reference ``io/fs`` + ``src/connectors/scanner/filesystem.rs``).

Supports static and streaming modes over csv / json(lines) / plaintext / binary formats, with
the ``_metadata`` column like the reference's metadata support (``src/connectors/metadata.rs``).
"""

from __future__ import annotations

import csv as _csv
import glob
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import pointer_from
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _coerce(value: str, dtype: dt.DType) -> Any:
    """Parse a raw CSV field per schema dtype; malformed fields poison the cell with
    ``Error`` (reference ``Value::Error`` semantics, ``data_format.rs`` Dsv parser) so bad
    input stays distinguishable from a genuine null."""
    from pathway_tpu.engine.columnar import ERROR

    base = dtype.strip_optional()
    if value is None:
        return None
    try:
        if base == dt.INT:
            return int(value)
        if base == dt.FLOAT:
            return float(value)
        if base == dt.BOOL:
            if value in ("true", "True", "1"):
                return True
            if value in ("false", "False", "0"):
                return False
            return ERROR
        if base == dt.JSON:
            return Json.parse(value)
    except (ValueError, TypeError):
        return ERROR
    return value


def _iter_files(path: str, object_pattern: str = "*") -> List[str]:
    p = Path(path)
    if p.is_dir():
        return sorted(str(f) for f in p.rglob(object_pattern) if f.is_file())
    return sorted(glob.glob(path)) or ([str(p)] if p.exists() else [])


def _metadata_for(filepath: str) -> Json:
    st = os.stat(filepath)
    return Json(
        {
            "path": str(Path(filepath).resolve()),
            "size": st.st_size,
            "seen_at": int(time.time()),
            "modified_at": int(st.st_mtime),
            "owner": str(st.st_uid),
        }
    )


def _parse_file(
    filepath: str,
    format: str,
    schema: sch.SchemaMetaclass | None,
    with_metadata: bool,
    csv_settings: Any = None,
) -> List[dict]:
    rows: List[dict] = []
    if format in ("plaintext", "plaintext_by_file"):
        with open(filepath, "r", errors="replace") as f:
            if format == "plaintext_by_file":
                rows.append({"data": f.read()})
            else:
                for line in f:
                    rows.append({"data": line.rstrip("\n")})
    elif format == "binary":
        with open(filepath, "rb") as f:
            rows.append({"data": f.read()})
    elif format == "csv":
        delimiter = getattr(csv_settings, "delimiter", ",") if csv_settings else ","
        with open(filepath, newline="") as f:
            reader = _csv.DictReader(f, delimiter=delimiter)
            dtypes = schema.dtypes() if schema else {}
            for rec in reader:
                rows.append({k: _coerce(v, dtypes.get(k, dt.STR)) for k, v in rec.items() if k in dtypes or not schema})
    elif format in ("json", "jsonlines"):
        dtypes = schema.dtypes() if schema else {}
        with open(filepath) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                row = {}
                for name, dtype in (dtypes or {k: dt.ANY for k in rec}).items():
                    v = rec.get(name)
                    if dtype.strip_optional() == dt.JSON and v is not None:
                        v = Json(v)
                    row[name] = v
                rows.append(row)
    else:
        raise ValueError(f"unknown format {format!r}")
    if with_metadata:
        meta = _metadata_for(filepath)
        for row in rows:
            row["_metadata"] = meta
    return rows


class _FsSubject:
    def __init__(
        self,
        path: str,
        format: str,
        schema: sch.SchemaMetaclass | None,
        mode: str,
        with_metadata: bool,
        object_pattern: str,
        refresh_interval: float = 0.5,
        csv_settings: Any = None,
    ):
        self.path = path
        self.format = format
        self.schema = schema
        self.mode = mode
        self.with_metadata = with_metadata
        self.object_pattern = object_pattern
        self.refresh_interval = refresh_interval
        self.csv_settings = csv_settings
        self.seen: Dict[str, float] = {}
        self.emitted: Dict[str, List[dict]] = {}

    # -- persistence: the scanner's seen/emitted maps are the analogue of the
    # reference's cached_object_storage (replay without re-reading unchanged files).
    # State is checkpointed *in-band* (push_state after each file's events), so each
    # marker is ordered after exactly the events it accounts for — no snapshot races.

    def _state_snapshot(self) -> dict:
        return {
            "seen": dict(self.seen),
            "emitted": {k: list(v) for k, v in self.emitted.items()},
        }

    def restore(self, state: dict) -> None:
        """Called before the scanner thread starts; repositions the scan."""
        self.seen = dict(state.get("seen", {}))
        self.emitted = {k: list(v) for k, v in state.get("emitted", {}).items()}

    def run(self, source: StreamingDataSource) -> None:
        stop = False
        while not stop:
            for filepath in _iter_files(self.path, self.object_pattern):
                mtime = os.stat(filepath).st_mtime
                if self.seen.get(filepath) == mtime:
                    continue
                if filepath in self.emitted:
                    for row in self.emitted[filepath]:
                        source.push(row, diff=-1)
                rows = _parse_file(
                    filepath, self.format, self.schema, self.with_metadata, self.csv_settings
                )
                for row in rows:
                    source.push(row, diff=1)
                self.seen[filepath] = mtime
                self.emitted[filepath] = rows
                source.push_state(self._state_snapshot())
            if self.mode in ("static", "batch"):
                stop = True
            else:
                time.sleep(self.refresh_interval)


def read(
    path: str | Path,
    *,
    format: str = "plaintext",
    schema: sch.SchemaMetaclass | None = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    json_field_paths: dict | None = None,
    object_pattern: str = "*",
    with_metadata: bool = False,
    autocommit_duration_ms: int | None = 100,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    path = str(path)
    if schema is None:
        if format in ("plaintext", "plaintext_by_file"):
            schema = sch.schema_from_types(data=str)
        elif format == "binary":
            schema = sch.schema_from_types(data=bytes)
        else:
            raise ValueError(f"schema is required for format {format!r}")
    out_schema = schema
    if with_metadata:
        out_schema = sch.schema_from_columns(
            {**schema.columns(), "_metadata": sch.ColumnSchema("_metadata", dt.JSON)},
            name="fs",
        )
    subject = _FsSubject(
        path, format, schema, mode, with_metadata, object_pattern, csv_settings=csv_settings
    )

    class _Runner:
        def run(self, source: StreamingDataSource) -> None:
            subject.run(source)

    source = StreamingDataSource(subject=_Runner(), autocommit_ms=autocommit_duration_ms)
    node = G.add_node(pg.InputNode(source=source, streaming=mode == "streaming", name=name or "fs"))
    return Table(node, out_schema, name=name or "fs")


class _FileWriter:
    def __init__(self, filename: str, format: str):
        self.filename = filename
        self.format = format
        self.file = open(filename, "w")
        self.lock = threading.Lock()

    def write_row(self, row: dict, time_: int, diff: int) -> None:
        with self.lock:
            if self.format == "json":
                rec = {**_plain(row), "time": time_, "diff": diff}
                self.file.write(json.dumps(rec) + "\n")
            else:
                values = [str(v) for v in _plain(row).values()] + [str(time_), str(diff)]
                self.file.write(",".join(values) + "\n")
            self.file.flush()

    def close(self) -> None:
        self.file.close()


def _plain(row: dict) -> dict:
    out = {}
    for k, v in row.items():
        if isinstance(v, Json):
            out[k] = v.value
        elif hasattr(v, "as_int") and type(v).__name__ == "Pointer":
            out[k] = repr(v)
        elif isinstance(v, bytes):
            out[k] = v.decode(errors="replace")
        else:
            out[k] = v
    return out


def write(table: Table, filename: str | Path, *, format: str = "json", name: str | None = None, **kwargs: Any) -> None:
    writer = _FileWriter(str(filename), format)

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        writer.write_row(row, time, 1 if is_addition else -1)

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=writer.close))
