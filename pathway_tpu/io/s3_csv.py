"""S3 CSV shortcut (parity: reference ``io/s3_csv``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import s3 as _s3


def read(path: str, *, aws_s3_settings: Any = None, schema: Any = None, mode: str = "streaming", csv_settings: Any = None, **kwargs: Any) -> Any:
    return _s3.read(
        path,
        aws_s3_settings=aws_s3_settings,
        format="csv",
        schema=schema,
        mode=mode,
        csv_settings=csv_settings,
        **kwargs,
    )
