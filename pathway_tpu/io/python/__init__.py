"""Python connector: user-scripted streaming sources.

Parity: reference ``io/python/__init__.py:49`` (``ConnectorSubject``) feeding the engine's
``PythonReader`` (``src/connectors/data_storage.rs:843``).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import Pointer, pointer_from
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


class ConnectorSubject:
    """Subclass and implement ``run``; call ``self.next(**values)`` to emit rows."""

    _source: StreamingDataSource | None = None
    _schema: sch.SchemaMetaclass | None = None

    def run(self, source: StreamingDataSource | None = None) -> None:  # pragma: no cover
        raise NotImplementedError

    # -- emit API -----------------------------------------------------------

    def next(self, **kwargs: Any) -> None:
        self._emit(kwargs)

    def next_json(self, message: dict) -> None:
        self._emit(dict(message))

    def next_str(self, message: str) -> None:
        self._emit({"data": message})

    def next_bytes(self, message: bytes) -> None:
        self._emit({"data": message})

    def _emit(self, values: Dict[str, Any], diff: int = 1) -> None:
        key = None
        pk = self._schema.primary_key_columns() if self._schema else None
        if pk:
            key = pointer_from(*(values[c] for c in pk))
        assert self._source is not None, "subject not attached to a running graph"
        self._source.push(values, key=key, diff=diff)

    def _remove(self, values: Dict[str, Any]) -> None:
        self._emit(values, diff=-1)

    def commit(self) -> None:
        pass  # commits are driven by the engine's autocommit loop

    def close(self) -> None:
        assert self._source is not None
        self._source.close()

    def on_stop(self) -> None:
        pass

    @property
    def _deletions_enabled(self) -> bool:
        return True


class _SubjectRunner:
    def __init__(self, subject: ConnectorSubject):
        self.subject = subject

    def run(self, source: StreamingDataSource) -> None:
        self.subject._source = source
        try:
            self.subject.run()
        finally:
            self.subject.on_stop()


class _NoopRunner:
    """Non-reader processes park the subject: the source closes immediately."""

    def run(self, source: StreamingDataSource) -> None:
        return


def _runs_on_this_process(subject: Any) -> bool:
    """Reference parallel-reader placement (``dataflow.rs:3317``): a source that
    does not declare itself ``parallelized`` reads on process 0 only — its rows
    reach peer processes through the groupby/join exchange. Subjects that shard
    their own input (one reader per process) set ``parallelized = True``."""
    if getattr(subject, "parallelized", False):
        return True
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    return cfg.processes <= 1 or cfg.process_id == 0


def read(
    subject: ConnectorSubject,
    *,
    schema: sch.SchemaMetaclass,
    autocommit_duration_ms: int | None = 100,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    runner = (
        _SubjectRunner(subject)
        if _runs_on_this_process(subject)
        else _NoopRunner()
    )
    source = StreamingDataSource(subject=runner, autocommit_ms=autocommit_duration_ms)
    subject._schema = schema
    node = G.add_node(pg.InputNode(source=source, streaming=True, name=name or "python"))
    return Table(node, schema, name=name or "python")
