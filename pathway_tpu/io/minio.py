"""MinIO connector (parity: reference ``io/minio`` — S3-compatible endpoint settings)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import s3 as _s3
from pathway_tpu.io.s3 import AwsS3Settings


class MinIOSettings:
    def __init__(
        self,
        endpoint: str,
        bucket_name: str,
        access_key: str,
        secret_access_key: str,
        *,
        with_path_style: bool = True,
        region: str | None = None,
    ):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.region = region

    def create_aws_settings(self) -> AwsS3Settings:
        return AwsS3Settings(
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            region=self.region,
            endpoint=self.endpoint,
            with_path_style=self.with_path_style,
        )


def read(path: str, minio_settings: MinIOSettings | None = None, **kwargs: Any) -> Any:
    settings = minio_settings.create_aws_settings() if minio_settings else None
    return _s3.read(path, aws_s3_settings=settings, **kwargs)
