"""BigQuery sink (parity: reference ``io/bigquery`` — buffered streaming
``insert_rows_json``).

Real client code against the ``google.cloud.bigquery`` API, with per-commit flush
and injectable client (``_client``) so unit tests run against fakes in environments
without credentials or the client library.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.io._utils import add_batched_sink
from pathway_tpu.internals.table import Table


def write(
    table: Table,
    dataset_name: str,
    table_name: str,
    service_user_credentials_file: str | None = None,
    *,
    max_batch_size: int | None = None,
    _client: Any = None,
    **kwargs: Any,
) -> None:
    """Stream ``table``'s updates into ``dataset.table`` via ``insert_rows_json``.

    ``_client``: any object with the bigquery ``Client`` surface
    (``project`` attr + ``insert_rows_json(target, rows)``); tests inject fakes.
    """
    if _client is None:
        try:
            from google.cloud import bigquery
            from google.oauth2.service_account import Credentials
        except ImportError as exc:
            raise ImportError(
                "no BigQuery client library (google-cloud-bigquery) is available "
                "in this environment; pass _client=... (any object with the "
                "bigquery.Client insert_rows_json surface)"
            ) from exc
        if service_user_credentials_file is not None:
            credentials = Credentials.from_service_account_file(
                service_user_credentials_file
            )
            _client = bigquery.Client(credentials=credentials)
        else:
            _client = bigquery.Client()
    target = f"{_client.project}.{dataset_name}.{table_name}"

    def write_rows(rows: list[dict]) -> None:
        errors = _client.insert_rows_json(target, rows)
        if errors:
            raise RuntimeError(f"BigQuery insert failed: {errors}")

    add_batched_sink(
        table, write_rows, max_batch_size=int(max_batch_size or 500), client=_client
    )
