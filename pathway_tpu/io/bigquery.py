"""BigQuery sink (parity: reference ``io/bigquery`` — streaming ``insert_rows_json``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def write(
    table: Table,
    dataset_name: str,
    table_name: str,
    service_user_credentials_file: str | None = None,
    **kwargs: Any,
) -> None:
    try:
        from google.cloud import bigquery
        from google.oauth2.service_account import Credentials
    except ImportError:
        raise ImportError("google-cloud-bigquery is not available in this environment")

    if service_user_credentials_file is not None:
        credentials = Credentials.from_service_account_file(service_user_credentials_file)
        client = bigquery.Client(credentials=credentials)
    else:
        client = bigquery.Client()
    target = f"{client.project}.{dataset_name}.{table_name}"
    batch: list[dict] = []
    batch_size = int(kwargs.get("max_batch_size") or 500)

    from pathway_tpu.io._utils import plain_row

    def flush() -> None:
        if not batch:
            return
        rows, batch[:] = list(batch), []
        errors = client.insert_rows_json(target, rows)
        if errors:
            raise RuntimeError(f"BigQuery insert failed: {errors}")

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        batch.append({**plain_row(row), "time": time, "diff": 1 if is_addition else -1})
        if len(batch) >= batch_size:
            flush()

    def close() -> None:
        flush()
        client.close()

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=close))
