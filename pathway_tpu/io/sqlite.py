"""SQLite connector (parity: reference ``data_storage.rs:1415`` SqliteReader)."""

from __future__ import annotations

import sqlite3
import time
from typing import Any

from pathway_tpu.engine.datasource import StreamingDataSource
from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


class _SqliteSubject:
    def __init__(self, path: str, table_name: str, schema: sch.SchemaMetaclass, mode: str, poll_interval: float = 0.5):
        self.path = path
        self.table_name = table_name
        self.schema = schema
        self.mode = mode
        self.poll_interval = poll_interval

    def run(self, source: StreamingDataSource) -> None:
        last_rows: dict = {}
        names = self.schema.column_names()
        while True:
            conn = sqlite3.connect(self.path)
            try:
                cur = conn.execute(
                    f"SELECT rowid, {', '.join(names)} FROM {self.table_name}"
                )
                current = {}
                for rec in cur.fetchall():
                    rowid, values = rec[0], dict(zip(names, rec[1:]))
                    current[rowid] = values
            finally:
                conn.close()
            for rowid, values in current.items():
                if rowid not in last_rows:
                    source.push(values, diff=1)
                elif last_rows[rowid] != values:
                    source.push(last_rows[rowid], diff=-1)
                    source.push(values, diff=1)
            for rowid, values in last_rows.items():
                if rowid not in current:
                    source.push(values, diff=-1)
            last_rows = current
            if self.mode != "streaming":
                return
            time.sleep(self.poll_interval)


def read(
    path: str,
    table_name: str,
    schema: sch.SchemaMetaclass,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 100,
    **kwargs: Any,
) -> Table:
    subject = _SqliteSubject(path, table_name, schema, mode)

    class _Runner:
        def run(self, source: StreamingDataSource) -> None:
            subject.run(source)

    source = StreamingDataSource(subject=_Runner(), autocommit_ms=autocommit_duration_ms)
    node = G.add_node(pg.InputNode(source=source, streaming=mode == "streaming", name="sqlite"))
    return Table(node, schema, name="sqlite")
