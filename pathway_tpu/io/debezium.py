"""Debezium CDC connector.

Parity: reference ``io/debezium`` over the ``DebeziumMessage`` parser
(``src/connectors/data_format.rs:1053``): each message is a Debezium envelope whose
``op`` maps to engine diffs — ``c``/``r`` insert ``after``, ``u`` retracts ``before``
and inserts ``after``, ``d`` retracts ``before``. The MongoDB variant carries
``before``/``after`` as JSON strings.

``read`` consumes from Kafka (gated on a client library); ``read_from_iterable`` feeds
the same parser from any message iterator, which is how the parser is tested hermetically.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

from pathway_tpu.internals import schema as sch


def parse_debezium_message(message: bytes | str | dict, column_names: list[str]) -> list[tuple[dict, int]]:
    """Envelope → [(row_values, diff)] (reference ``data_format.rs`` ``DebeziumMessage``)."""
    if isinstance(message, (bytes, str)):
        message = json.loads(message)
    payload = message.get("payload", message)
    op = payload.get("op")
    before = payload.get("before")
    after = payload.get("after")
    if isinstance(before, str):
        before = json.loads(before)  # Mongo variant ships embedded JSON strings
    if isinstance(after, str):
        after = json.loads(after)

    def project(record: dict | None) -> dict:
        record = record or {}
        return {name: record.get(name) for name in column_names}

    if op in ("c", "r"):
        return [(project(after), 1)]
    if op == "u":
        return [(project(before), -1), (project(after), 1)]
    if op == "d":
        return [(project(before), -1)]
    raise ValueError(f"unknown debezium operation {op!r}")


def read_from_iterable(
    messages: Iterable[bytes | str | dict],
    *,
    schema: sch.SchemaMetaclass,
    autocommit_duration_ms: int | None = 100,
) -> Any:
    """Feed Debezium envelopes from any iterator (tests, custom consumers)."""
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    names = schema.column_names()

    class _DebeziumSubject(ConnectorSubject):
        def run(self) -> None:
            for message in messages:
                for values, diff in parse_debezium_message(message, names):
                    self._emit(values, diff=diff)

    return py_read(
        _DebeziumSubject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms
    )


def read(
    rdkafka_settings: dict,
    topic_name: str | None = None,
    *,
    schema: sch.SchemaMetaclass,
    autocommit_duration_ms: int | None = 1500,
    **kwargs: Any,
) -> Any:
    """Consume Debezium envelopes from a Kafka topic (requires a Kafka client)."""
    try:
        import confluent_kafka
    except ImportError:
        raise ImportError(
            "no Kafka client library is available in this environment; use "
            "pw.io.debezium.read_from_iterable(...) to feed envelopes from your own "
            "consumer"
        )
    if topic_name is None:
        raise ValueError("pw.io.debezium.read requires topic_name")

    def consume() -> Iterable[bytes]:
        consumer = confluent_kafka.Consumer(rdkafka_settings)
        consumer.subscribe([topic_name])
        while True:
            msg = consumer.poll(1.0)
            if msg is None:
                continue
            if msg.error():
                if msg.error().code() == confluent_kafka.KafkaError._PARTITION_EOF:
                    continue
                raise RuntimeError(f"kafka consumer error: {msg.error()}")
            yield msg.value()

    return read_from_iterable(
        consume(), schema=schema, autocommit_duration_ms=autocommit_duration_ms
    )
