"""Debezium CDC connector.

Parity: reference ``io/debezium`` over the ``DebeziumMessage`` parser
(``src/connectors/data_format.rs:1053``): each message is a Debezium envelope whose
``op`` maps to engine diffs — ``c``/``r`` insert ``after``, ``u`` retracts ``before``
and inserts ``after``, ``d`` retracts ``before``. The MongoDB variant carries
``before``/``after`` as JSON strings.

``read`` consumes from Kafka (gated on a client library); ``read_from_iterable`` feeds
the same parser from any message iterator, which is how the parser is tested hermetically.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

from pathway_tpu.internals import schema as sch


def parse_debezium_message(message: bytes | str | dict, column_names: list[str]) -> list[tuple[dict, int]]:
    """Envelope → [(row_values, diff)] (reference ``data_format.rs`` ``DebeziumMessage``)."""
    if isinstance(message, (bytes, str)):
        message = json.loads(message)
    payload = message.get("payload", message)
    op = payload.get("op")
    before = payload.get("before")
    after = payload.get("after")
    if isinstance(before, str):
        before = json.loads(before)  # Mongo variant ships embedded JSON strings
    if isinstance(after, str):
        after = json.loads(after)

    def project(record: dict | None) -> dict:
        record = record or {}
        return {name: record.get(name) for name in column_names}

    if op in ("c", "r"):
        return [(project(after), 1)]
    if op == "u":
        return [(project(before), -1), (project(after), 1)]
    if op == "d":
        return [(project(before), -1)]
    raise ValueError(f"unknown debezium operation {op!r}")


def read_from_iterable(
    messages: Iterable[bytes | str | dict],
    *,
    schema: sch.SchemaMetaclass,
    autocommit_duration_ms: int | None = 100,
) -> Any:
    """Feed Debezium envelopes from any iterator (tests, custom consumers)."""
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    names = schema.column_names()

    class _DebeziumSubject(ConnectorSubject):
        def run(self) -> None:
            for message in messages:
                for values, diff in parse_debezium_message(message, names):
                    self._emit(values, diff=diff)

    return py_read(
        _DebeziumSubject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms
    )


def read(
    rdkafka_settings: dict,
    topic_name: str | None = None,
    *,
    schema: sch.SchemaMetaclass,
    autocommit_duration_ms: int | None = 1500,
    mode: str = "streaming",
    name: str | None = None,
    _consumer_factory: Any = None,
    **kwargs: Any,
) -> Any:
    """Consume Debezium envelopes from a Kafka topic.

    Rides the full Kafka connector machinery (``io/kafka._KafkaSubject``): offsets
    checkpoint as in-band segment state and SEEK back on resume (the reference's
    Debezium seek, ``data_format.rs:1053`` + ``offset.rs``); the consumer is
    injectable for broker-less tests. Row keys derive from the schema's primary-key
    columns when declared (upserts retract/insert under the same key), else from
    the full row values.
    """
    from pathway_tpu.engine.datasource import StreamingDataSource
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.internals.keys import pointer_from
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.table import Table
    from pathway_tpu.io.kafka import _default_consumer_factory, _KafkaSubject

    if topic_name is None:
        raise ValueError("pw.io.debezium.read requires topic_name")
    from pathway_tpu.internals.config import get_pathway_config

    if get_pathway_config().processes > 1 and "group.id" not in rdkafka_settings:
        # same parallel-read contract as kafka.read: consumer groups split
        # partitions across processes; without one every process re-consumes
        # the full CDC topic and aggregates double-count
        raise ValueError(
            "multi-process debezium.read requires rdkafka_settings['group.id'] "
            "so the broker splits partitions across the spawned processes"
        )
    if _consumer_factory is None:
        try:
            import confluent_kafka  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "no Kafka client library is available in this environment; pass "
                "_consumer_factory=... or use pw.io.debezium.read_from_iterable(...)"
            ) from exc
    names = schema.column_names()
    pk_cols = schema.primary_key_columns()

    class _DebeziumKafkaSubject(_KafkaSubject):
        """Keyed CDC decoding with UpsertSession semantics (reference
        ``adaptors.rs:67``): a per-pk last-values cache resolves retractions whose
        ``before`` image is missing or partial (Postgres REPLICA IDENTITY
        DEFAULT), so the engine always retracts the exact values it inserted.
        The cache rides offset markers, making it resume-exact."""

        def __init__(self, *args: Any, **kwargs: Any):
            super().__init__(*args, **kwargs)
            self._last_values: dict = {}  # pk tuple -> row values dict
            self._dirty_upserts: dict = {}  # pk -> values | None, since last marker

        def _marker_extra(self) -> dict:
            if self._dirty_upserts:
                d, self._dirty_upserts = self._dirty_upserts, {}
                return {"upserts": d}
            return {}

        @staticmethod
        def fold_state_deltas(state_deltas: list) -> list:
            latest: dict = {}
            upserts: dict = {}
            for delta in state_deltas:
                latest[(delta["topic"], delta["partition"])] = {
                    k: v for k, v in delta.items() if k != "upserts"
                }
                for pk, vals in (delta.get("upserts") or {}).items():
                    if vals is None:
                        upserts.pop(pk, None)
                    else:
                        upserts[pk] = vals
            out = [latest[k] for k in sorted(latest)]
            if upserts:
                if out:
                    out[-1] = {**out[-1], "upserts": upserts}
                else:
                    out = [{"upserts": upserts}]
            return out

        def restore(self, state_deltas: list) -> None:
            super().restore([d for d in state_deltas if "topic" in d])
            for delta in state_deltas:
                for pk, vals in (delta.get("upserts") or {}).items():
                    if vals is None:
                        self._last_values.pop(pk, None)
                    else:
                        self._last_values[pk] = vals

        def _decode_events(self, msg: Any) -> list:
            value = msg.value()
            if value is None:
                return []
            events = parse_debezium_message(value, names)
            # With a primary key, both halves of an update key by the SAME pk so
            # the retraction cancels the original insert — and a `before` that
            # lacks the pk (REPLICA IDENTITY DEFAULT ships before=null) falls
            # back to `after`'s pk, with the retracted VALUES resolved from the
            # last-values cache (the values actually inserted). Without a
            # declared pk the row values are the key, requiring full before
            # images (REPLICA IDENTITY FULL).
            after_pk = None
            if pk_cols:
                for values, diff in events:
                    if diff > 0 and all(values.get(c) is not None for c in pk_cols):
                        after_pk = tuple(values[c] for c in pk_cols)
                        break
            out = []
            for values, diff in events:
                if pk_cols:
                    pk = tuple(values.get(c) for c in pk_cols)
                    if any(v is None for v in pk):
                        if after_pk is None:
                            raise ValueError(
                                "debezium envelope carries no primary-key values "
                                f"(columns {pk_cols}); configure the source with "
                                "a replica identity that ships them"
                            )
                        pk = after_pk
                    if diff < 0:
                        # the cache is AUTHORITATIVE for retractions: the engine
                        # must retract exactly the values it inserted, and before
                        # images are unreliable (REPLICA IDENTITY DEFAULT ships
                        # null or pk-only befores). Envelope values are only a
                        # fallback for rows never seen (e.g. pre-resume history
                        # with REPLICA IDENTITY FULL). A retraction for a row
                        # NEVER seen in this run with no usable before image is
                        # DROPPED: this engine's state doesn't hold the row (a
                        # restart without persistence starts empty), so there is
                        # nothing to retract and the insert half upserts cleanly.
                        cached = self._last_values.get(pk)
                        if cached is not None:
                            values = dict(cached)
                        elif all(values.get(c) is None for c in names):
                            from pathway_tpu.internals.config import (
                                get_pathway_config,
                            )

                            if get_pathway_config().processes > 1:
                                # multi-process: a consumer-group rebalance can
                                # hand us a partition whose inserts a PEER
                                # cached — the row may well live in exchanged
                                # engine state, so dropping would leak it; fail
                                # loudly instead
                                raise ValueError(
                                    f"debezium retraction for pk {pk} has no "
                                    "before image and no local insert history "
                                    "(likely a consumer-group rebalance); "
                                    "enable REPLICA IDENTITY FULL or Pathway "
                                    "persistence so retraction values resolve"
                                )
                            import logging

                            logging.getLogger("pathway_tpu").warning(
                                "debezium retraction for pk %s has no before "
                                "image and no prior insert was seen in this "
                                "run; dropping the retraction (single-process: "
                                "engine state cannot hold the row)",
                                pk,
                            )
                            continue
                    key = pointer_from(*pk)
                    if diff > 0:
                        self._last_values[pk] = dict(values)
                        self._dirty_upserts[pk] = dict(values)
                    else:
                        self._last_values.pop(pk, None)
                        self._dirty_upserts[pk] = None
                else:
                    if diff < 0 and all(values.get(c) is None for c in names):
                        raise ValueError(
                            "debezium retraction has no before image and the "
                            "schema declares no primary key; declare one "
                            "(column_definition(primary_key=True)) or enable "
                            "REPLICA IDENTITY FULL"
                        )
                    key = pointer_from(*(values[c] for c in names))
                out.append((values, diff, key))
            return out

    subject = _DebeziumKafkaSubject(
        _consumer_factory or _default_consumer_factory,
        rdkafka_settings,
        [topic_name],
        "json",
        schema,
        False,
        mode=mode,
    )
    source = StreamingDataSource(subject=subject, autocommit_ms=autocommit_duration_ms)
    node = G.add_node(
        pg.InputNode(source=source, streaming=mode == "streaming", name=name or "debezium")
    )
    return Table(node, schema, name=name or "debezium")
