"""JSON Lines connector (parity: reference ``io/jsonlines``)."""

from __future__ import annotations

from pathlib import Path
from typing import Any

from pathway_tpu.io import fs


def read(path: str | Path, *, schema: Any = None, mode: str = "streaming", **kwargs: Any):
    return fs.read(path, format="jsonlines", schema=schema, mode=mode, **kwargs)


def write(table: Any, filename: str | Path, **kwargs: Any) -> None:
    fs.write(table, filename, format="json", **kwargs)
