"""Elasticsearch sink.

Parity: reference ``io/elasticsearch`` over the Elastic writer
(``src/connectors/data_storage.rs:1336``). Implemented against the REST ``_bulk`` API via
``requests`` (no elasticsearch-py needed): additions index documents, retractions delete
by the row key, matching the reference's update-stream semantics.
"""

from __future__ import annotations

import json
import threading
from typing import Any

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._utils import plain_row as _plain_row
from pathway_tpu.internals.table import Table


class ElasticSearchAuth:
    """Auth settings holder (reference ``io/elasticsearch`` ``ElasticSearchAuth``)."""

    def __init__(self, kind: str, **params: Any):
        self.kind = kind
        self.params = params

    @classmethod
    def basic(cls, username: str, password: str) -> "ElasticSearchAuth":
        return cls("basic", username=username, password=password)

    @classmethod
    def apikey(cls, api_key_id: str, api_key: str) -> "ElasticSearchAuth":
        return cls("apikey", api_key_id=api_key_id, api_key=api_key)

    @classmethod
    def bearer(cls, token: str) -> "ElasticSearchAuth":
        return cls("bearer", token=token)

    def apply(self, session: Any) -> None:
        if self.kind == "basic":
            session.auth = (self.params["username"], self.params["password"])
        elif self.kind == "apikey":
            session.headers["Authorization"] = (
                f"ApiKey {self.params['api_key_id']}:{self.params['api_key']}"
            )
        elif self.kind == "bearer":
            session.headers["Authorization"] = f"Bearer {self.params['token']}"


class _BulkWriter:
    def __init__(self, host: str, index_name: str, auth: ElasticSearchAuth | None, batch_size: int = 500):
        import requests

        self.host = host.rstrip("/")
        self.index = index_name
        self.session = requests.Session()
        if auth is not None:
            auth.apply(self.session)
        self.batch: list[str] = []
        self.batch_size = batch_size
        self.lock = threading.Lock()

    def add(self, key: Any, row: dict, is_addition: bool) -> None:
        doc_id = repr(key)
        with self.lock:
            if is_addition:
                self.batch.append(json.dumps({"index": {"_index": self.index, "_id": doc_id}}))
                self.batch.append(json.dumps(_plain_row(row)))
            else:
                self.batch.append(json.dumps({"delete": {"_index": self.index, "_id": doc_id}}))
            if len(self.batch) >= self.batch_size:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self.batch:
            return
        body = "\n".join(self.batch) + "\n"
        self.batch = []
        response = self.session.post(
            f"{self.host}/_bulk",
            data=body.encode(),
            headers={"Content-Type": "application/x-ndjson"},
            timeout=30,
        )
        response.raise_for_status()

    def close(self) -> None:
        with self.lock:
            self._flush_locked()


def write(
    table: Table,
    host: str,
    auth: ElasticSearchAuth | None = None,
    index_name: str | None = None,
    **kwargs: Any,
) -> None:
    writer = _BulkWriter(host, index_name or "pathway", auth)

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        writer.add(key, row, is_addition)

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=writer.close))
