"""Shared sink helpers (parity: reference ``io/_utils.py``)."""

from __future__ import annotations

from typing import Any


def plain_row(row: dict) -> dict:
    """Engine values → JSON-friendly plain Python values (one rule set for all sinks)."""
    from pathway_tpu.internals.json import Json

    out = {}
    for k, v in row.items():
        if isinstance(v, Json):
            out[k] = v.value
        elif hasattr(v, "item"):
            out[k] = v.item()
        elif type(v).__name__ == "Pointer":
            out[k] = repr(v)
        else:
            out[k] = v
    return out
