"""Shared sink helpers (parity: reference ``io/_utils.py``)."""

from __future__ import annotations

from typing import Any


def plain_row(row: dict) -> dict:
    """Engine values → JSON-friendly plain Python values (one rule set for all sinks)."""
    from pathway_tpu.internals.json import Json

    out = {}
    for k, v in row.items():
        if isinstance(v, Json):
            out[k] = v.value
        elif hasattr(v, "item"):
            out[k] = v.item()
        elif type(v).__name__ == "Pointer":
            out[k] = repr(v)
        else:
            out[k] = v
    return out


def columns_to_pylists(columns: dict, names: list) -> dict:
    """Columnar batch -> per-column Python lists for row-oriented sinks.

    ``tolist()`` on numeric columns yields native Python scalars (callbacks and
    JSON payloads must not see numpy scalars); datetime64 columns must NOT tolist
    (ns precision would degrade to raw int nanoseconds), and object columns pass
    through as-is.
    """
    return {
        c: (columns[c].tolist() if columns[c].dtype.kind in "ifb" else list(columns[c]))
        for c in names
    }
