"""Shared sink helpers (parity: reference ``io/_utils.py``)."""

from __future__ import annotations

from typing import Any


def plain_row(row: dict) -> dict:
    """Engine values → JSON-friendly plain Python values (one rule set for all sinks)."""
    from pathway_tpu.internals.json import Json

    out = {}
    for k, v in row.items():
        if isinstance(v, Json):
            out[k] = v.value
        elif hasattr(v, "item"):
            out[k] = v.item()
        elif type(v).__name__ == "Pointer":
            out[k] = repr(v)
        else:
            out[k] = v
    return out


def columns_to_pylists(columns: dict, names: list) -> dict:
    """Columnar batch -> per-column Python lists for row-oriented sinks.

    ``tolist()`` on numeric columns yields native Python scalars (callbacks and
    JSON payloads must not see numpy scalars); datetime64 columns must NOT tolist
    (ns precision would degrade to raw int nanoseconds), and object columns pass
    through as-is.
    """
    return {
        c: (columns[c].tolist() if columns[c].dtype.kind in "ifb" else list(columns[c]))
        for c in names
    }


def add_batched_sink(
    table,
    write_rows,
    *,
    max_batch_size: int,
    client=None,
):
    """Shared OutputNode scaffolding for document sinks (mongodb/bigquery):
    rows carry ``time``/``diff``, batch up to ``max_batch_size``, flush at every
    commit boundary and at close; ``client.close()`` (when present) runs after
    the final flush."""
    from pathway_tpu.internals import parse_graph as pg
    from pathway_tpu.internals.parse_graph import G

    batch: list[dict] = []

    def flush() -> None:
        if batch:
            rows, batch[:] = list(batch), []
            write_rows(rows)

    def callback(key, row: dict, time: int, is_addition: bool) -> None:
        batch.append({**plain_row(row), "time": time, "diff": 1 if is_addition else -1})
        if len(batch) >= max_batch_size:
            flush()

    def close() -> None:
        flush()
        close_fn = getattr(client, "close", None)
        if close_fn is not None:
            close_fn()

    G.add_node(
        pg.OutputNode(
            inputs=[table],
            callback=callback,
            on_end=close,
            on_time_end=lambda _t: flush(),
        )
    )
