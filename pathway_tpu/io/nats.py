"""NATS connector.

Parity: reference ``io/nats`` over ``data_storage.rs:2271`` (reader) / ``:2345`` (writer).
Implemented against nats-py (absent from this image — these paths run only where it is
installed): a background asyncio loop subscribes/publishes; ``read_from_iterable`` offers
the client-free surface used by tests.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Iterable

from pathway_tpu.internals import parse_graph as pg
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table


def _require() -> Any:
    try:
        import nats

        return nats
    except ImportError as exc:
        raise ImportError(
            "nats-py is not available in this environment; use "
            "pw.io.nats.read_from_iterable(...) or pw.io.python.read(...)"
        ) from exc


def read(
    uri: str,
    topic: str,
    *,
    format: str = "json",
    schema: sch.SchemaMetaclass | None = None,
    autocommit_duration_ms: int | None = 1500,
    **kwargs: Any,
) -> Table:
    nats = _require()

    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    if schema is None:
        schema = sch.schema_from_types(data=str)
    names = schema.column_names()

    class _NatsSubject(ConnectorSubject):
        def run(self) -> None:
            import asyncio

            async def main() -> None:
                client = await nats.connect(uri)
                subscription = await client.subscribe(topic)
                async for msg in subscription.messages:
                    if format == "json":
                        record = json.loads(msg.data)
                        self._emit({n: record.get(n) for n in names})
                    else:
                        self._emit({"data": msg.data.decode()})

            asyncio.run(main())

    return py_read(
        _NatsSubject(), schema=schema, autocommit_duration_ms=autocommit_duration_ms
    )


def write(table: Table, uri: str, topic: str, *, format: str = "json", **kwargs: Any) -> None:
    nats = _require()
    import asyncio

    from pathway_tpu.io._utils import plain_row

    loop = asyncio.new_event_loop()
    ready = threading.Event()
    state: dict = {}

    def loop_runner() -> None:
        asyncio.set_event_loop(loop)

        async def connect() -> None:
            state["client"] = await nats.connect(uri)
            ready.set()

        loop.create_task(connect())
        loop.run_forever()

    threading.Thread(target=loop_runner, daemon=True, name="pathway:nats").start()

    def callback(key: Any, row: dict, time: int, is_addition: bool) -> None:
        ready.wait(timeout=30)
        doc = json.dumps({**plain_row(row), "time": time, "diff": 1 if is_addition else -1})
        asyncio.run_coroutine_threadsafe(
            state["client"].publish(topic, doc.encode()), loop
        ).result(timeout=30)

    def close() -> None:
        if "client" in state:
            asyncio.run_coroutine_threadsafe(state["client"].drain(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)

    G.add_node(pg.OutputNode(inputs=[table], callback=callback, on_end=close))


def read_from_iterable(
    messages: Iterable[bytes | str | dict],
    *,
    schema: sch.SchemaMetaclass | None = None,
    format: str = "json",
    autocommit_duration_ms: int | None = 100,
) -> Any:
    from pathway_tpu.io.kafka import read_from_iterable as _kafka_iter

    return _kafka_iter(
        messages, schema=schema, format=format, autocommit_duration_ms=autocommit_duration_ms
    )
