"""NATS connector (parity: reference ``io/nats`` over ``data_storage.rs:2271,2345``).
Requires nats-py; ``read_from_iterable`` offers the client-free surface."""

from __future__ import annotations

import json
from typing import Any, Iterable

from pathway_tpu.internals import schema as sch


def _no_client() -> None:
    raise ImportError(
        "nats-py is not available in this environment; use "
        "pw.io.nats.read_from_iterable(...) or pw.io.python.read(...)"
    )


def read(uri: str, topic: str, *, format: str = "json", schema: Any = None, **kwargs: Any) -> Any:
    try:
        import nats  # noqa: F401
    except ImportError:
        _no_client()


def write(table: Any, uri: str, topic: str, *, format: str = "json", **kwargs: Any) -> None:
    try:
        import nats  # noqa: F401
    except ImportError:
        _no_client()


def read_from_iterable(
    messages: Iterable[bytes | str | dict],
    *,
    schema: sch.SchemaMetaclass | None = None,
    format: str = "json",
    autocommit_duration_ms: int | None = 100,
) -> Any:
    from pathway_tpu.io.kafka import read_from_iterable as _kafka_iter

    return _kafka_iter(
        messages, schema=schema, format=format, autocommit_duration_ms=autocommit_duration_ms
    )
