"""Object-store persistence backends: S3 / Azure / memory behind one surface.

Parity: reference ``src/persistence/backends/mod.rs:50`` defines the
``PersistenceBackend`` trait (``list_keys`` / ``get_value`` / ``put_value`` /
``remove_key``) with filesystem, S3 (``backends/s3.rs``), Azure and mock
implementations; the metadata and snapshot layers are written against the trait.

Here the same contract is ``ObjectStore``. Journal frames become immutable
numbered objects (object stores have no append — a PUT per commit gives the
same crash guarantee as the fs backend's fsync-per-frame: a frame either fully
exists or doesn't), checkpoints are single-PUT blobs (atomic per key), and
compaction deletes subsumed frame objects. Clients are injectable the same way
the S3 scanner's are (``io/s3.py``), so hermetic tests run the full engine
against an in-memory or directory-backed fake.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List


class ObjectStore:
    """Minimal durable key -> bytes contract the persistence engine needs."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> "bytes | None":
        raise NotImplementedError

    def list(self, prefix: str) -> List[str]:
        """Keys under ``prefix``, SORTED — journal replay order rides on it."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError


def _is_not_found(exc: Exception) -> bool:
    """Distinguish 'object does not exist' from transient store errors: a
    throttle or network failure must NOT read as an absent checkpoint — the
    runner would silently start fresh and later overwrite the good checkpoint."""
    if isinstance(exc, (KeyError, FileNotFoundError)):
        return True  # fakes / dict-backed clients
    resp = getattr(exc, "response", None)  # botocore ClientError surface
    if isinstance(resp, dict):
        code = str(resp.get("Error", {}).get("Code", ""))
        return code in ("NoSuchKey", "NoSuchBucket", "404", "NotFound")
    return type(exc).__name__ in ("ResourceNotFoundError", "BlobNotFound")


class RetryingObjectStore(ObjectStore):
    """Transient-failure absorption for object-store persistence: every op runs
    through an :class:`~pathway_tpu.internals.udfs.AsyncRetryStrategy` (default
    ``ExponentialBackoffRetryStrategy``), so a throttled PUT or a flaky network
    read retries with backoff+jitter instead of killing the pipeline mid-commit.

    Not-found is NOT an error at this layer (inner stores return ``None``), so
    retries fire only on genuine exceptions. Wrap ORDER matters in tests: the
    chaos store (``internals/chaos.py``) injects below this wrapper, so injected
    transient write errors are exactly what this absorbs."""

    def __init__(self, inner: ObjectStore, strategy: Any = None):
        if strategy is None:
            from pathway_tpu.internals.udfs import ExponentialBackoffRetryStrategy

            strategy = ExponentialBackoffRetryStrategy(
                max_retries=4, initial_delay=50, backoff_factor=2, jitter_ms=20
            )
        self._inner = inner
        self._strategy = strategy
        # the STOCK backoff strategies run a plain sync sleep loop — one
        # journal PUT per commit must not pay event-loop setup/teardown per
        # call. Exact-type check: a subclass may override invoke() (selective
        # retry, logging) and must go through it, not a reimplemented schedule.
        from pathway_tpu.internals.udfs import (
            ExponentialBackoffRetryStrategy,
            FixedDelayRetryStrategy,
        )

        self._sync_schedule = type(strategy) in (
            ExponentialBackoffRetryStrategy,
            FixedDelayRetryStrategy,
        )

    def _retry(self, fun: Callable, *args: Any) -> Any:
        if self._sync_schedule:
            import random
            import time

            s = self._strategy
            delay = s.initial_delay
            for attempt in range(s.max_retries + 1):
                try:
                    return fun(*args)
                except Exception as exc:
                    # triage before retrying (PWA202 discipline): a not-found
                    # raised by an inner client (instead of the None contract)
                    # is DEFINITIVE — burning the whole backoff schedule on it
                    # delays the caller's absent-checkpoint handling by the
                    # full retry budget for nothing
                    if _is_not_found(exc) or attempt == s.max_retries:
                        raise
                    time.sleep(delay + random.random() * s.jitter)
                    delay *= s.backoff_factor
            raise RuntimeError("unreachable")
        import asyncio

        async def call(*a: Any) -> Any:
            return fun(*a)

        return asyncio.run(self._strategy.invoke(call, *args))

    def put(self, key: str, data: bytes) -> None:
        self._retry(self._inner.put, key, data)

    def get(self, key: str) -> "bytes | None":
        return self._retry(self._inner.get, key)

    def list(self, prefix: str) -> List[str]:
        return self._retry(self._inner.list, prefix)

    def delete(self, key: str) -> None:
        self._retry(self._inner.delete, key)


class PrefixedStore(ObjectStore):
    """A namespaced view over another store (per-process shards, cached-object
    subtrees) — every key gets the prefix applied on the way in/out."""

    def __init__(self, inner: ObjectStore, prefix: str):
        self._inner = inner
        self._prefix = prefix.strip("/") + "/" if prefix.strip("/") else ""

    def put(self, key: str, data: bytes) -> None:
        self._inner.put(self._prefix + key, data)

    def get(self, key: str) -> "bytes | None":
        return self._inner.get(self._prefix + key)

    def list(self, prefix: str) -> List[str]:
        cut = len(self._prefix)
        return [k[cut:] for k in self._inner.list(self._prefix + prefix)]

    def delete(self, key: str) -> None:
        self._inner.delete(self._prefix + key)


class MemoryObjectStore(ObjectStore):
    def __init__(self) -> None:
        self.objects: Dict[str, bytes] = {}

    def put(self, key: str, data: bytes) -> None:
        self.objects[key] = bytes(data)

    def get(self, key: str) -> "bytes | None":
        return self.objects.get(key)

    def list(self, prefix: str) -> List[str]:
        return sorted(k for k in self.objects if k.startswith(prefix))

    def delete(self, key: str) -> None:
        self.objects.pop(key, None)


class S3ObjectStore(ObjectStore):
    """Over the boto3 S3 client surface (list_objects_v2 / get_object /
    put_object / delete_object) — the exact surface ``io/s3.py`` readers use,
    so the same injectable fakes exercise both paths."""

    def __init__(self, client: Any, bucket: str, prefix: str):
        self._client = client
        self._bucket = bucket
        self._prefix = prefix.strip("/")

    def _full(self, key: str) -> str:
        return f"{self._prefix}/{key}" if self._prefix else key

    def put(self, key: str, data: bytes) -> None:
        self._client.put_object(Bucket=self._bucket, Key=self._full(key), Body=bytes(data))

    def get(self, key: str) -> "bytes | None":
        try:
            resp = self._client.get_object(Bucket=self._bucket, Key=self._full(key))
        except Exception as exc:
            if _is_not_found(exc):
                return None
            raise
        return resp["Body"].read()

    def list(self, prefix: str) -> List[str]:
        from pathway_tpu.io.s3 import _list_objects

        cut = len(self._prefix) + 1 if self._prefix else 0
        return [
            o["Key"][cut:]
            for o in _list_objects(self._client, self._bucket, self._full(prefix))
        ]

    def delete(self, key: str) -> None:
        try:
            self._client.delete_object(Bucket=self._bucket, Key=self._full(key))
        except Exception as exc:
            # deleting an absent object is fine; a transient failure is NOT —
            # compaction/rewind callers rely on the object actually going away
            if not _is_not_found(exc):
                raise


class AzureObjectStore(ObjectStore):
    """Over the azure-storage-blob ContainerClient surface (upload_blob /
    download_blob / list_blob_names / delete_blob)."""

    def __init__(self, container_client: Any, prefix: str):
        self._client = container_client
        self._prefix = prefix.strip("/")

    def _full(self, key: str) -> str:
        return f"{self._prefix}/{key}" if self._prefix else key

    def put(self, key: str, data: bytes) -> None:
        self._client.upload_blob(self._full(key), bytes(data), overwrite=True)

    def get(self, key: str) -> "bytes | None":
        try:
            return self._client.download_blob(self._full(key)).readall()
        except Exception as exc:
            if _is_not_found(exc):
                return None
            raise

    def list(self, prefix: str) -> List[str]:
        full = self._full(prefix)
        names = self._client.list_blob_names(name_starts_with=full)
        cut = len(self._prefix) + 1 if self._prefix else 0
        return sorted(str(n)[cut:] for n in names)

    def delete(self, key: str) -> None:
        try:
            self._client.delete_blob(self._full(key))
        except Exception as exc:
            if not _is_not_found(exc):
                raise


def _default_azure_factory(account: Any, root_path: str, kw: dict) -> Any:
    try:
        from azure.storage.blob import ContainerClient  # type: ignore
    except ImportError as exc:
        raise ImportError(
            "no Azure client library (azure-storage-blob) in this environment; pass "
            "_client_factory=... (any object with the ContainerClient upload_blob/"
            "download_blob/list_blob_names/delete_blob surface)"
        ) from exc
    container = kw.get("container") or root_path.split("/", 1)[0]
    return ContainerClient(
        account_url=f"https://{account}.blob.core.windows.net", container_name=container,
        credential=kw.get("credential"),
    )


def make_object_store(backend: Any) -> ObjectStore:
    """Build the ObjectStore for a ``persistence.Backend`` (s3/azure kinds)."""
    root = str(backend.root or "")
    if backend.kind == "s3":
        from pathway_tpu.io.s3 import _default_client_factory, _split_uri

        factory: "Callable[[Any], Any]" = (
            getattr(backend, "_client_factory", None) or _default_client_factory
        )
        settings = getattr(backend, "bucket_settings", None)
        client = factory(settings)
        if root.startswith("s3://"):
            bucket, prefix = _split_uri(root, settings)
        else:
            bucket = getattr(settings, "bucket_name", None) or ""
            prefix = root
            if not bucket:
                raise ValueError(
                    "S3 persistence root must be s3://bucket/prefix or "
                    "bucket_settings must carry bucket_name"
                )
        return S3ObjectStore(client, bucket, prefix)
    if backend.kind == "azure":
        factory = getattr(backend, "_client_factory", None)
        account = getattr(backend, "account", None)
        kw = getattr(backend, "kwargs", {})
        if factory is not None:
            client = factory(account)
        else:
            client = _default_azure_factory(account, root, kw)
        # container from kwargs -> the WHOLE root is the blob prefix; otherwise
        # the root's first segment names the container and the rest prefixes
        if kw.get("container") or factory is not None:
            prefix = root
        else:
            prefix = root.split("/", 1)[1] if "/" in root else ""
        return AzureObjectStore(client, prefix)
    raise ValueError(f"no object store for backend kind {backend.kind!r}")
