"""Replica feed: the durable handoff lane between a primary and its read
replicas.

The read-replica fleet (``parallel/replica.py``) never joins the ingest mesh —
it bootstraps from a *bounded-fragment* export of the primary's index rebuild
descriptor and then follows a compact row-delta journal tail. This module owns
that on-disk contract; everything above it (HTTP serving, staleness bounds,
routing) lives in ``parallel/replica.py``.

Layout under one feed root (a filesystem directory, typically
``<persistence root>/replica-feed`` or ``PATHWAY_REPLICA_FEED``)::

    bootstrap-{commit:010d}/header.pkl        # filter data + quant sidecars
    bootstrap-{commit:010d}/fragment-{k:06d}.pkl
    bootstrap-{commit:010d}.json              # manifest, committed LAST
    frames/{commit:010d}.frame                # per-commit row deltas > commit

Three disciplines carried over from the checkpoint manifests
(``persistence/engine.py``):

- **versioned, torn-proof bootstraps** — fragments and header land first, the
  manifest JSON is written atomically last and READ BACK before the export
  counts; a torn export of bootstrap N never destroys bootstrap N-1 (readers
  take the newest manifest whose fragment set verifies);
- **checksummed fragments** — every fragment (and the header) carries its
  sha256 in the manifest; a mismatch on the replica is
  :class:`ReplicaBootstrapError`, a typed refusal that keeps the replica OUT
  of rotation instead of serving wrong bytes;
- **bounded peak memory** — fragments hold at most
  ``PATHWAY_REPLICA_FRAGMENT_ROWS`` rows (default 4096), so replica-bootstrap
  memory stays flat as the index grows (PAPERS.md: memory-efficient
  redistribution through bounded collective steps); the writer streams them
  from ``BruteForceKnnIndex.iter_rebuild_fragments`` without materializing the
  corpus twice.

Frames are atomic (tmp + rename) with a checksummed pickle payload; a frame
that fails verification is treated as *not yet visible* (the follower stops
before it and retries), never applied torn.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

_BOOTSTRAP_DIR_FMT = "bootstrap-{commit:010d}"
_BOOTSTRAP_MANIFEST_FMT = "bootstrap-{commit:010d}.json"
_BOOTSTRAP_MANIFEST_RE = re.compile(r"^bootstrap-(\d{10})\.json$")
_FRAGMENT_FMT = "fragment-{idx:06d}.pkl"
_HEADER_NAME = "header.pkl"
_FRAMES_DIR = "frames"
_FRAME_FMT = "{commit:010d}.frame"
_FRAME_RE = re.compile(r"^(\d{10})\.frame$")
#: feed format version — a replica refuses a feed written by an incompatible
#: later layout instead of guessing at it
_FEED_VERSION = 1


def fragment_rows_from_env() -> int:
    """Rows per bootstrap fragment (``PATHWAY_REPLICA_FRAGMENT_ROWS``)."""
    try:
        return max(1, int(os.environ.get("PATHWAY_REPLICA_FRAGMENT_ROWS", "4096")))
    except ValueError:
        return 4096


class ReplicaFeedError(RuntimeError):
    """Base class for replica-feed contract violations."""


class ReplicaBootstrapError(ReplicaFeedError):
    """Torn or mismatched bootstrap state: missing fragments, checksum
    mismatch, commit disagreement between manifest and payload, or an injected
    ``replica_torn_bootstrap`` chaos fault. The replica must refuse to serve
    (stay out of rotation) — wrong bytes are worse than no replica."""


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class ReplicaFeed:
    """One feed root: primary-side writer AND replica-side reader (the two
    sides share the path/format constants by sharing the class)."""

    def __init__(self, root: str):
        self.root = str(root)

    # -- primary side: bootstrap export ------------------------------------

    def export_bootstrap(
        self,
        commit_id: int,
        index: Any,
        *,
        rows_per_fragment: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Export ``index`` (a ``BruteForceKnnIndex`` or subclass) at
        ``commit_id`` as a bounded-fragment bootstrap. Fragments + header land
        first; the manifest commits LAST, atomically, and is read back and
        re-verified before the export counts (the read-back-verified manifest
        discipline). Returns the manifest dict. Older bootstraps and frames
        at/below ``commit_id`` are pruned AFTER the new manifest verifies —
        one previous bootstrap is kept so a torn export never strands the
        fleet."""
        rows = rows_per_fragment or fragment_rows_from_env()
        commit_id = int(commit_id)
        bdir = os.path.join(self.root, _BOOTSTRAP_DIR_FMT.format(commit=commit_id))
        os.makedirs(bdir, exist_ok=True)
        os.makedirs(os.path.join(self.root, _FRAMES_DIR), exist_ok=True)
        header, fragments = iter_rebuild_fragments(index, rows)
        header_blob = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
        _atomic_write(os.path.join(bdir, _HEADER_NAME), header_blob)
        frag_entries: List[Dict[str, Any]] = []
        total_rows = 0
        for idx, frag in enumerate(fragments):
            blob = pickle.dumps(frag, protocol=pickle.HIGHEST_PROTOCOL)
            name = _FRAGMENT_FMT.format(idx=idx)
            _atomic_write(os.path.join(bdir, name), blob)
            n = len(frag["keys"])
            total_rows += n
            frag_entries.append({"name": name, "sha256": _sha256(blob), "rows": n})
        manifest = {
            "version": _FEED_VERSION,
            "commit": commit_id,
            "header_sha256": _sha256(header_blob),
            "fragments": frag_entries,
            "rows": total_rows,
        }
        mpath = os.path.join(
            self.root, _BOOTSTRAP_MANIFEST_FMT.format(commit=commit_id)
        )
        _atomic_write(
            mpath, json.dumps(manifest, sort_keys=True).encode("utf-8")
        )
        # read-back verification: the export only counts if a fresh reader
        # accepts it end to end (catches torn fragments AND manifest bugs)
        readback = self.latest_bootstrap()
        if readback is None or int(readback["commit"]) != commit_id:
            raise ReplicaFeedError(
                f"replica bootstrap {commit_id} failed read-back verification "
                f"(latest readable: {readback and readback['commit']})"
            )
        self._prune(commit_id)
        return manifest

    def _prune(self, newest_commit: int) -> None:
        """Drop bootstraps older than the previous one and frames at/below the
        OLDER kept bootstrap (frames above it must survive: a replica booting
        from the previous bootstrap still needs its tail)."""
        commits = sorted(self._bootstrap_commits())
        keep = set(commits[-2:])
        for c in commits:
            if c in keep:
                continue
            try:
                os.unlink(
                    os.path.join(self.root, _BOOTSTRAP_MANIFEST_FMT.format(commit=c))
                )
            except OSError:
                pass
            bdir = os.path.join(self.root, _BOOTSTRAP_DIR_FMT.format(commit=c))
            try:
                for name in os.listdir(bdir):
                    try:
                        os.unlink(os.path.join(bdir, name))
                    except OSError:
                        pass
                os.rmdir(bdir)
            except OSError:
                pass
        floor = min(keep) if keep else newest_commit
        for commit, path in self._frame_paths():
            if commit <= floor:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- primary side: journal tail ----------------------------------------

    def record_commit(
        self,
        commit_id: int,
        keys: List[Any],
        vectors: Any,
        *,
        removals: Optional[List[Any]] = None,
        filter_data: Optional[Dict[Any, Any]] = None,
    ) -> str:
        """Append one commit's row deltas as an atomic, checksummed frame.
        ``vectors`` rows align with ``keys`` (upserts); ``removals`` are keys
        deleted this commit. Returns the frame path."""
        commit_id = int(commit_id)
        frames_dir = os.path.join(self.root, _FRAMES_DIR)
        os.makedirs(frames_dir, exist_ok=True)
        # the primary's commit span context rides the frame so a replica can
        # link its apply/serve spans back to the originating commit's trace
        # (frames carry no epoch, so the replica cannot re-derive the id)
        trace_rider: "Optional[str]" = None
        try:
            from pathway_tpu.engine.tracing import (
                current_context,
                format_trace_header,
            )

            ctx = current_context()
            if ctx is not None:
                trace_rider = format_trace_header(ctx)
        except Exception:
            trace_rider = None
        payload = pickle.dumps(
            {
                "commit": commit_id,
                "keys": list(keys),
                "vectors": np.asarray(vectors, dtype=np.float32)
                if len(keys)
                else np.zeros((0, 0), dtype=np.float32),
                "removals": list(removals or []),
                "filter_data": dict(filter_data or {}),
                "trace": trace_rider,
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        blob = _sha256(payload).encode("ascii") + b"\n" + payload
        path = os.path.join(frames_dir, _FRAME_FMT.format(commit=commit_id))
        _atomic_write(path, blob)
        return path

    # -- replica side: discovery + verified loads ---------------------------

    def _bootstrap_commits(self) -> List[int]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for name in names:
            m = _BOOTSTRAP_MANIFEST_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_bootstrap(self) -> Optional[Dict[str, Any]]:
        """The newest bootstrap manifest whose manifest JSON parses and whose
        fragment files all EXIST (cheap structural check; byte verification
        happens fragment-by-fragment during :meth:`load_bootstrap`). Torn or
        partial exports are skipped — newest valid wins, same as
        ``load_cluster_manifest``."""
        for commit in reversed(self._bootstrap_commits()):
            mpath = os.path.join(
                self.root, _BOOTSTRAP_MANIFEST_FMT.format(commit=commit)
            )
            try:
                with open(mpath, "rb") as f:
                    manifest = json.loads(f.read().decode("utf-8"))
            except (OSError, ValueError):
                continue
            if int(manifest.get("version", -1)) != _FEED_VERSION:
                continue
            if int(manifest.get("commit", -1)) != commit:
                continue
            bdir = os.path.join(
                self.root, _BOOTSTRAP_DIR_FMT.format(commit=commit)
            )
            names = set()
            try:
                names = set(os.listdir(bdir))
            except OSError:
                continue
            if _HEADER_NAME not in names:
                continue
            if any(e["name"] not in names for e in manifest.get("fragments", [])):
                continue
            return manifest
        return None

    def load_bootstrap(
        self,
        *,
        replica_id: int = 0,
        install_header: Callable[[Dict[str, Any]], None],
        install_fragment: Callable[[List[Any], np.ndarray], None],
        manifest: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Stream the newest verified bootstrap into an index, one bounded
        fragment at a time (peak memory: one fragment, never the corpus).
        Every byte is checksum-verified before install; any mismatch, missing
        file, or injected ``replica_torn_bootstrap`` fault raises
        :class:`ReplicaBootstrapError` — the caller must treat that as
        out-of-rotation, not retryable-by-serving. Returns the bootstrap's
        commit id."""
        manifest = manifest or self.latest_bootstrap()
        if manifest is None:
            raise ReplicaBootstrapError(
                f"no verifiable replica bootstrap under {self.root!r}"
            )
        commit = int(manifest["commit"])
        bdir = os.path.join(self.root, _BOOTSTRAP_DIR_FMT.format(commit=commit))
        torn = self._torn_bootstrap_injected(replica_id)
        header_blob = self._read_verified(
            os.path.join(bdir, _HEADER_NAME), manifest["header_sha256"], torn=torn
        )
        install_header(pickle.loads(header_blob))
        for entry in manifest.get("fragments", []):
            blob = self._read_verified(
                os.path.join(bdir, entry["name"]), entry["sha256"], torn=torn
            )
            frag = pickle.loads(blob)
            install_fragment(frag["keys"], frag["vectors"])
        return commit

    @staticmethod
    def _torn_bootstrap_injected(replica_id: int) -> bool:
        from pathway_tpu.internals.chaos import get_chaos

        chaos = get_chaos()
        return chaos is not None and chaos.replica_fault(
            "replica_torn_bootstrap", replica_id
        )

    @staticmethod
    def _read_verified(path: str, want_sha: str, *, torn: bool = False) -> bytes:
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as exc:
            raise ReplicaBootstrapError(
                f"bootstrap fragment unreadable: {path!r} ({exc})"
            ) from exc
        if torn:
            # injected torn read: drop the tail so the checksum below fails
            # the same way a real torn/partial install would
            blob = blob[: max(0, len(blob) - 8)]
        if _sha256(blob) != want_sha:
            raise ReplicaBootstrapError(
                f"bootstrap fragment checksum mismatch: {path!r} "
                "(torn or mismatched export; refusing to serve from it)"
            )
        return blob

    def _frame_paths(self) -> List[Tuple[int, str]]:
        frames_dir = os.path.join(self.root, _FRAMES_DIR)
        try:
            names = os.listdir(frames_dir)
        except OSError:
            return []
        out = []
        for name in names:
            m = _FRAME_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(frames_dir, name)))
        return sorted(out)

    def frames_after(self, commit_id: int) -> List[Tuple[int, str]]:
        """(commit, path) for every tail frame strictly above ``commit_id``,
        ascending — the follower's poll primitive."""
        return [(c, p) for c, p in self._frame_paths() if c > int(commit_id)]

    def read_frame(self, path: str) -> Dict[str, Any]:
        """Verified frame payload; :class:`ReplicaFeedError` on a torn or
        checksum-failing frame (the follower stops BEFORE it and retries —
        an atomically-renamed frame should never tear, so persistent failure
        here is a real contract violation, surfaced loudly)."""
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as exc:
            raise ReplicaFeedError(f"frame unreadable: {path!r} ({exc})") from exc
        sha, _, payload = blob.partition(b"\n")
        if _sha256(payload) != sha.decode("ascii", "replace"):
            raise ReplicaFeedError(
                f"frame checksum mismatch: {path!r} (torn write?)"
            )
        return pickle.loads(payload)

    def latest_frame_commit(self) -> Optional[int]:
        frames = self._frame_paths()
        return frames[-1][0] if frames else None


# -- descriptor fragmenting (shared with ops/knn.py) ---------------------------


def iter_rebuild_fragments(
    index: Any, rows_per_fragment: int
) -> Tuple[Dict[str, Any], Iterable[Dict[str, Any]]]:
    """Split an index's rebuild descriptor into a header (filter data + quant
    sidecars + geometry) and an iterator of bounded row fragments. Prefers the
    index's own streaming export (``iter_rebuild_fragments`` — the tiered
    store walks pages without concatenating the corpus); falls back to
    chunking the monolithic ``rebuild_descriptor``."""
    stream = getattr(index, "iter_rebuild_fragments", None)
    if stream is not None:
        return stream(rows_per_fragment)
    desc = index.rebuild_descriptor()
    if desc is None:
        raise ReplicaFeedError(
            "index store cannot export a rebuild descriptor (no export_rows); "
            "replica bootstrap is refused for device-opaque stores"
        )
    header = {k: v for k, v in desc.items() if k not in ("keys", "vectors")}
    keys, vectors = desc["keys"], desc["vectors"]

    def chunks() -> Iterable[Dict[str, Any]]:
        for lo in range(0, len(keys), rows_per_fragment) or [0]:
            yield {
                "keys": list(keys[lo : lo + rows_per_fragment]),
                "vectors": np.asarray(
                    vectors[lo : lo + rows_per_fragment], dtype=np.float32
                ),
            }

    return header, chunks()


def default_feed_root(persistence_root: Optional[str]) -> Optional[str]:
    """Where the feed lives when ``PATHWAY_REPLICA_FEED`` is unset: beside the
    persistence journal for fs backends, else a run-scoped tempdir fallback
    chosen by the caller."""
    env = os.environ.get("PATHWAY_REPLICA_FEED")
    if env:
        return env
    if persistence_root:
        return os.path.join(str(persistence_root), "replica-feed")
    return None
