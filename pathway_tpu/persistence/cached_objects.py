"""Versioned cached-object storage: pin downloaded connector objects durably.

Parity target: reference ``src/persistence/cached_object_storage.rs:377``. The
reference pins every downloaded S3/FS object (raw bytes + file-like metadata)
under the persistence backend so that a resumed pipeline can (a) skip
re-downloading unchanged objects and (b) reproduce a deleted/replaced object's
old content for retractions — and can REWIND the store to the version a
checkpoint refers to, dropping newer events.

This engine's fs/s3 scanners already journal parsed rows in-band (their
``push_state`` deltas), which covers (a)/(b) for the built-in readers; this
component provides the same durable URI -> (blob, metadata) contract for
custom connectors and for raw-bytes pinning, with the reference's versioned
event log + rewind semantics, over the local persistence layout (one
``<version>.blob`` / ``<version>.meta`` pair per event under ``objects/``).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Iterable, Optional

_OBJECTS_DIR = "objects"
_BLOB_EXT = ".blob"
_META_EXT = ".meta"


class CachedObjectStorage:
    """Durable, versioned URI -> (blob, metadata) store.

    Every ``place_object``/``remove_object`` appends an event at the next
    version; lookups answer from the latest state; ``rewind(version)`` undoes
    (and durably deletes) every event newer than ``version``, then prunes
    events shadowed by newer ones. A fresh instance over the same root replays
    the surviving events, so the state survives restarts.
    """

    def __init__(self, root: "str | os.PathLike | None", store: Any = None):
        # root=None, store=None -> in-memory only (mock/memory persistence
        # backends); store=ObjectStore -> durable over S3/Azure-style objects
        self._store = store
        self._dir = (
            None if (root is None or store is not None) else os.path.join(str(root), _OBJECTS_DIR)
        )
        if self._dir is not None:
            os.makedirs(self._dir, exist_ok=True)
        self._events: Dict[int, tuple] = {}  # version -> (uri, meta | None=delete)
        self._blobs: Dict[int, bytes] = {}  # in-memory blobs (root=None)
        self._latest: Dict[str, int] = {}  # uri -> version of its live event
        self._version = 0
        if self._dir is not None or self._store is not None:
            self._reload()

    # -- event persistence ----------------------------------------------------

    def _meta_path(self, version: int) -> str:
        return os.path.join(self._dir, f"{version}{_META_EXT}")

    def _blob_path(self, version: int) -> str:
        return os.path.join(self._dir, f"{version}{_BLOB_EXT}")

    def _meta_key(self, version: int) -> str:
        return f"{_OBJECTS_DIR}/{version}{_META_EXT}"

    def _blob_key(self, version: int) -> str:
        return f"{_OBJECTS_DIR}/{version}{_BLOB_EXT}"

    def _iter_meta_payloads(self) -> "Iterable[bytes]":
        if self._store is not None:
            for key in self._store.list(f"{_OBJECTS_DIR}/"):
                if key.endswith(_META_EXT):
                    blob = self._store.get(key)
                    if blob is not None:
                        yield blob
            return
        for name in os.listdir(self._dir):
            if name.endswith(_META_EXT):
                try:
                    with open(os.path.join(self._dir, name), "rb") as f:
                        yield f.read()
                except OSError:
                    continue

    def _reload(self) -> None:
        for payload in self._iter_meta_payloads():
            try:
                event = json.loads(payload)
                version = int(event["version"])
            except (ValueError, KeyError):
                continue  # torn write: a partial event never becomes state
            self._events[version] = (
                event["uri"],
                event["metadata"] if event["type"] == "update" else None,
            )
        self._rebuild_latest()
        self._version = max(self._events, default=0)

    def _rebuild_latest(self) -> None:
        self._latest = {}
        for version in sorted(self._events):
            uri, meta = self._events[version]
            if meta is None:
                self._latest.pop(uri, None)
            else:
                self._latest[uri] = version

    def _append_event(self, uri: str, meta: Optional[dict], blob: Optional[bytes]) -> int:
        self._version += 1
        version = self._version
        self._events[version] = (uri, meta)
        if self._store is not None:
            if blob is not None:
                self._store.put(self._blob_key(version), blob)
            # metadata written AFTER the blob: an event exists once its meta does
            self._store.put(
                self._meta_key(version),
                json.dumps(
                    {
                        "uri": uri,
                        "version": version,
                        "type": "update" if meta is not None else "delete",
                        "metadata": meta,
                    }
                ).encode(),
            )
        elif self._dir is None:
            if blob is not None:
                self._blobs[version] = blob
        else:
            if blob is not None:
                tmp = self._blob_path(version) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self._blob_path(version))
            # metadata written AFTER the blob: an event exists once its .meta does
            tmp = self._meta_path(version) + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "uri": uri,
                        "version": version,
                        "type": "update" if meta is not None else "delete",
                        "metadata": meta,
                    },
                    f,
                )
            os.replace(tmp, self._meta_path(version))
        return version

    def _drop_event(self, version: int) -> None:
        self._events.pop(version, None)
        self._blobs.pop(version, None)
        if self._store is not None:
            self._store.delete(self._meta_key(version))
            self._store.delete(self._blob_key(version))
        elif self._dir is not None:
            for path in (self._meta_path(version), self._blob_path(version)):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- reference interface ---------------------------------------------------

    def place_object(self, uri: str, blob: bytes, metadata: dict | None = None) -> int:
        """Upsert; returns the event's version."""
        version = self._append_event(uri, dict(metadata or {}), bytes(blob))
        self._latest[uri] = version
        return version

    def remove_object(self, uri: str) -> int:
        version = self._append_event(uri, None, None)
        self._latest.pop(uri, None)
        return version

    def contains_object(self, uri: str) -> bool:
        return uri in self._latest

    def get_object(self, uri: str) -> bytes:
        version = self._latest[uri]
        if self._store is not None:
            blob = self._store.get(self._blob_key(version))
            if blob is None:
                raise KeyError(uri)
            return blob
        if self._dir is None:
            return self._blobs[version]
        with open(self._blob_path(version), "rb") as f:
            return f.read()

    def get_metadata(self, uri: str) -> dict:
        return dict(self._events[self._latest[uri]][1])

    def actual_key_set(self) -> set:
        return set(self._latest)

    @property
    def current_version(self) -> int:
        return self._version

    def rewind(self, version: int) -> None:
        """Undo (and durably delete) every event newer than ``version``, then
        prune events shadowed by a newer surviving event of the same URI.
        ``rewind(0)`` clears the store.

        Pruning compacts history exactly as the reference does ("versions that
        are obsolete after the rewind … are also removed"): rewinding is for
        ONE resume point per run — after ``rewind(v)``, a later rewind to an
        older version cannot resurrect content whose events were already
        pruned as shadowed."""
        for v in sorted((v for v in self._events if v > version), reverse=True):
            self._drop_event(v)
        self._rebuild_latest()
        live = set(self._latest.values())
        for v in list(self._events):
            if v not in live:
                # shadowed update, stale delete marker, or pre-rewind garbage:
                # nothing can resolve to it anymore
                self._drop_event(v)
        self._version = version

    def clear(self) -> None:
        self.rewind(0)
        if self._store is not None:
            for key in self._store.list(f"{_OBJECTS_DIR}/"):
                self._store.delete(key)
        elif self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)
            os.makedirs(self._dir, exist_ok=True)

    def __iter__(self) -> Iterable[tuple]:
        for uri, version in self._latest.items():
            yield uri, self._events[version][1]
