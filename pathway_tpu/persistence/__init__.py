"""Persistence configuration — checkpoint/resume.

Parity: reference ``python/pathway/persistence/__init__.py`` (``Backend.filesystem/s3/mock``
``:27-71``, ``Config`` ``:88``) over ``src/persistence/``. The engine journals input snapshots
per connector and checkpoints stateful-operator state at commit boundaries; resume replays the
journal then continues from stored offsets (see ``pathway_tpu/persistence/engine.py``).
"""

from __future__ import annotations

import os
from typing import Any, List

from pathway_tpu.persistence.cached_objects import CachedObjectStorage  # noqa: F401


class Backend:
    kind = "none"

    def __init__(self, root: str | None = None):
        self.root = root

    @classmethod
    def filesystem(cls, path: str | os.PathLike) -> "Backend":
        b = cls(str(path))
        b.kind = "filesystem"
        return b

    @classmethod
    def s3(
        cls,
        root_path: str,
        bucket_settings: Any = None,
        *,
        _client_factory: Any = None,
    ) -> "Backend":
        b = cls(root_path)
        b.kind = "s3"
        b.bucket_settings = bucket_settings
        b._client_factory = _client_factory
        return b

    @classmethod
    def azure(
        cls,
        root_path: str,
        account: Any = None,
        *,
        _client_factory: Any = None,
        **kw: Any,
    ) -> "Backend":
        b = cls(root_path)
        b.kind = "azure"
        b.account = account
        b._client_factory = _client_factory
        b.kwargs = kw
        return b

    def make_object_store(self) -> Any:
        from pathway_tpu.persistence.backends import make_object_store

        return make_object_store(self)

    @classmethod
    def mock(cls, events: Any = None) -> "Backend":
        b = cls(None)
        b.kind = "mock"
        b.events = events
        return b


class Config:
    """Persistence settings (reference ``persistence/__init__.py:88``).

    ``persistence_mode="silent_replay"`` keeps output callbacks / external sinks from
    re-receiving already-delivered rows during journal replay on resume (the default
    re-delivers, matching the reference's speedrun replay where sinks dedup by key).

    ``backend_retry_strategy`` governs transient object-store (s3/azure) failures:
    by default every journal/checkpoint op retries with exponential backoff
    (``udfs.ExponentialBackoffRetryStrategy``); pass ``udfs.NoRetryStrategy()`` to
    fail fast, or a custom strategy to tune the schedule.
    """

    def __init__(
        self,
        backend: Backend | None = None,
        *,
        snapshot_interval_ms: int = 0,
        snapshot_access: Any = None,
        persistence_mode: Any = None,
        continue_after_replay: bool = True,
        backend_retry_strategy: Any = None,
    ):
        self.backend = backend
        self.snapshot_interval_ms = snapshot_interval_ms
        self.snapshot_access = snapshot_access
        self.persistence_mode = persistence_mode
        self.continue_after_replay = continue_after_replay
        self.backend_retry_strategy = backend_retry_strategy

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs: Any) -> "Config":
        return cls(backend, **kwargs)
