"""Persistence engine: input journal + source-offset snapshots + replay resume.

Parity: reference ``src/persistence/`` — input snapshots journal every connector's parsed
events per worker (``input_snapshot.rs``), offsets let readers seek past replayed data
(``offset.rs:37``, ``frontier.rs``/``tracker.rs`` threshold times), and
``Connector::read_snapshot`` (``connectors/mod.rs:472``) replays the journal before
realtime reads resume.

Design here (batch-incremental engine): every commit's *input* deltas are appended to a
single journal file as length-prefixed pickle frames — everything downstream is
deterministic, so replaying the journal reconstructs all operator state exactly. A crash
mid-write leaves a truncated final frame, which the loader discards (the reference gets the
same guarantee from chunked binary logs). Source offsets (event counts + optional
subject state) ride in each frame; heavyweight subject state (e.g. the fs scanner's
seen-files map — the analogue of ``cached_object_storage.rs``) is dumped separately at
``snapshot_interval`` and paired with skip-counts on resume.
"""

from __future__ import annotations

import io
import os
import pickle
import struct
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from pathway_tpu.engine.columnar import Delta

_FRAME_HEADER = struct.Struct(">Q")
_JOURNAL = "journal.bin"
_SOURCES = "sources.pkl"
_HEADER_MAGIC = b"PWTPUJ1\n"


def _delta_to_payload(delta: Delta) -> tuple:
    return (
        delta.keys.tobytes(),
        delta.diffs,
        {n: c for n, c in delta.columns.items()},
        delta.neu,
    )


def _payload_to_delta(payload: tuple) -> Delta:
    from pathway_tpu.internals.keys import KEY_DTYPE

    keys_b, diffs, columns, neu = payload
    keys = np.frombuffer(keys_b, dtype=KEY_DTYPE).copy()
    return Delta(keys, diffs, columns, neu=neu)


class PersistenceManager:
    """Owns the journal + source-state files for one pipeline under one backend root."""

    def __init__(self, config: Any):
        backend = config.backend
        if backend is None or backend.kind not in ("filesystem", "memory", "mock"):
            raise ValueError(
                f"persistence backend {getattr(backend, 'kind', None)!r} not supported; "
                "use pw.persistence.Backend.filesystem(path)"
            )
        self.config = config
        self.root = backend.root
        self._memory = backend.kind in ("memory", "mock") or self.root is None
        self._mem_journal: io.BytesIO = io.BytesIO()
        self._mem_sources: bytes | None = None
        self._journal_file: Any = None
        self._last_sources_dump = 0.0
        self.snapshot_interval_s = (config.snapshot_interval_ms or 0) / 1000.0
        if not self._memory:
            os.makedirs(self.root, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def _journal_path(self) -> str:
        return os.path.join(self.root, _JOURNAL)

    def _sources_path(self) -> str:
        return os.path.join(self.root, _SOURCES)

    # -- journal write path --------------------------------------------------

    def open_for_append(self, graph_sig: str) -> None:
        if self._memory:
            if self._mem_journal.getbuffer().nbytes == 0:
                self._mem_journal.write(_HEADER_MAGIC + graph_sig.encode() + b"\n")
            return
        fresh = not os.path.exists(self._journal_path())
        self._journal_file = open(self._journal_path(), "ab")
        if fresh:
            self._journal_file.write(_HEADER_MAGIC + graph_sig.encode() + b"\n")
            self._journal_file.flush()
            os.fsync(self._journal_file.fileno())

    def record_commit(
        self,
        commit_id: int,
        input_deltas: Dict[int, Delta],
        offsets: Dict[int, dict],
    ) -> None:
        """Append one frame: the commit's input deltas + light per-source offsets."""
        frame = pickle.dumps(
            (
                commit_id,
                {nid: _delta_to_payload(d) for nid, d in input_deltas.items()},
                offsets,
            ),
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        buf = _FRAME_HEADER.pack(len(frame)) + frame
        if self._memory:
            self._mem_journal.write(buf)
        else:
            self._journal_file.write(buf)
            self._journal_file.flush()

    def maybe_dump_sources(self, states: Dict[int, Any], offsets: Dict[int, dict]) -> None:
        """Periodically persist heavyweight subject state (atomic rename for crash
        consistency), tagged with the offsets it corresponds to."""
        now = time.monotonic()
        if now - self._last_sources_dump < max(self.snapshot_interval_s, 1e-9):
            return
        self._last_sources_dump = now
        blob = pickle.dumps((states, offsets), protocol=pickle.HIGHEST_PROTOCOL)
        if self._memory:
            self._mem_sources = blob
            return
        tmp = self._sources_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._sources_path())

    def close(self) -> None:
        if self._journal_file is not None:
            self._journal_file.close()
            self._journal_file = None

    # -- journal read path ---------------------------------------------------

    def load_journal(self, graph_sig: str) -> List[Tuple[int, Dict[int, Delta], Dict[int, dict]]]:
        """All complete frames; a truncated tail frame (crash mid-write) is dropped."""
        if self._memory:
            data = self._mem_journal.getvalue()
        else:
            if not os.path.exists(self._journal_path()):
                return []
            with open(self._journal_path(), "rb") as f:
                data = f.read()
        if not data.startswith(_HEADER_MAGIC):
            return []
        nl = data.index(b"\n", len(_HEADER_MAGIC))
        stored_sig = data[len(_HEADER_MAGIC) : nl].decode()
        if stored_sig != graph_sig:
            raise ValueError(
                "persisted journal was written by a different dataflow graph; "
                "clear the persistence directory or keep the program unchanged"
            )
        pos = nl + 1
        frames: List[Tuple[int, Dict[int, Delta], Dict[int, dict]]] = []
        while pos + _FRAME_HEADER.size <= len(data):
            (length,) = _FRAME_HEADER.unpack_from(data, pos)
            start = pos + _FRAME_HEADER.size
            if start + length > len(data):
                break  # truncated tail frame — crash during write; discard
            commit_id, payloads, offsets = pickle.loads(data[start : start + length])
            frames.append(
                (commit_id, {nid: _payload_to_delta(p) for nid, p in payloads.items()}, offsets)
            )
            pos = start + length
        return frames

    def load_sources(self) -> Optional[Tuple[Dict[int, Any], Dict[int, dict]]]:
        if self._memory:
            return pickle.loads(self._mem_sources) if self._mem_sources else None
        if not os.path.exists(self._sources_path()):
            return None
        try:
            with open(self._sources_path(), "rb") as f:
                return pickle.loads(f.read())
        except Exception:
            return None  # torn write of the tmp file never renamed; ignore
