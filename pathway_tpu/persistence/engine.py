"""Persistence engine: input journal + segment-state deltas + replay resume.

Parity: reference ``src/persistence/`` — input snapshots journal every connector's parsed
events per worker (``input_snapshot.rs``), offsets let readers seek past replayed data
(``offset.rs:37``, ``frontier.rs``/``tracker.rs`` threshold times), and
``Connector::read_snapshot`` (``connectors/mod.rs:472``) replays the journal before
realtime reads resume.

Design here (batch-incremental engine): every commit's *input* deltas are appended to a
single journal file as length-prefixed pickle frames — everything downstream is
deterministic, so replaying the journal reconstructs all operator state exactly. Frames are
fsynced, so a crash can only lose the in-flight frame; its torn bytes are detected on load
and truncated away before new appends (the reference gets the same guarantee from chunked
binary logs). Each frame also carries light per-source offsets: consumed counts, sequence
cursors, and the segment-state deltas sources pushed that commit (the analogue of
``cached_object_storage.rs`` — replay repositions scanners without re-reading data).
"""

from __future__ import annotations

import io
import json
import os
import pickle
import shutil
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pathway_tpu.engine.columnar import Delta
from pathway_tpu.internals.keys import KEY_DERIVATION_VERSION

_FRAME_HEADER = struct.Struct(">Q")
_JOURNAL = "journal.bin"
_CHECKPOINT = "checkpoint.pkl"
_STORE_META = "store.meta"
# cluster-coordinated checkpoints: per-rank snapshots are VERSIONED by commit id
# (the manifest names the commit every rank snapshotted at), unlike the
# single-process checkpoint.pkl which is always "the latest"
_CLUSTER_SNAPSHOT_FMT = "checkpoint-{commit:010d}.pkl"
# the cluster checkpoint manifest lives at the UNSHARDED base root (it spans
# every rank's shard) and is versioned too: a torn write of manifest N must
# never destroy manifest N-1, so recovery can always fall back one checkpoint
_CLUSTER_MANIFEST_FMT = "cluster-manifest-{commit:010d}.json"
_CLUSTER_MANIFEST_PREFIX = "cluster-manifest-"
# v2: header line is a json meta object carrying the graph signature PLUS the
# key-derivation version and worker count — frames store derived row keys, so a
# journal from a build with different key derivation (or replayed under a
# different shard layout) must be refused, not silently resumed
_HEADER_MAGIC = b"PWTPUJ2\n"
# known-incompatible prior formats: loading one must fail LOUDLY (v1 journals
# predate the splitmix int-key derivation — their stored keys no longer match
# keys this build derives for the same values)
_OLD_MAGICS = (b"PWTPUJ1\n",)

_OLD_FORMAT_ERROR = (
    "persisted journal was written by an incompatible earlier build (format v1, "
    "before the splitmix key-derivation change): its stored row keys no longer "
    "match keys this build derives for the same values, so replayed rows would "
    "become unreachable for updates/retractions — clear the persistence "
    "directory to start fresh"
)


def _delta_to_payload(delta: Delta) -> tuple:
    return (
        delta.keys.tobytes(),
        delta.diffs,
        {n: c for n, c in delta.columns.items()},
        delta.neu,
    )


def _payload_to_delta(payload: tuple) -> Delta:
    from pathway_tpu.internals.keys import KEY_DTYPE

    keys_b, diffs, columns, neu = payload
    keys = np.frombuffer(keys_b, dtype=KEY_DTYPE).copy()
    return Delta(keys, diffs, columns, neu=neu)


class PersistenceManager:
    """Owns the journal file for one pipeline under one backend root."""

    def __init__(self, config: Any):
        backend = config.backend
        if backend is None or backend.kind not in (
            "filesystem", "memory", "mock", "s3", "azure"
        ):
            raise ValueError(
                f"persistence backend {getattr(backend, 'kind', None)!r} not supported; "
                "use pw.persistence.Backend.filesystem/s3/azure(...)"
            )
        self.config = config
        self.root = backend.root
        self._object_store: Any = None
        self._object_prefix = ""
        self._next_seq = 0
        if backend.kind in ("s3", "azure"):
            # object-store mode: journal frames are immutable numbered objects —
            # object stores have no append, and a PUT per commit frame gives the
            # fs backend's fsync-per-frame crash guarantee (a frame either fully
            # exists or doesn't; no torn tails)
            store = backend.make_object_store()
            from pathway_tpu.internals.chaos import get_chaos

            chaos = get_chaos()
            if chaos is not None:
                # fault injection sits BELOW the retry layer: injected transient
                # write errors must be absorbed exactly like real ones
                store = chaos.wrap_object_store(store)
            retry_strategy = getattr(config, "backend_retry_strategy", None)
            from pathway_tpu.internals.udfs import NoRetryStrategy

            if not isinstance(retry_strategy, NoRetryStrategy):
                from pathway_tpu.persistence.backends import RetryingObjectStore

                store = RetryingObjectStore(store, retry_strategy)
            self._object_store = store
            self._memory = False
        else:
            self._memory = backend.kind in ("memory", "mock") or self.root is None
        from pathway_tpu.internals.config import get_pathway_config

        cfg = get_pathway_config()
        self._workers = max(1, int(getattr(cfg, "processes", 1) or 1))
        # the UNSHARDED root: the store-wide meta object lives here so a reopen
        # with a different worker count is detected even though each worker only
        # reads its own process-{id}/ shard
        self._base_root = self.root
        if cfg.processes > 1 and (self._object_store is not None or not self._memory):
            # spawned replicas each own a journal shard; a shared file would
            # interleave frames from different processes into garbage
            if self._object_store is not None:
                self._object_prefix = f"process-{cfg.process_id}/"
            else:
                self.root = os.path.join(str(self.root), f"process-{cfg.process_id}")
        self._mem_journal: io.BytesIO = io.BytesIO()
        self._journal_file: Any = None
        # id of the last frame THIS incarnation appended (None before the first):
        # the surgical-rejoin fence uses it to tell a journaled in-flight commit
        # (already durable, must not be re-ingested) from a lost one (its drained
        # input rows must be carried over the rollback)
        self.last_commit_id: Optional[int] = None
        # byte offset of the last complete frame, set by load_journal; open_for_append
        # truncates torn tail bytes past it so new frames never land after garbage
        self._valid_end: Optional[int] = None
        # frames appended since the last compaction — the journal-tail length the
        # recovery SLO metrics report at each coordinated checkpoint
        self.frames_since_compact = 0
        if not self._memory and self._object_store is None:
            os.makedirs(self.root, exist_ok=True)

    @property
    def supports_cluster_checkpoints(self) -> bool:
        """Cluster-coordinated checkpoints need a store every rank (and a
        relaunched replacement) can reopen — any durable backend. The in-memory
        backends are per-process and die with the rank, so there is nothing a
        manifest could coordinate."""
        return self._object_store is not None or not self._memory

    def _journal_path(self) -> str:
        return os.path.join(self.root, _JOURNAL)

    # -- journal write path --------------------------------------------------

    # -- object-store mode helpers -------------------------------------------

    def _meta_key(self) -> str:
        return f"{self._object_prefix}meta"

    def _frame_key(self, seq: int) -> str:
        return f"{self._object_prefix}journal/{seq:010d}.frame"

    def _checkpoint_key(self) -> str:
        return f"{self._object_prefix}{_CHECKPOINT}"

    # -- versioned header / store-wide meta ----------------------------------

    def _header_bytes(self, graph_sig: str) -> bytes:
        meta = {
            "sig": graph_sig,
            "key_derivation": KEY_DERIVATION_VERSION,
            "workers": self._workers,
        }
        return _HEADER_MAGIC + json.dumps(meta, sort_keys=True).encode() + b"\n"

    def _check_meta(self, meta: dict, what: str) -> None:
        """Refuse to resume state this build cannot replay correctly."""
        kv = meta.get("key_derivation")
        if kv != KEY_DERIVATION_VERSION:
            raise ValueError(
                f"persisted {what} was written with key-derivation v{kv} but this "
                f"build derives v{KEY_DERIVATION_VERSION} keys; replayed rows would "
                "become unreachable for updates/retractions — clear the persistence "
                "directory to start fresh"
            )
        workers = meta.get("workers")
        if workers != self._workers:
            # typed (membership-aware): the supervisor reads manifest_n off
            # this error's status report to adapt -n after a mid-transition
            # crash, and operators get the --scale-vs-corrupt-store triage
            from pathway_tpu.parallel.membership import MembershipMismatchError

            raise MembershipMismatchError(
                what,
                manifest_n=workers,
                current_n=self._workers,
                epoch=int(meta.get("epoch", 0) or 0),
            )

    def _write_store_meta(self) -> None:
        payload = json.dumps(
            {"workers": self._workers, "key_derivation": KEY_DERIVATION_VERSION},
            sort_keys=True,
        ).encode()
        if self._object_store is not None:
            self._object_store.put(_STORE_META, payload)
            return
        if self._memory or self._base_root is None:
            return
        os.makedirs(str(self._base_root), exist_ok=True)
        path = os.path.join(str(self._base_root), _STORE_META)
        # pid-unique temp: spawned replicas race to create the meta file
        # concurrently; both write identical content, either rename may win
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _check_store_meta(self) -> None:
        """Store-WIDE guard at the unsharded root: a run with a different worker
        count reads different ``process-{id}/`` shards (possibly none), so the
        per-shard headers alone cannot catch the mismatch.

        Elastic-membership self-heal: the membership manifest is the COMMIT
        POINT of a scale transition and the meta file is updated after it, so
        a crash in between leaves meta naming the OLD count. When the newest
        manifest agrees with THIS run's count, the meta write is simply
        replayed; a genuine mismatch still refuses typed."""
        if self._object_store is not None:
            blob = self._object_store.get(_STORE_META)
            if blob is None:
                self._write_store_meta()
                return
            meta = json.loads(blob)
        elif self._memory or self._base_root is None:
            return  # in-memory stores cannot be reopened by another run
        else:
            path = os.path.join(str(self._base_root), _STORE_META)
            if not os.path.exists(path):
                self._write_store_meta()
                return
            with open(path) as f:
                meta = json.load(f)
        from pathway_tpu.parallel.membership import MembershipMismatchError

        try:
            self._check_meta(meta, "store")
        except MembershipMismatchError:
            if self._newest_manifest_workers() == self._workers:
                self._write_store_meta()
                return
            raise

    def _newest_manifest_workers(self) -> "int | None":
        """Worker count named by the newest parseable cluster manifest (the
        authoritative topology record), or None when no manifest exists."""
        best: "tuple | None" = None
        for commit_id, raw in self._manifest_candidates():
            if best is not None and commit_id <= best[0]:
                continue
            try:
                meta = json.loads(raw)
            except ValueError:
                continue
            if meta.get("commit_id") != commit_id:
                continue
            workers = meta.get("workers")
            if workers is not None:
                best = (commit_id, int(workers))
        return best[1] if best is not None else None

    def set_workers(self, workers: int) -> None:
        """Adopt a new cluster worker count mid-run (the membership
        transition, after its manifest committed): later snapshots, journal
        headers, and manifests are stamped with it, and the store-wide meta
        is brought up to date."""
        self._workers = int(workers)
        self._write_store_meta()

    def _validate_header_line(
        self, line: bytes, graph_sig: str, prefix_hint: str = "directory"
    ) -> None:
        meta = json.loads(line)
        if meta.get("sig") != graph_sig:
            raise ValueError(
                "persisted journal was written by a different dataflow graph; "
                f"clear the persistence {prefix_hint} or keep the program unchanged"
            )
        from pathway_tpu.parallel.membership import MembershipMismatchError

        try:
            self._check_meta(meta, "journal")
        except MembershipMismatchError:
            # membership-transition crash window: the manifest (the commit
            # point) already names THIS count but the shard crashed before
            # compaction rewrote its header. Every frame <= the manifest
            # commit is subsumed by it, so the stale header is harmless —
            # the next compaction rewrites it. A header disagreeing with the
            # manifest too is a genuine mismatch and still refuses.
            if self._newest_manifest_workers() != self._workers:
                raise

    def open_for_append(self, graph_sig: str) -> None:
        self._check_store_meta()
        header = self._header_bytes(graph_sig)
        if self._object_store is not None:
            if self._object_store.get(self._meta_key()) is None:
                self._object_store.put(self._meta_key(), header)
            existing = self._object_store.list(f"{self._object_prefix}journal/")
            seqs = [
                int(k.rsplit("/", 1)[-1].split(".")[0])
                for k in existing
                if k.endswith(".frame")
            ]
            self._next_seq = max(seqs) + 1 if seqs else 0
            return
        if self._memory:
            if self._valid_end is not None:
                self._mem_journal.truncate(self._valid_end)
                self._mem_journal.seek(self._valid_end)
            if self._mem_journal.getbuffer().nbytes == 0:
                self._mem_journal.write(header)
            return
        path = self._journal_path()
        if not os.path.exists(path):
            self._journal_file = open(path, "ab")
            self._journal_file.write(header)
            self._journal_file.flush()
            os.fsync(self._journal_file.fileno())
            return
        self._journal_file = open(path, "r+b")
        if self._valid_end is not None:
            self._journal_file.truncate(self._valid_end)
        self._journal_file.seek(0, os.SEEK_END)
        if self._journal_file.tell() == 0:
            # corrupt header was discarded: start a fresh journal
            self._journal_file.write(header)
            self._journal_file.flush()
            os.fsync(self._journal_file.fileno())

    def record_commit(
        self,
        commit_id: int,
        input_deltas: Dict[int, Delta],
        offsets: Dict[int, dict],
    ) -> None:
        """Append one frame: the commit's input deltas + light per-source offsets
        (consumed counts, sequence cursors, segment-state deltas). fsynced — the
        crash-consistency story depends on frames surviving power loss."""
        frame = pickle.dumps(
            (
                commit_id,
                {nid: _delta_to_payload(d) for nid, d in input_deltas.items()},
                offsets,
            ),
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        self.last_commit_id = commit_id
        self.frames_since_compact += 1
        if self._object_store is not None:
            self._object_store.put(self._frame_key(self._next_seq), frame)
            self._next_seq += 1
            return
        buf = _FRAME_HEADER.pack(len(frame)) + frame
        if self._memory:
            self._mem_journal.write(buf)
        else:
            self._journal_file.write(buf)
            self._journal_file.flush()
            os.fsync(self._journal_file.fileno())

    def close(self) -> None:
        if self._journal_file is not None:
            self._journal_file.close()
            self._journal_file = None

    def reload(self, graph_sig: str) -> List[Tuple[int, Dict[int, Delta], Dict[int, dict]]]:
        """Surgical-rejoin rollback: drop the append handle, re-read every
        durable frame of THIS rank's journal shard, and reopen for append.

        The caller (the fenced survivor, or the relaunched rank via the normal
        setup path) rebuilds its operator state by replaying the returned
        frames; the cluster's lockstep union replay then aligns commit ids
        across ranks, so everyone converges on the last cluster-wide committed
        id no matter whose journal ran ahead when the failure hit."""
        self.close()
        frames = self.load_journal(graph_sig)
        self.open_for_append(graph_sig)
        return frames

    def cached_objects(self) -> Any:
        """The pipeline's durable URI -> (blob, metadata) store (reference
        ``cached_object_storage.rs:377``), rooted under this manager's backend
        directory; in-memory under mock/memory backends."""
        from pathway_tpu.persistence.cached_objects import CachedObjectStorage

        cache = getattr(self, "_cached_objects", None)
        if cache is None:
            if self._object_store is not None:
                from pathway_tpu.persistence.backends import PrefixedStore

                # share the journal's per-process namespace: replicas must not
                # interleave cached-object versions in one objects/ tree
                store = (
                    PrefixedStore(self._object_store, self._object_prefix)
                    if self._object_prefix
                    else self._object_store
                )
                cache = CachedObjectStorage(None, store=store)
            else:
                cache = CachedObjectStorage(None if self._memory else self.root)
            self._cached_objects = cache
        return cache

    # -- operator snapshots (reference ``operator_snapshot.rs`` + compaction) --

    def dump_checkpoint(self, graph_sig: str, commit_id: int, blob: dict) -> None:
        """Atomically persist a full engine checkpoint (operator + source state), then
        compact the journal: frames ≤ ``commit_id`` are subsumed by the checkpoint.
        Crash between the two steps is safe — load skips subsumed frames by id."""
        payload = self._snapshot_payload(graph_sig, commit_id, blob)
        if self._object_store is not None:
            # single-PUT checkpoint is atomic per key; then compact by deleting
            # the subsumed frame objects. A crash between the two steps leaves
            # stale frames <= commit_id, which load skips by id.
            self._object_store.put(self._checkpoint_key(), payload)
            self.compact_journal(graph_sig)
            return
        if self._memory:
            self._mem_checkpoint = payload
            self.compact_journal(graph_sig)
            return
        tmp = os.path.join(self.root, _CHECKPOINT + ".tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, _CHECKPOINT))
        # compact: restart the journal after the checkpointed commit
        self.compact_journal(graph_sig)

    def load_checkpoint(self, graph_sig: str) -> Optional[Tuple[int, dict]]:
        if self._object_store is not None:
            payload = self._object_store.get(self._checkpoint_key())
            if payload is None:
                return None
        elif self._memory:
            payload = getattr(self, "_mem_checkpoint", None)
            if payload is None:
                return None
        else:
            path = os.path.join(self.root, _CHECKPOINT)
            if not os.path.exists(path):
                return None
            try:
                with open(path, "rb") as f:
                    payload = f.read()
            except OSError:
                return None
        try:
            data = pickle.loads(payload)
        except Exception as exc:
            # the journal was compacted when this checkpoint was written — treating a
            # corrupt checkpoint as absent would silently lose all compacted history
            raise ValueError(
                "persisted checkpoint is unreadable; the journal alone cannot restore "
                "state (it was compacted) — restore checkpoint.pkl from a copy or clear "
                "the persistence directory to start fresh"
            ) from exc
        if data.get("sig") != graph_sig:
            raise ValueError(
                "persisted checkpoint was written by a different dataflow graph; "
                "clear the persistence directory or keep the program unchanged"
            )
        self._check_meta(data, "checkpoint")
        return data["commit_id"], data["state"]

    # -- cluster-coordinated checkpoints (manifest + per-rank snapshots) ------
    #
    # Protocol (driven by GraphRunner._coordinated_checkpoint, one attempt per
    # cluster at one lockstep commit id):
    #   1. every rank writes its VERSIONED snapshot (dump_cluster_snapshot) —
    #      atomic + fsynced, no compaction yet;
    #   2. ranks allgather durability acks;
    #   3. rank 0 commits the manifest (commit_cluster_manifest) naming the
    #      commit id and every rank's snapshot — written atomically, then READ
    #      BACK and validated before it counts (a store that tears the bytes
    #      must fail the checkpoint, not poison recovery);
    #   4. after a durability barrier, every rank compacts its journal shard
    #      and prunes snapshots/manifests older than the manifest commit.
    # A crash at ANY point leaves the previous manifest + its snapshots + the
    # uncompacted journal intact: recovery falls back one checkpoint,
    # bit-identically.

    def _cluster_snapshot_name(self, commit_id: int) -> str:
        return _CLUSTER_SNAPSHOT_FMT.format(commit=commit_id)

    def _snapshot_payload(self, graph_sig: str, commit_id: int, blob: dict) -> bytes:
        return pickle.dumps(
            {
                "sig": graph_sig,
                "commit_id": commit_id,
                "state": blob,
                "key_derivation": KEY_DERIVATION_VERSION,
                "workers": self._workers,
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )

    def dump_cluster_snapshot(self, graph_sig: str, commit_id: int, blob: dict) -> int:
        """Write this rank's snapshot for one coordinated checkpoint attempt.
        Atomic + durable, NO journal compaction (that waits for the manifest
        barrier). Returns the snapshot size in bytes. Raises ``ConnectionError``
        /``OSError`` on backend failure (including injected chaos faults) — the
        caller acks "transient" and the cluster keeps the previous checkpoint."""
        from pathway_tpu.internals.chaos import get_chaos

        chaos = get_chaos()
        if chaos is not None and chaos.checkpoint_fault("snapshot_error", self._rank_id()):
            from pathway_tpu.internals.chaos import ChaosBackendError

            raise ChaosBackendError(
                f"chaos: injected snapshot write error at commit {commit_id}"
            )
        payload = self._snapshot_payload(graph_sig, commit_id, blob)
        name = self._cluster_snapshot_name(commit_id)
        if self._object_store is not None:
            self._object_store.put(f"{self._object_prefix}{name}", payload)
            return len(payload)
        if self._memory:
            raise OSError("cluster checkpoints need a durable persistence backend")
        tmp = os.path.join(self.root, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, name))
        return len(payload)

    def load_cluster_snapshot(self, graph_sig: str, commit_id: int) -> dict:
        """This rank's snapshot named by a durable manifest. Loud on ANY
        failure: the manifest promised this snapshot exists, and the journal
        frames it subsumes were compacted away — treating it as absent would
        silently lose all checkpointed history."""
        name = self._cluster_snapshot_name(commit_id)
        payload: "bytes | None" = None
        if self._object_store is not None:
            payload = self._object_store.get(f"{self._object_prefix}{name}")
        elif not self._memory:
            path = os.path.join(self.root, name)
            try:
                with open(path, "rb") as f:
                    payload = f.read()
            except OSError:
                payload = None
        if payload is None:
            raise ValueError(
                f"cluster checkpoint snapshot {name!r} named by the manifest is "
                "missing from this rank's shard; the journal alone cannot restore "
                "state (it was compacted) — restore the snapshot from a copy or "
                "clear the persistence directory to start fresh"
            )
        try:
            data = pickle.loads(payload)
        except Exception as exc:
            raise ValueError(
                f"cluster checkpoint snapshot {name!r} is unreadable; the journal "
                "alone cannot restore state (it was compacted) — restore the "
                "snapshot from a copy or clear the persistence directory"
            ) from exc
        if data.get("sig") != graph_sig:
            raise ValueError(
                "cluster checkpoint snapshot was written by a different dataflow "
                "graph; clear the persistence directory or keep the program unchanged"
            )
        self._check_meta(data, "checkpoint snapshot")
        return data["state"]

    def _rank_id(self) -> int:
        from pathway_tpu.internals.config import get_pathway_config

        return int(getattr(get_pathway_config(), "process_id", 0) or 0)

    def _manifest_name(self, commit_id: int) -> str:
        return _CLUSTER_MANIFEST_FMT.format(commit=commit_id)

    def commit_cluster_manifest(
        self, graph_sig: str, commit_id: int, epoch: int = 0
    ) -> bool:
        """Rank 0 only: durably commit the cluster checkpoint manifest, then
        read it back and validate before declaring success. Returns False when
        the write tore (injected or store-side) — the cluster then skips
        compaction and the previous checkpoint stands."""
        from pathway_tpu.internals.chaos import get_chaos

        meta = {
            "format": 1,
            "sig": graph_sig,
            "commit_id": int(commit_id),
            "epoch": int(epoch),
            "workers": self._workers,
            "key_derivation": KEY_DERIVATION_VERSION,
            "snapshots": {
                str(rank): f"process-{rank}/{self._cluster_snapshot_name(commit_id)}"
                if self._workers > 1
                else self._cluster_snapshot_name(commit_id)
                for rank in range(self._workers)
            },
        }
        payload = json.dumps(meta, sort_keys=True).encode()
        chaos = get_chaos()
        if chaos is not None and chaos.checkpoint_fault("torn_manifest", self._rank_id()):
            payload = payload[: max(1, len(payload) // 2)]  # simulated torn PUT
        name = self._manifest_name(commit_id)
        if self._object_store is not None:
            self._object_store.put(name, payload)  # base root: UNPREFIXED key
        else:
            assert self._base_root is not None
            os.makedirs(str(self._base_root), exist_ok=True)
            tmp = os.path.join(str(self._base_root), name + f".tmp.{os.getpid()}")
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(str(self._base_root), name))
        # read-back verification: the manifest only counts if a fresh reader
        # would accept it — this is what turns a torn write into a clean
        # "checkpoint failed, previous one stands" instead of data loss
        try:
            loaded = self.load_cluster_manifest(graph_sig)
        except ValueError:
            return False
        return loaded is not None and loaded["commit_id"] == int(commit_id)

    # -- elastic membership: handoff fragments + membership manifest ----------
    #
    # A membership transition (parallel/membership.py, driven by
    # GraphRunner._membership_transition) reshards the cluster at one
    # quiesced commit id C:
    #   1. every OLD rank writes one handoff fragment per NEW rank under its
    #      own shard (``process-r/reshard-C/frag-j.pkl``), read-back verified
    #      — fragments are complete partitions, so the set of fragments
    #      addressed to rank j IS rank j's full checkpoint at C;
    #   2. rank 0 commits a MEMBERSHIP manifest: a cluster manifest whose
    #      ``workers`` is the NEW count and whose snapshots are the fragment
    #      sets — the atomic commit point of the transition (then the
    #      store-wide meta is brought up to date, self-healed on crash);
    #   3. every rank compacts its journal (frames <= C are subsumed).
    # A joiner (or any rank recovering after the transition) cold-starts
    # from the membership manifest + its fragments + the journal tail — the
    # same bounded-recovery contract as a PR-6 replacement, never a
    # full-history replay.

    def _reshard_dir(self, commit_id: int) -> str:
        return f"reshard-{commit_id:010d}"

    def _fragment_name(self, commit_id: int, dest: int) -> str:
        return f"{self._reshard_dir(commit_id)}/frag-{dest:05d}.pkl"

    def _chunk_name(self, commit_id: int, dest: int, idx: int) -> str:
        return f"{self._reshard_dir(commit_id)}/frag-{dest:05d}.c{idx:04d}.pkl"

    def _chunk_manifest_name(self, commit_id: int, dest: int) -> str:
        return f"{self._reshard_dir(commit_id)}/frag-{dest:05d}.mf"

    def _write_frag_blob(self, name: str, payload: bytes) -> bytes:
        """Durably write one handoff blob under this rank's shard and return
        the bytes a fresh reader sees (the read-back the verifications run
        on). Raises on a memory-only store — membership handoffs need a
        durable backend."""
        if self._object_store is not None:
            key = f"{self._object_prefix}{name}"
            self._object_store.put(key, payload)
            back = self._object_store.get(key)
            return b"" if back is None else back
        if self._memory:
            raise OSError(
                "membership handoff needs a durable persistence backend"
            )
        path = os.path.join(self.root, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with open(path, "rb") as f:
            return f.read()

    def _read_donor_blob(self, donor: int, name: str) -> "bytes | None":
        if self._object_store is not None:
            return self._object_store.get(f"process-{donor}/{name}")
        if self._memory or self._base_root is None:
            return None
        # membership transitions only exist for sharded stores
        # (spawn -n >= 2), so donor shards are always process-<r>/
        shard = os.path.join(str(self._base_root), f"process-{donor}")
        try:
            with open(os.path.join(shard, name), "rb") as f:
                return f.read()
        except OSError:
            return None

    def dump_reshard_fragments(
        self, graph_sig: str, commit_id: int, fragments: Dict[int, dict]
    ) -> int:
        """Gather-transport handoff dump: write this rank's fragments (one
        per new rank) under its own shard, then READ EACH BACK and verify it
        unpickles — a torn fragment must fail the transition's ack barrier,
        not poison a later import. Returns total bytes written. Raises
        ``ConnectionError``/``OSError``/``ValueError`` on failure (incl.
        injected chaos faults); the caller acks "transient" and the
        transition aborts cleanly."""
        from pathway_tpu.internals.chaos import get_chaos

        chaos = get_chaos()
        total = 0
        for dest, frag in sorted(fragments.items()):
            payload = pickle.dumps(
                {"sig": graph_sig, **frag}, protocol=pickle.HIGHEST_PROTOCOL
            )
            if chaos is not None and chaos.scale_fault(
                "handoff_torn", self._rank_id()
            ):
                payload = payload[: max(1, len(payload) // 2)]  # torn write
            name = self._fragment_name(commit_id, dest)
            back = self._write_frag_blob(name, payload)
            try:
                got = pickle.loads(back)
            except Exception as exc:
                raise ValueError(
                    f"handoff fragment {name!r} failed read-back verification "
                    "(torn write) — aborting this membership attempt"
                ) from exc
            if got.get("sig") != graph_sig or got.get("from_rank") != frag.get(
                "from_rank"
            ):
                raise ValueError(
                    f"handoff fragment {name!r} read back inconsistent — "
                    "aborting this membership attempt"
                )
            total += len(payload)
        return total

    def dump_reshard_chunks(
        self, graph_sig: str, commit_id: int, chunk_iter: Any
    ) -> int:
        """Streamed (chunked-transport) handoff dump: consume ``(dest,
        chunk)`` mini-fragments one at a time, write each read-back
        verified, then commit one CHUNK MANIFEST per destination naming the
        complete stream (chunk count + per-chunk crc32). A reader treats a
        stream whose manifest is missing, or whose chunks are fewer or fail
        their checksums, as ABSENT — complete-or-abort, never a partial
        install. Peak memory here is one pickled chunk, which is what keeps
        a donor's handoff RSS flat as state grows. Returns total bytes
        written."""
        import zlib

        from pathway_tpu.internals.chaos import get_chaos

        chaos = get_chaos()
        rank = self._rank_id()
        total = 0
        per_dest: Dict[int, List[dict]] = {}
        first_written = False
        for dest, chunk in chunk_iter:
            idx = len(per_dest.setdefault(dest, []))
            payload = pickle.dumps(
                {"sig": graph_sig, **chunk}, protocol=pickle.HIGHEST_PROTOCOL
            )
            if chaos is not None and (
                chaos.scale_fault("handoff_torn", rank)
                or (
                    "join" in (chunk.get("kinds") or ())
                    and chaos.scale_fault("join_handoff_torn", rank)
                )
            ):
                payload = payload[: max(1, len(payload) // 2)]  # torn write
            name = self._chunk_name(commit_id, dest, idx)
            back = self._write_frag_blob(name, payload)
            try:
                got = pickle.loads(back)
            except Exception as exc:
                raise ValueError(
                    f"handoff chunk {name!r} failed read-back verification "
                    "(torn write) — aborting this membership attempt"
                ) from exc
            if got.get("sig") != graph_sig or got.get("from_rank") != chunk.get(
                "from_rank"
            ):
                raise ValueError(
                    f"handoff chunk {name!r} read back inconsistent — "
                    "aborting this membership attempt"
                )
            per_dest[dest].append(
                {"bytes": len(payload), "crc32": zlib.crc32(payload)}
            )
            total += len(payload)
            if chaos is not None and not first_written:
                first_written = True
                # chunk_stream_kill: donor dies with a half-written stream —
                # no manifest exists yet, so the stream reads as absent and
                # the recovery ladder replays the attempt from scratch
                chaos.maybe_scale_kill(
                    rank, "chunk_stream_kill", commit=int(commit_id)
                )
        for dest, entries in sorted(per_dest.items()):
            meta = {
                "sig": graph_sig,
                "from_rank": rank,
                "commit": int(commit_id),
                "count": len(entries),
                "chunks": entries,
            }
            payload = json.dumps(meta, sort_keys=True).encode()
            name = self._chunk_manifest_name(commit_id, dest)
            back = self._write_frag_blob(name, payload)
            try:
                got = json.loads(back)
            except ValueError as exc:
                raise ValueError(
                    f"handoff chunk manifest {name!r} failed read-back "
                    "verification (torn write) — aborting this membership "
                    "attempt"
                ) from exc
            if got.get("count") != len(entries) or got.get("sig") != graph_sig:
                raise ValueError(
                    f"handoff chunk manifest {name!r} read back inconsistent "
                    "— aborting this membership attempt"
                )
            total += len(payload)
        return total

    def load_reshard_fragments(
        self, graph_sig: str, commit_id: int, dest: int, from_n: int
    ) -> List[dict]:
        """Every donor rank's handoff addressed to ``dest`` for the
        transition at ``commit_id``, as a list of fragment/chunk dicts. Per
        donor the CHUNKED stream is preferred (chunk manifest + verified
        chunks — complete-or-abort); a donor without a chunk manifest falls
        back to the legacy single gather fragment. Loud on anything missing,
        torn, or incomplete: the membership manifest promised the complete
        set."""
        import zlib

        out: List[dict] = []
        for donor in range(from_n):
            mf_raw = self._read_donor_blob(
                donor, self._chunk_manifest_name(commit_id, dest)
            )
            if mf_raw is not None:
                try:
                    mf = json.loads(mf_raw)
                except ValueError as exc:
                    raise ValueError(
                        f"handoff chunk manifest from rank {donor} for rank "
                        f"{dest} at commit {commit_id} is unreadable"
                    ) from exc
                if mf.get("sig") != graph_sig:
                    raise ValueError(
                        "handoff fragment was written by a different "
                        "dataflow graph; clear the persistence directory"
                    )
                entries = mf.get("chunks") or []
                if int(mf.get("count", -1)) != len(entries):
                    raise ValueError(
                        f"handoff chunk manifest from rank {donor} for rank "
                        f"{dest} at commit {commit_id} is self-inconsistent"
                    )
                for idx, entry in enumerate(entries):
                    raw = self._read_donor_blob(
                        donor, self._chunk_name(commit_id, dest, idx)
                    )
                    if raw is None or zlib.crc32(raw) != int(
                        entry.get("crc32", -1)
                    ):
                        raise ValueError(
                            f"handoff chunk {idx} from rank {donor} for rank "
                            f"{dest} at commit {commit_id} is missing or "
                            "fails its checksum; the chunk manifest promised "
                            "the complete stream — restore the store or "
                            "clear the persistence directory"
                        )
                    frag = pickle.loads(raw)
                    if frag.get("sig") != graph_sig:
                        raise ValueError(
                            "handoff fragment was written by a different "
                            "dataflow graph; clear the persistence directory"
                        )
                    out.append(frag)
                continue
            payload = self._read_donor_blob(
                donor, self._fragment_name(commit_id, dest)
            )
            if payload is None:
                raise ValueError(
                    f"handoff fragment from rank {donor} for rank {dest} at "
                    f"commit {commit_id} is missing; the membership manifest "
                    "promised it — restore the store or clear the "
                    "persistence directory"
                )
            try:
                frag = pickle.loads(payload)
            except Exception as exc:
                raise ValueError(
                    f"handoff fragment from rank {donor} for rank {dest} at "
                    f"commit {commit_id} is unreadable"
                ) from exc
            if frag.get("sig") != graph_sig:
                raise ValueError(
                    "handoff fragment was written by a different dataflow "
                    "graph; clear the persistence directory"
                )
            out.append(frag)
        return out

    def commit_membership_manifest(
        self,
        graph_sig: str,
        commit_id: int,
        *,
        epoch: int,
        from_n: int,
        to_n: int,
        generation: int,
    ) -> bool:
        """Rank 0 only: durably commit the MEMBERSHIP manifest — a cluster
        manifest whose ``workers`` is the NEW count and whose per-rank
        snapshot entries name the fragment sets. Read-back verified under
        the NEW count; on success the store-wide meta adopts the new count
        too. This is the transition's atomic commit point."""
        meta = {
            "format": 1,
            "sig": graph_sig,
            "commit_id": int(commit_id),
            "epoch": int(epoch),
            "workers": int(to_n),
            "key_derivation": KEY_DERIVATION_VERSION,
            "membership": {
                "from_n": int(from_n),
                "to_n": int(to_n),
                "generation": int(generation),
            },
            "snapshots": {
                str(rank): [
                    f"process-{donor}/{self._fragment_name(commit_id, rank)}"
                    for donor in range(from_n)
                ]
                for rank in range(to_n)
            },
        }
        payload = json.dumps(meta, sort_keys=True).encode()
        name = self._manifest_name(commit_id)
        if self._object_store is not None:
            self._object_store.put(name, payload)
        else:
            assert self._base_root is not None
            os.makedirs(str(self._base_root), exist_ok=True)
            tmp = os.path.join(str(self._base_root), name + f".tmp.{os.getpid()}")
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(str(self._base_root), name))
        # verification must read as the NEW topology reads
        old_workers = self._workers
        self._workers = int(to_n)
        try:
            loaded = self.load_cluster_manifest(graph_sig)
        except ValueError:
            self._workers = old_workers
            return False
        if loaded is None or loaded["commit_id"] != int(commit_id):
            self._workers = old_workers
            return False
        self._workers = old_workers
        return True

    # -- leaver source park: a drained rank's source continuation -------------

    def _park_name(self) -> str:
        return "source-park.pkl"

    def dump_source_park(self, graph_sig: str, commit_id: int, payload: dict) -> None:
        """A draining leaver parks its rank-local source continuation
        (offsets, consumed counters) in its own shard: a future joiner
        reusing this rank id restores it and never re-ingests rows the rank
        already contributed before it drained."""
        blob = pickle.dumps(
            {"sig": graph_sig, "commit_id": commit_id, "state": payload},
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        if self._object_store is not None:
            self._object_store.put(f"{self._object_prefix}{self._park_name()}", blob)
            return
        if self._memory:
            return
        path = os.path.join(self.root, self._park_name())
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def load_source_park(self, graph_sig: str) -> "Optional[dict]":
        """This rank's parked source continuation, if a previous incarnation
        drained away. Torn/foreign parks are ignored (worst case the rank
        starts its rank-local sources fresh, exactly like a brand-new rank)."""
        payload: "bytes | None" = None
        if self._object_store is not None:
            payload = self._object_store.get(
                f"{self._object_prefix}{self._park_name()}"
            )
        elif not self._memory:
            try:
                with open(os.path.join(self.root, self._park_name()), "rb") as f:
                    payload = f.read()
            except OSError:
                payload = None
        if payload is None:
            return None
        try:
            data = pickle.loads(payload)
        except Exception:
            return None
        if data.get("sig") != graph_sig:
            return None
        return data.get("state")

    def clear_source_park(self) -> None:
        try:
            if self._object_store is not None:
                self._object_store.delete(
                    f"{self._object_prefix}{self._park_name()}"
                )
            elif not self._memory:
                os.unlink(os.path.join(self.root, self._park_name()))
        except OSError:
            pass

    def _manifest_candidates(self) -> List[tuple]:
        """(commit_id, raw bytes) of every versioned manifest, unsorted."""
        out: List[tuple] = []
        if self._object_store is not None:
            for key in self._object_store.list(_CLUSTER_MANIFEST_PREFIX):
                tail = key[len(_CLUSTER_MANIFEST_PREFIX):].split(".")[0]
                if not tail.isdigit():
                    continue
                blob = self._object_store.get(key)
                if blob is not None:
                    out.append((int(tail), blob))
            return out
        if self._memory or self._base_root is None:
            return out
        try:
            names = os.listdir(str(self._base_root))
        except OSError:
            return out
        for fname in names:
            if not (
                fname.startswith(_CLUSTER_MANIFEST_PREFIX) and fname.endswith(".json")
            ):
                continue
            tail = fname[len(_CLUSTER_MANIFEST_PREFIX):-len(".json")]
            if not tail.isdigit():
                continue
            try:
                with open(os.path.join(str(self._base_root), fname), "rb") as f:
                    out.append((int(tail), f.read()))
            except OSError:
                continue
        return out

    def load_cluster_manifest(self, graph_sig: str) -> Optional[dict]:
        """The newest VALID cluster checkpoint manifest, or None. Torn/
        unparseable manifests are skipped with a warning (recovery falls back
        to the previous checkpoint); a manifest from a different graph, worker
        count, or key-derivation version is refused loudly."""
        best: Optional[dict] = None
        for commit_id, raw in sorted(self._manifest_candidates(), reverse=True):
            try:
                meta = json.loads(raw)
            except ValueError:
                import logging

                logging.getLogger("pathway_tpu").warning(
                    "cluster checkpoint manifest for commit %d is torn/unreadable; "
                    "falling back to the previous checkpoint",
                    commit_id,
                )
                continue
            if meta.get("sig") != graph_sig:
                raise ValueError(
                    "cluster checkpoint manifest was written by a different "
                    "dataflow graph; clear the persistence directory or keep the "
                    "program unchanged"
                )
            self._check_meta(meta, "cluster manifest")
            if meta.get("commit_id") != commit_id:
                continue  # name/content mismatch: treat as torn
            best = meta
            break
        return best

    def compact_journal(self, graph_sig: str) -> int:
        """Drop every journal frame of this shard (all frames are ≤ the
        checkpoint commit when this is called — the commit loop is sequential
        and the checkpoint rides the current commit's barrier). Returns the
        number of frames dropped."""
        dropped = self.frames_since_compact
        if self._object_store is not None:
            for key in self._object_store.list(f"{self._object_prefix}journal/"):
                if key.endswith(".frame"):
                    seq = int(key.rsplit("/", 1)[-1].split(".")[0])
                    if seq < self._next_seq:
                        self._object_store.delete(key)
        elif self._memory:
            self._mem_journal = io.BytesIO()
            self._mem_journal.write(self._header_bytes(graph_sig))
        else:
            header = self._header_bytes(graph_sig)
            self._journal_file.truncate(len(header))
            self._journal_file.seek(0, os.SEEK_END)
            self._journal_file.flush()
            os.fsync(self._journal_file.fileno())
        self.frames_since_compact = 0
        return dropped

    def cleanup_cluster_checkpoints(self, keep_commit: int) -> None:
        """Best-effort pruning AFTER a manifest is durable: drop this shard's
        snapshots and (rank 0) manifests older than ``keep_commit``. Never
        raises — a failed cleanup only leaves extra files behind."""
        try:
            if self._object_store is not None:
                for key in self._object_store.list(self._object_prefix or ""):
                    base = key.rsplit("/", 1)[-1]
                    if base.startswith("checkpoint-") and base.endswith(".pkl"):
                        tail = base[len("checkpoint-"):-len(".pkl")]
                        if tail.isdigit() and int(tail) < keep_commit:
                            self._object_store.delete(key)
                    elif "/reshard-" in f"/{key}" and base.startswith("frag-"):
                        # handoff fragments of transitions superseded by a
                        # newer durable checkpoint
                        rdir = key.rsplit("/", 2)[-2]
                        tail = rdir[len("reshard-"):]
                        if tail.isdigit() and int(tail) < keep_commit:
                            self._object_store.delete(key)
                if self._rank_id() == 0:
                    for key in self._object_store.list(_CLUSTER_MANIFEST_PREFIX):
                        tail = key[len(_CLUSTER_MANIFEST_PREFIX):].split(".")[0]
                        if tail.isdigit() and int(tail) < keep_commit:
                            self._object_store.delete(key)
                return
            if self._memory:
                return
            for fname in os.listdir(self.root):
                if fname.startswith("checkpoint-") and fname.endswith(".pkl"):
                    tail = fname[len("checkpoint-"):-len(".pkl")]
                    if tail.isdigit() and int(tail) < keep_commit:
                        try:
                            os.unlink(os.path.join(self.root, fname))
                        except OSError:
                            pass
                elif fname.startswith("reshard-"):
                    tail = fname[len("reshard-"):]
                    if tail.isdigit() and int(tail) < keep_commit:
                        shutil.rmtree(
                            os.path.join(self.root, fname), ignore_errors=True
                        )
            if self._rank_id() == 0 and self._base_root is not None:
                for fname in os.listdir(str(self._base_root)):
                    if (
                        fname.startswith(_CLUSTER_MANIFEST_PREFIX)
                        and fname.endswith(".json")
                    ):
                        tail = fname[len(_CLUSTER_MANIFEST_PREFIX):-len(".json")]
                        if tail.isdigit() and int(tail) < keep_commit:
                            try:
                                os.unlink(os.path.join(str(self._base_root), fname))
                            except OSError:
                                pass
        except OSError:
            pass

    # -- journal read path ---------------------------------------------------

    def load_journal(self, graph_sig: str) -> List[Tuple[int, Dict[int, Delta], Dict[int, dict]]]:
        """All complete frames; a truncated tail frame (crash mid-write) is dropped and
        marked for truncation by ``open_for_append``. Object-store mode has no
        torn tails — PUTs are atomic — so every listed frame object is whole."""
        if self._object_store is not None:
            meta = self._object_store.get(self._meta_key())
            if meta is not None:
                if any(meta.startswith(old) for old in _OLD_MAGICS):
                    raise ValueError(_OLD_FORMAT_ERROR)
                if not meta.startswith(_HEADER_MAGIC):
                    return []
                self._validate_header_line(
                    meta[len(_HEADER_MAGIC) :].rstrip(b"\n"), graph_sig,
                    prefix_hint="prefix",
                )
            frames_o: List[Tuple[int, Dict[int, Delta], Dict[int, dict]]] = []
            # sorted() belt-and-braces: frame keys are zero-padded so lexicographic
            # order IS replay order, but a custom store may list unsorted
            for key in sorted(self._object_store.list(f"{self._object_prefix}journal/")):
                if not key.endswith(".frame"):
                    continue
                blob = self._object_store.get(key)
                if blob is None:
                    continue
                try:
                    commit_id, payloads, offsets = pickle.loads(blob)
                except Exception as exc:
                    # PUTs are atomic, so a frame object is never torn — an
                    # unreadable one means store-side corruption; truncating
                    # here would silently drop every LATER committed frame
                    raise ValueError(
                        f"persisted journal frame {key!r} is unreadable; refusing to "
                        "resume with missing commits — restore the object or clear "
                        "the persistence prefix to start fresh"
                    ) from exc
                frames_o.append(
                    (
                        commit_id,
                        {nid: _payload_to_delta(p) for nid, p in payloads.items()},
                        offsets,
                    )
                )
            # every surviving frame postdates the last compaction (compaction
            # deletes all of them), so the loaded count IS the journal tail —
            # without this a relaunched rank reports journal_tail_frames=0 and
            # the recovery-SLO fields understate the next recovery's replay cost
            self.frames_since_compact = len(frames_o)
            return frames_o
        if self._memory:
            data = self._mem_journal.getvalue()
        else:
            if not os.path.exists(self._journal_path()):
                self._valid_end = None
                return []
            with open(self._journal_path(), "rb") as f:
                data = f.read()
        if any(data.startswith(old) for old in _OLD_MAGICS):
            raise ValueError(_OLD_FORMAT_ERROR)
        if not data.startswith(_HEADER_MAGIC):
            self._valid_end = 0  # corrupt/foreign header: truncate and start fresh
            return []
        try:
            nl = data.index(b"\n", len(_HEADER_MAGIC))
        except ValueError:
            self._valid_end = 0
            return []
        self._validate_header_line(data[len(_HEADER_MAGIC) : nl], graph_sig)
        pos = nl + 1
        frames: List[Tuple[int, Dict[int, Delta], Dict[int, dict]]] = []
        while pos + _FRAME_HEADER.size <= len(data):
            (length,) = _FRAME_HEADER.unpack_from(data, pos)
            start = pos + _FRAME_HEADER.size
            if start + length > len(data):
                break  # truncated tail frame — crash during write; discard
            try:
                commit_id, payloads, offsets = pickle.loads(data[start : start + length])
            except Exception:
                break  # torn frame body despite intact length prefix
            frames.append(
                (commit_id, {nid: _payload_to_delta(p) for nid, p in payloads.items()}, offsets)
            )
            pos = start + length
        self._valid_end = pos
        # see the object-store branch: loaded frame count IS the current tail
        self.frames_since_compact = len(frames)
        return frames
