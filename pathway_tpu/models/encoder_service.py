"""Persistent on-device encoder service: continuous batching + warm jit caches.

The PR-4 :class:`~pathway_tpu.models.embed_pipeline.QueryCoalescer` is a
*deadline* micro-batcher: the first request at an empty queue anchors a
``max_wait_ms`` window, so a **solo** query always pays the window plus a cold
dispatch — coalescing only helps under concurrency, and ``/v1/retrieve`` solo
p50 stayed embed-bound (~392 ms, ROADMAP item 2). This module replaces the
deadline loop with a *continuously-batched* encoder worker, the ragged-serving
shape of the Ragged Paged Attention recipe (PAPERS.md) applied to the query
tower:

1. **Ragged admission queue.** Requests (solo or coalesced) append to a FIFO of
   variable-length text lists and wake the worker immediately — no deadline
   wait. Whatever is queued when the worker comes around is packed
   length-sorted into the next in-flight batch, capped at ``max_in_flight``
   rows; requests arriving while the device is busy ride the *next* tick, so
   concurrency still amortizes into one dispatch without any solo request ever
   waiting for a window to close.
2. **Always-warm pow2-bucketed forward.** The jitted forward only ever sees
   power-of-two (batch, seq) buckets (``JaxSentenceEncoder._dispatch``), so the
   whole reachable shape set is finite and enumerable. A background pre-warm
   thread compiles every bucket at service start (the Compiler-First caching
   argument: compiled state stays resident across requests) and records the
   wall cost as ``embed.svc.prewarm_s`` — compilation is reported at startup,
   never silently billed to the first query.
3. **Semantic query cache** (:class:`SemanticQueryCache`) sits ABOVE the PR-4
   content-hash cache in :class:`~pathway_tpu.models.embed_pipeline.EmbedPipeline`:
   exact mode (default) keys on the tokenizer's canonical form
   (``JaxSentenceEncoder.canonicalize``: whitespace collapse + case fold for
   uncased tokenizers), so a hit returns an embedding *bitwise-identical* to
   what the forward would produce — "  What is  RAG?" hits the entry stored
   for "what is rag?". Cosine mode (opt-in, ``threshold``) additionally
   answers near-matches via a cheap hashed bag-of-words proxy; it trades
   bitwise honesty for hit rate and is OFF by default.

Lifecycle: the worker thread spawns lazily on first :meth:`submit`, drains the
queue on :func:`stop_all_workers` (wired into ``GraphRunner.finish`` so
``pw.run`` teardown never leaks a device-owning thread) and respawns on the
next submit; :meth:`close` is the permanent variant. Every wait is timed and
abortable (the PWA102 contract) and the module lives in ``RUNTIME_MODULES`` so
PWA101-104 police its locks; the admission/tick/shutdown protocol is modeled
in ``internals/protocol_models.encoder_service_model`` and explored under
``internals/sched.py`` (no deadlock, no dropped request, slots always
released) — the model was written and checked BEFORE this implementation, per
the PR-9 discipline.

Knobs (ctor args, env defaults): ``PATHWAY_ENCSVC`` (``on``/``off`` — the
pipeline-level gate), ``PATHWAY_ENCSVC_TICK_MS`` (idle poll bound; wakeups are
notify-driven, the tick only bounds how long a lost wakeup could park the
worker), ``PATHWAY_ENCSVC_MAX_INFLIGHT`` (rows packed per tick),
``PATHWAY_ENCSVC_PREWARM`` (``1``/``0``), ``PATHWAY_ENCSVC_PREWARM_MAX_BATCH``
(largest batch bucket pre-compiled), ``PATHWAY_ENCSVC_SEMANTIC``
(``exact``/``cosine``/``off``), ``PATHWAY_ENCSVC_SEMANTIC_SIZE``,
``PATHWAY_ENCSVC_SEMANTIC_THRESHOLD``.

Telemetry (PR-5 plane): ``embed.svc.*`` stage counters (prewarm_s,
prewarm_compiles, ticks, rows, batches, dedup_rows, encode_s,
semantic_hits/misses) and three log-bucketed histograms on ``/metrics``:
``pathway_encsvc_queue_depth_rows``, ``pathway_encsvc_tick_occupancy``
(packed rows / max_in_flight), ``pathway_encsvc_tick_seconds``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pathway_tpu.engine import telemetry
from pathway_tpu.engine import tracing as _tracing


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.lower() not in ("0", "false", "no", "off")


def default_canonicalize(text: str) -> str:
    """Fallback canonical form when the encoder exposes none: collapse
    whitespace runs and case-fold — the equivalence every uncased BERT-family
    tokenizer already applies before wordpiece."""
    return " ".join(str(text).split()).lower()


class SemanticQueryCache:
    """Normalized-text query cache above the content-hash ``EmbedCache``.

    **exact** mode (default): key = ``canonicalize(text)``. Because the
    canonical form is exactly the equivalence the tokenizer applies anyway,
    two texts with the same key tokenize to identical ids and therefore
    identical (bitwise) embeddings — an exact-mode hit is as honest as
    re-running the forward. **cosine** mode (opt-in): on an exact-key miss, a
    hashed bag-of-words proxy vector of the query is cosine-compared against
    the cached proxies; a best match >= ``threshold`` answers with the cached
    embedding. Cosine hits are approximations — results are no longer
    bitwise-identical to a fresh encode, which is why the mode is off by
    default. **off**: get always misses, put is a no-op.

    Query-path ONLY by contract: the ingest path (``encode_batch``) and engine
    retraction rows never consult this layer — retractions replay from the
    evaluator's per-key memo (the ``deterministic=False`` contract) and
    re-ingested chunks ride the content-hash cache, so a semantic entry can
    never leak into document embeddings or retraction replay
    (regression-tested in ``tests/test_encoder_service.py``)."""

    #: proxy dimensionality for cosine mode — cheap to build and compare
    PROXY_DIM = 128

    def __init__(
        self,
        max_entries: int = 4096,
        *,
        mode: str = "exact",
        threshold: float = 0.95,
        canonicalize: Callable[[str], str] | None = None,
        key_tag: str = "",
    ):
        if mode not in ("exact", "cosine", "off"):
            raise ValueError(f"semantic cache mode must be exact|cosine|off, got {mode!r}")
        self.mode = mode
        self.max_entries = int(max_entries) if mode != "off" else 0
        self.threshold = float(threshold)
        base_canon = canonicalize or default_canonicalize
        if key_tag:
            # geometry-mode tag (e.g. the encoder's quantized-tower mode)
            # folded into every key: a mode flip can never serve embeddings
            # encoded under the other geometry — stale entries simply miss
            self._canon = lambda text: f"{key_tag}\x00{base_canon(text)}"
        else:
            self._canon = base_canon
        self._lock = threading.Lock()
        self._data: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self._proxies: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self.exact_hits = 0
        self.semantic_hits = 0
        self.misses = 0
        self.evictions = 0

    def _proxy(self, canon: str) -> np.ndarray:
        import xxhash

        vec = np.zeros(self.PROXY_DIM, dtype=np.float32)
        for word in canon.split():
            vec[xxhash.xxh32_intdigest(word) % self.PROXY_DIM] += 1.0
        norm = float(np.linalg.norm(vec))
        return vec / norm if norm > 0 else vec

    def get(self, text: str) -> Optional[np.ndarray]:
        if self.max_entries <= 0:
            return None
        key = self._canon(text)
        proxy = self._proxy(key) if self.mode == "cosine" else None
        with self._lock:
            vec = self._data.get(key)
            if vec is not None:
                self._data.move_to_end(key)
                self.exact_hits += 1
                return vec
            if proxy is not None and self._proxies:
                keys = list(self._proxies)
                mat = np.stack([self._proxies[k] for k in keys])
                sims = mat @ proxy
                best = int(np.argmax(sims))
                if float(sims[best]) >= self.threshold:
                    self.semantic_hits += 1
                    self._data.move_to_end(keys[best])
                    self._proxies.move_to_end(keys[best])
                    return self._data[keys[best]]
            self.misses += 1
            return None

    def put(self, text: str, vec: np.ndarray) -> None:
        if self.max_entries <= 0:
            return
        key = self._canon(text)
        row = np.ascontiguousarray(vec, dtype=np.float32)
        row.setflags(write=False)  # shared across queries: must never mutate
        proxy = self._proxy(key) if self.mode == "cosine" else None
        with self._lock:
            self._data[key] = row
            self._data.move_to_end(key)
            if proxy is not None:
                self._proxies[key] = proxy
                self._proxies.move_to_end(key)
            while len(self._data) > self.max_entries:
                old, _ = self._data.popitem(last=False)
                self._proxies.pop(old, None)
                self.evictions += 1

    def seed(self, text: str, vec: np.ndarray) -> None:
        """Idempotent :meth:`put` for the serving hot path: skips the lock,
        the row copy, and the LRU churn when the canonical key is already
        cached (the common case — every repeated content-cache hit re-seeds).
        The unlocked membership pre-check is benign: a racing double put is
        idempotent."""
        if self.max_entries <= 0:
            return
        if self._canon(text) in self._data:
            return
        self.put(text, vec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._proxies.clear()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "semantic_mode": self.mode,
                "semantic_exact_hits": self.exact_hits,
                "semantic_cosine_hits": self.semantic_hits,
                "semantic_misses": self.misses,
                "semantic_evictions": self.evictions,
                "semantic_size": len(self._data),
            }


class _Submission:
    __slots__ = ("texts", "arrived", "event", "rows", "error")

    def __init__(self, texts: List[str]):
        self.texts = texts
        self.arrived = time.monotonic()
        self.event = threading.Event()
        self.rows: Optional[List[Any]] = None
        self.error: Optional[BaseException] = None


#: every live service, so ``pw.run`` teardown can stop idle workers without
#: holding references that would keep dead pipelines alive
_services: "weakref.WeakSet[EncoderService]" = weakref.WeakSet()


def stop_all_workers(timeout_s: float = 10.0) -> None:
    """Stop (drain + join) every live service's worker and pre-warm threads.
    Called from ``GraphRunner.finish`` so back-to-back runs and interpreter
    shutdown never hold a device-owning thread; services stay usable — the
    worker respawns lazily on the next submit."""
    for svc in list(_services):
        svc.stop_worker(timeout_s=timeout_s)


class EncoderService:
    """Persistent continuous-batching worker in front of one encoder.

    ``submit(texts)`` blocks until the worker answers with one row value per
    text (device-resident jax slices from ``encoder.encode_device``). The
    worker packs everything queued at each tick — up to ``max_in_flight`` rows,
    length-sorted, duplicates encoded once — into one bucketed dispatch, so a
    solo request is dispatched the moment the worker is free (no deadline
    window) and a burst coalesces exactly like the PR-4 path did under load.

    The admission-cap/shed contract lives in the :class:`QueryCoalescer` shim
    in front of this class (``max_queue_rows`` here defaults to 0 =
    unbounded); ``queue_depth_rows`` feeds the shim's ``overloaded`` /
    ``retry_after_s`` probes so the REST plane's 429 + Retry-After semantics
    are unchanged."""

    def __init__(
        self,
        encoder: Any,
        *,
        tick_ms: float | None = None,
        max_in_flight: int | None = None,
        sub_batch: int = 64,
        max_queue_rows: int = 0,
        prewarm: bool | None = None,
        prewarm_max_batch: int | None = None,
        after_batch: Callable[[List[str], Sequence[Any]], None] | None = None,
    ):
        self.encoder = encoder
        if tick_ms is None:
            tick_ms = _env_float("PATHWAY_ENCSVC_TICK_MS", 50.0)
        # the tick is the IDLE poll bound, not a batching delay: admission
        # notifies the worker, so a solo request never waits for it — it only
        # bounds how long a (hypothetical) lost wakeup could park the loop,
        # which is also what makes the idle wait abortable (PWA102)
        self.tick_s = max(0.001, float(tick_ms) / 1000.0)
        if max_in_flight is None:
            max_in_flight = _env_int("PATHWAY_ENCSVC_MAX_INFLIGHT", 256)
        self.max_in_flight = max(1, int(max_in_flight))
        self.sub_batch = max(1, int(sub_batch))
        self.max_queue_rows = max(0, int(max_queue_rows))
        self._after_batch = after_batch
        self.wait_timeout_s = _env_float("PATHWAY_EMBED_WAIT_TIMEOUT_S", 0.0)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "deque[_Submission]" = deque()
        self._queued_rows = 0
        self._inflight_rows = 0
        self._worker: threading.Thread | None = None
        self._stop_requested = False
        self._closed = False
        self._encode_ewma_s = 0.0
        # counters (mirrored batch-level into the telemetry stage counters)
        self.requests = 0
        self.ticks = 0
        self.total_rows = 0
        self.batches = 0
        self.dedup_rows = 0
        self.max_tick_rows = 0
        self.shed_requests = 0
        # pre-warm state (abort via its own event: stop_worker must be able to
        # cancel a compile matrix even when no worker thread ever spawned, and
        # the worker's exit path resetting _stop_requested must not un-cancel)
        self._warm = threading.Event()
        self._prewarm_abort = threading.Event()
        self._prewarm_thread: threading.Thread | None = None
        self.prewarm_s = 0.0
        self.prewarm_compiles = 0
        if prewarm is None:
            prewarm = _env_flag("PATHWAY_ENCSVC_PREWARM", True)
        if prewarm_max_batch is None:
            prewarm_max_batch = _env_int("PATHWAY_ENCSVC_PREWARM_MAX_BATCH", 64)
        self.prewarm_max_batch = max(8, int(prewarm_max_batch))
        _services.add(self)
        if prewarm and self._prewarm_shapes():
            self._prewarm_thread = threading.Thread(
                target=self._prewarm_run, name="pathway:encsvc-prewarm", daemon=True
            )
            self._prewarm_thread.start()
        else:
            self._warm.set()

    # -- pre-warm ------------------------------------------------------------

    def _prewarm_shapes(self) -> List[Tuple[int, int]]:
        """Every pow2 (batch, seq) bucket the bucketed dispatch can reach,
        bounded by ``prewarm_max_batch`` x the encoder's ``max_length``. Empty
        when the encoder is not the jitted JAX module (mock encoders)."""
        if not hasattr(self.encoder, "_encode_ids") or not hasattr(self.encoder, "params"):
            return []
        from pathway_tpu.internals.shapes import next_pow2

        max_batch = next_pow2(
            min(self.max_in_flight, self.prewarm_max_batch), floor=8
        )
        max_seq = next_pow2(int(getattr(self.encoder, "max_length", 128)), floor=8)
        shapes = []
        b = 8
        while b <= max_batch:
            s = 8
            while s <= max_seq:
                shapes.append((b, s))
                s *= 2
            b *= 2
        return shapes

    def _prewarm_run(self) -> None:
        """Compile every reachable bucket off the request path; wall time and
        compile count land on ``embed.svc.prewarm_*`` so startup cost is
        reported instead of billed to the first query."""
        import jax.numpy as jnp

        t0 = time.perf_counter()
        compiles = 0
        try:
            for batch, seq in self._prewarm_shapes():
                if self._prewarm_abort.is_set() or self._closed:
                    break  # remaining buckets compile lazily on first use
                ids = jnp.zeros((batch, seq), dtype=jnp.int32)
                out = self.encoder._encode_ids(self.encoder.params, ids)
                out.block_until_ready()
                compiles += 1
        except Exception:
            pass  # pre-warm is best-effort: a failed compile resurfaces on use
        finally:
            elapsed = time.perf_counter() - t0
            with self._cond:
                self.prewarm_s += elapsed
                self.prewarm_compiles += compiles
            telemetry.stage_add_many(
                {
                    "embed.svc.prewarm_s": elapsed,
                    "embed.svc.prewarm_compiles": float(compiles),
                }
            )
            self._warm.set()

    def wait_warm(self, timeout_s: float = 300.0) -> bool:
        """Block until the pre-warm pass finished (True) or ``timeout_s``
        elapsed (False). The bench calls this before timing solo queries so
        compilation is excluded from request latency by construction."""
        return self._warm.wait(timeout=timeout_s)

    @property
    def warm(self) -> bool:
        return self._warm.is_set()

    # -- admission probes (consumed by the QueryCoalescer shim) --------------

    def queue_depth_rows(self) -> int:
        """Rows admitted but not yet answered (waiting + in-flight). Lock-free
        read — a soft probe with bounded staleness, same contract as the
        coalescer's ``overloaded``."""
        return self._queued_rows + self._inflight_rows

    def encode_ewma_s(self) -> float:
        return self._encode_ewma_s

    # -- submission ----------------------------------------------------------

    def submit(self, texts: List[str], *, enforce_cap: bool = True) -> List[Any]:
        """Blocking: one row value per input text, in order. Sheds with
        :class:`~pathway_tpu.models.embed_pipeline.EmbedOverloadError` when a
        local ``max_queue_rows`` cap is set and would be exceeded (the usual
        deployment leaves this 0 and caps in the coalescer shim instead)."""
        if not texts:
            return []
        sub = _Submission(list(texts))
        with self._cond:
            if self._closed:
                raise RuntimeError("EncoderService is closed")
            pending = self._queued_rows + self._inflight_rows
            if (
                enforce_cap
                and self.max_queue_rows
                and pending + len(texts) > self.max_queue_rows
            ):
                # same waiting+in-flight accounting and honest Retry-After the
                # coalescer shim's probe uses — the two admission points must
                # not disagree
                self.shed_requests += 1
                from pathway_tpu.models.embed_pipeline import EmbedOverloadError

                ticks = max(1.0, (pending + len(texts)) / self.max_in_flight)
                raise EmbedOverloadError(
                    f"encoder service queue full ({pending} rows pending, "
                    f"cap {self.max_queue_rows})",
                    retry_after_s=max(1.0, ticks * (self._encode_ewma_s or 0.05)),
                )
            self._queue.append(sub)
            self._queued_rows += len(texts)
            self.requests += 1
            self._ensure_worker_locked()
            self._cond.notify_all()
        self._await(sub)
        if sub.error is not None:
            raise sub.error
        assert sub.rows is not None
        return sub.rows

    def _ensure_worker_locked(self) -> None:
        # _locked suffix = caller-holds-self._cond convention (submit/_await);
        # the writes below are therefore lock-protected even though this frame
        # takes no lock itself
        if self._worker is None or not self._worker.is_alive():
            self._stop_requested = False  # noqa: PWA103 (caller holds self._cond)
            self._worker = threading.Thread(  # noqa: PWA103 (caller holds self._cond)
                target=self._run, name="pathway:encsvc-worker", daemon=True
            )
            self._worker.start()

    def _await(self, sub: _Submission) -> None:
        """Abortable timed wait (PWA102): wakes every 0.25 s to observe
        teardown. A submission stranded with no worker (a stop/close raced the
        append) is self-healed by respawning the worker — unless the service
        is permanently closed, which fails it typed; an optional
        ``PATHWAY_EMBED_WAIT_TIMEOUT_S`` bounds the total wait against a
        wedged device."""
        deadline = (
            time.monotonic() + self.wait_timeout_s if self.wait_timeout_s > 0 else None
        )
        while not sub.event.wait(timeout=0.25):
            with self._cond:
                if sub.event.is_set():
                    break
                worker = self._worker
                worker_dead = worker is None or not worker.is_alive()
                if worker_dead and sub in self._queue:
                    if self._closed:
                        self._queue.remove(sub)
                        self._queued_rows -= len(sub.texts)
                        sub.error = RuntimeError(
                            "EncoderService closed before this submission was "
                            "dispatched (no worker left to drain the queue)"
                        )
                        sub.event.set()
                        break
                    self._ensure_worker_locked()
                    self._cond.notify_all()
            if deadline is not None and time.monotonic() > deadline:
                with self._cond:
                    if sub in self._queue:
                        self._queue.remove(sub)
                        self._queued_rows -= len(sub.texts)
                raise TimeoutError(
                    f"encoder service did not answer within "
                    f"{self.wait_timeout_s:.0f}s "
                    "(PATHWAY_EMBED_WAIT_TIMEOUT_S) — device wedged?"
                )

    # -- worker --------------------------------------------------------------

    def _gather(self) -> Tuple[List[_Submission], int]:
        """Take everything queued, up to ``max_in_flight`` rows (always at
        least one submission). Returns the take and the queue depth observed
        at wake — continuous batching: no deadline window, whatever is waiting
        when the worker is free rides this tick."""
        with self._cond:
            while not self._queue:
                if self._closed or self._stop_requested:
                    return [], 0
                self._cond.wait(timeout=self.tick_s)
            depth = self._queued_rows
            take: List[_Submission] = []
            rows = 0
            while self._queue and (
                not take or rows + len(self._queue[0].texts) <= self.max_in_flight
            ):
                sub = self._queue.popleft()
                take.append(sub)
                rows += len(sub.texts)
            self._queued_rows -= rows
            self._inflight_rows += rows
            return take, depth

    def _release_inflight(self, rows: int) -> None:
        with self._cond:
            self._inflight_rows -= rows
            self._cond.notify_all()

    def _encode_packed(self, texts: List[str]) -> Tuple[List[Any], int]:
        """Length-sorted packing of one tick's unique texts: small ticks are a
        single bucketed dispatch; large ticks split into ``sub_batch``-row
        length-sorted sub-batches (each padded only to ITS longest row's pow2
        bucket, dispatched async) so a ragged burst doesn't pay the longest
        row's padding on every short query. Returns (rows, dispatches)."""
        n = len(texts)
        if n <= self.sub_batch:
            dev = self.encoder.encode_device(texts)
            return [dev[i] for i in range(n)], 1
        order = sorted(range(n), key=lambda i: len(str(texts[i]).split()))
        rows: List[Any] = [None] * n
        dispatches = 0
        for start in range(0, n, self.sub_batch):
            idx = order[start : start + self.sub_batch]
            dev = self.encoder.encode_device([texts[i] for i in idx])
            for j, i in enumerate(idx):
                rows[i] = dev[j]
            dispatches += 1
        return rows, dispatches

    def _run(self) -> None:
        from pathway_tpu.engine.profile import histogram

        depth_hist = histogram("pathway_encsvc_queue_depth_rows")
        occ_hist = histogram("pathway_encsvc_tick_occupancy")
        tick_hist = histogram("pathway_encsvc_tick_seconds")
        while True:
            batch, depth = self._gather()
            if not batch:
                with self._cond:
                    # exit only with an empty queue (drain semantics); a
                    # request appended after the final check respawns the
                    # worker from submit()/_await()
                    if (self._closed or self._stop_requested) and not self._queue:
                        self._stop_requested = False
                        self._worker = None
                        self._cond.notify_all()
                        return
                continue
            t_tick = time.perf_counter()
            texts = [t for sub in batch for t in sub.texts]
            n_rows = len(texts)
            # content dedup inside the tick: N clients asking the same
            # question pay one forward row
            first_of: Dict[str, int] = {}
            unique: List[str] = []
            slot_of: List[int] = []
            for t in texts:
                j = first_of.setdefault(t, len(unique))
                if j == len(unique):
                    unique.append(t)
                slot_of.append(j)
            # a coalesced batch links its N parent query spans: drain the
            # contexts REST handlers registered under this tick's texts. The
            # tick span samples whenever ANY linked query's trace is sampled
            # (the batch is shared work — every sampled parent needs it)
            tracer = _tracing.get_tracer()
            trace_links = (
                tuple(tracer.take_query_links(unique)) if tracer.enabled else ()
            )
            enc_span = None
            if trace_links:
                enc_span = tracer.start(
                    "encode",
                    f"encode tick {len(unique)}",
                    links=trace_links,
                )
                if enc_span is not None and any(l.sampled for l in trace_links):
                    enc_span.sampled = True
            try:
                t_enc = time.monotonic()
                with telemetry.stage_timer("embed.svc.encode"):
                    out, dispatches = self._encode_packed(unique)
                enc_s = time.monotonic() - t_enc
                self._encode_ewma_s = (
                    0.8 * self._encode_ewma_s + 0.2 * enc_s
                    if self._encode_ewma_s
                    else enc_s
                )
                rows = [out[j] for j in slot_of]
                if enc_span is not None:
                    enc_span.attrs.update(
                        {"rows": n_rows, "unique": len(unique),
                         "dispatches": dispatches}
                    )
                    tracer.finish(enc_span)
            except BaseException as exc:  # propagate to every waiter in the tick
                if enc_span is not None:
                    enc_span.attrs["error"] = type(exc).__name__
                    tracer.finish(enc_span)
                self._release_inflight(n_rows)
                for sub in batch:
                    sub.error = exc
                    sub.event.set()
                continue
            with self._cond:
                self.ticks += 1
                self.total_rows += n_rows
                self.batches += dispatches
                self.dedup_rows += n_rows - len(unique)
                self.max_tick_rows = max(self.max_tick_rows, n_rows)
                self._inflight_rows -= n_rows
                self._cond.notify_all()
            pos = 0
            for sub in batch:
                sub.rows = rows[pos : pos + len(sub.texts)]
                pos += len(sub.texts)
                sub.event.set()
            # telemetry AFTER responders are released: stage counters and
            # histograms are off the request latency path
            telemetry.stage_add_many(
                {
                    "embed.svc.ticks": 1.0,
                    "embed.svc.rows": float(n_rows),
                    "embed.svc.batches": float(dispatches),
                    "embed.svc.dedup_rows": float(n_rows - len(unique)),
                }
            )
            depth_hist.observe(float(depth))
            occ_hist.observe(n_rows / self.max_in_flight)
            tick_hist.observe(time.perf_counter() - t_tick)
            if self._after_batch is not None:
                try:
                    self._after_batch(unique, out)
                except Exception:
                    pass  # cache fill is best-effort; responders already released

    # -- lifecycle -----------------------------------------------------------

    def stop_worker(self, timeout_s: float = 10.0) -> None:
        """Drain the queue and stop the worker, and abort a running pre-warm
        (it cancels between bucket compiles; the join may still ride out ONE
        in-flight compile). The service stays usable — the next submit
        respawns the worker. Safe to call with requests in flight: every
        admitted submission is still answered before the worker exits."""
        self._prewarm_abort.set()
        with self._cond:
            worker = self._worker
            if worker is not None and worker.is_alive():
                self._stop_requested = True
            self._cond.notify_all()
        if worker is not None:
            worker.join(timeout=timeout_s)
        prewarm = self._prewarm_thread
        if prewarm is not None and prewarm is not threading.current_thread():
            prewarm.join(timeout=timeout_s)

    def close(self, timeout_s: float = 10.0) -> None:
        """Permanent, idempotent: drain, stop the worker, refuse new submits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self.stop_worker(timeout_s=timeout_s)

    def worker_alive(self) -> bool:
        worker = self._worker
        return worker is not None and worker.is_alive()

    # -- reporting -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "svc_requests": self.requests,
                "svc_ticks": self.ticks,
                "svc_rows": self.total_rows,
                "svc_batches": self.batches,
                "svc_dedup_rows": self.dedup_rows,
                "svc_max_tick_rows": self.max_tick_rows,
                "svc_avg_tick_rows": round(self.total_rows / max(self.ticks, 1), 2),
                "svc_occupancy": round(
                    self.total_rows / max(self.ticks * self.max_in_flight, 1), 4
                ),
                "svc_queue_rows": self._queued_rows + self._inflight_rows,
                "svc_shed_requests": self.shed_requests,
                "svc_prewarm_s": round(self.prewarm_s, 3),
                "svc_prewarm_compiles": self.prewarm_compiles,
                "svc_warm": self._warm.is_set(),
            }
