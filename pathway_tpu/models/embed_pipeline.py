"""Pipelined embedding runtime shared by the ingest and query paths.

Stages in front of ``JaxSentenceEncoder``, each measured through
``engine/telemetry.py`` stage counters:

1. **Content-hash embed cache** (:class:`EmbedCache`): an LRU keyed on
   (model, xxhash-of-text) consulted BEFORE the encoder on both paths, so
   re-ingested/duplicate chunks and repeated queries skip the forward pass
   entirely. The cache is orthogonal to the engine's memoize-on-retraction
   contract for non-deterministic UDFs: retraction rows are replayed from the
   evaluator's per-key memo and never reach this layer — the cache only
   deduplicates *forward* work across distinct rows/commits with equal text.
2. **Semantic query cache** (query path only;
   :class:`~pathway_tpu.models.encoder_service.SemanticQueryCache`): above the
   content hash — exact mode keys on the tokenizer's canonical form so
   whitespace/case variants of a served query hit without a forward pass, and
   stay bitwise-honest by construction; cosine mode is opt-in.
3. **Overlapped length-sorted ingest** (``JaxSentenceEncoder.encode_pipelined``):
   commit batches split into length-sorted sub-batches, host tokenization of
   sub-batch k+1 overlapping the device's forward of k via JAX async dispatch.
4. **Query serving** — by default the persistent continuously-batched
   :class:`~pathway_tpu.models.encoder_service.EncoderService`
   (``PATHWAY_ENCSVC=off`` reverts to the PR-4 deadline path). The
   :class:`QueryCoalescer` stays as the ADMISSION SHIM in front of it: the
   ``max_queue_rows`` cap, ``overloaded`` pre-admission probe, typed shed with
   honest Retry-After, and the ``embed.shed`` counter keep their PR-6
   contract; only the batching mechanics moved into the service (a solo query
   no longer waits for a deadline window).

Counters (``telemetry.stage_snapshot("embed.")``): cache hits/misses/evictions,
semantic hits/misses, coalesce/service requests/batches/rows, dedup_rows,
tokenize/encode timings, padded vs real token counts, ``embed.svc.*`` service
stages.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from pathway_tpu.engine import telemetry
from pathway_tpu.engine import tracing


class EmbedCache:
    """Thread-safe LRU of text → embedding keyed by (model, content hash).

    Keys are 128-bit xxh3 digests of the text salted with the model name —
    content-addressed, so identical chunks across files/commits share one
    entry. Values are read-only float32 host rows. ``max_entries=0`` disables
    the cache (get always misses, put is a no-op) without branching at call
    sites."""

    def __init__(self, max_entries: int = 50_000, model: str = ""):
        self.max_entries = int(max_entries)
        self._salt = model.encode()
        self._data: "OrderedDict[bytes, np.ndarray]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _key(self, text: str) -> bytes:
        import xxhash

        return xxhash.xxh3_128_digest(self._salt + b"\x00" + str(text).encode())

    def get(self, text: str) -> Optional[np.ndarray]:
        # per-row counters stay on the cache's own lock; the telemetry stage
        # counters (process-global lock) are fed one batch-level add per commit
        # by EmbedPipeline — a 1024-row ingest must not take the global lock
        # 1024 times
        if self.max_entries <= 0:
            return None
        key = self._key(text)
        with self._lock:
            vec = self._data.get(key)
            if vec is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return vec

    def put(self, text: str, vec: np.ndarray) -> None:
        if self.max_entries <= 0:
            return
        row = np.ascontiguousarray(vec, dtype=np.float32)
        row.setflags(write=False)  # shared across rows/commits: must never mutate
        key = self._key(text)
        with self._lock:
            self._data[key] = row
            self._data.move_to_end(key)
            while len(self._data) > self.max_entries:
                self._data.popitem(last=False)
                self.evictions += 1
                telemetry.stage_add("embed.cache_evictions")  # rare: batch-level in practice

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "cache_hits": self.hits,
                "cache_misses": self.misses,
                "cache_evictions": self.evictions,
                "cache_size": len(self._data),
            }


class EmbedOverloadError(RuntimeError):
    """The embed admission queue is full; the caller should shed load. Raised
    by direct ``QueryCoalescer.embed`` callers only — the REST plane consults
    the same cap BEFORE admission (``overloaded`` probe wired through
    ``rest_connector``) and sheds with HTTP 429 + ``Retry-After`` there, so an
    admitted request never dies inside an engine commit."""

    def __init__(self, message: str, *, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class _Request:
    __slots__ = ("texts", "arrived", "event", "rows", "error")

    def __init__(self, texts: List[str]):
        self.texts = texts
        self.arrived = time.monotonic()
        self.event = threading.Event()
        self.rows: Optional[List[Any]] = None
        self.error: Optional[BaseException] = None


class QueryCoalescer:
    """Deadline-based micro-batcher merging concurrent embed requests into one
    encoder dispatch.

    The first request to arrive at an empty queue anchors a batch window of
    ``max_wait_ms``; requests arriving inside the window (or while the encoder
    is busy with the previous batch) join the same dispatch, capped at
    ``max_batch`` rows. A request is therefore dispatched no later than
    ``max_wait_ms`` after submission (deadline contract) and immediately once
    ``max_batch`` rows are waiting. Duplicate texts within a batch encode once
    (content dedup) — every request still receives its own rows, in order.

    ``encode_rows(texts) -> sequence of per-row values`` runs on the worker
    thread; row values may be host arrays or device-resident jax slices — the
    coalescer never inspects them. An optional ``after_batch(texts, rows)``
    hook runs AFTER responders are released (cache fill without adding to
    request latency).

    **Service shim mode** (``service=`` set, the default through
    ``EmbedPipeline`` since the encoder-service PR): the deadline worker is
    bypassed — :meth:`embed` enforces the admission cap / shed contract here
    (unchanged REST semantics: ``overloaded`` probed pre-admission, typed
    :class:`EmbedOverloadError` with honest Retry-After, ``embed.shed``
    counter) and then submits into the
    :class:`~pathway_tpu.models.encoder_service.EncoderService`'s ragged
    queue, whose continuous-batching tick replaces the ``max_wait_ms``
    window."""

    def __init__(
        self,
        encode_rows: Callable[[List[str]], Sequence[Any]],
        *,
        max_wait_ms: float = 2.0,
        max_batch: int = 256,
        max_queue_rows: int = 0,
        after_batch: Callable[[List[str], Sequence[Any]], None] | None = None,
        service: Any = None,
    ):
        self._encode_rows = encode_rows
        self.max_wait_ms = float(max_wait_ms)
        self.max_batch = max(1, int(max_batch))
        # admission cap: rows allowed to WAIT for the encoder (0 = unbounded).
        # Past it, embed() sheds with EmbedOverloadError instead of queueing —
        # an overloaded encoder otherwise grows the queue without bound and
        # every client's deadline contract silently dies
        self.max_queue_rows = max(0, int(max_queue_rows))
        self._after_batch = after_batch
        self._service = service
        # hard bound on one request's total wait (0 = no bound; the wait is
        # still abortable — see _await). Covers a wedged encoder device: the
        # fence deadline must never sit behind an unbounded embed wait.
        self.wait_timeout_s = float(
            os.environ.get("PATHWAY_EMBED_WAIT_TIMEOUT_S", "0") or 0
        )
        self._queue: "deque[_Request]" = deque()
        self._queued_rows = 0
        self._encode_ewma_s = 0.0  # smoothed per-batch encode time (Retry-After)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._worker: threading.Thread | None = None
        self._closed = False
        # counters (also mirrored into telemetry stage counters)
        self.requests = 0
        self.batches = 0
        self.coalesced_rows = 0
        self.dedup_rows = 0
        self.max_batch_rows = 0
        self.shed_requests = 0

    def _rows_pending(self) -> int:
        """Rows admitted against the cap but not yet answered — the shim
        delegates to the service's queue (waiting + in-flight), the legacy
        path counts its own queue. Lock-free read either way."""
        if self._service is not None:
            return int(self._service.queue_depth_rows())
        return self._queued_rows

    def overloaded(self, extra_rows: int = 0) -> bool:
        """Admission probe: would admitting ``extra_rows`` more rows exceed
        ``max_queue_rows``? Lock-free read — a soft cap with bounded overshoot,
        same contract as the REST ``max_pending`` check. Each probe also feeds
        the brownout ladder (``engine/brownout.py``) one occupancy sample, so
        the serving plane's degradation rungs engage from the same signal the
        shed decision uses."""
        if not self.max_queue_rows:
            return False
        pending = self._rows_pending()
        from pathway_tpu.engine.brownout import get_brownout

        get_brownout().observe_occupancy(pending / self.max_queue_rows)
        return pending + extra_rows >= self.max_queue_rows

    def retry_after_s(self, extra_rows: int = 0) -> float:
        """Honest Retry-After estimate: batches needed to drain the current
        queue x (batch window + smoothed encode time), floored at 1 s. In shim
        mode the window term drops (the service has no deadline wait) and the
        smoothed encode time comes from the service's ticks."""
        rows = self._rows_pending() + extra_rows
        if self._service is not None:
            batches = max(1.0, rows / self._service.max_in_flight)
            per_batch = self._service.encode_ewma_s() or 0.05
        else:
            batches = max(1.0, rows / self.max_batch)
            per_batch = self.max_wait_ms / 1000.0 + (self._encode_ewma_s or 0.05)
        return max(1.0, batches * per_batch)

    # -- submission ----------------------------------------------------------

    def embed(self, texts: List[str], *, enforce_cap: bool = True) -> List[Any]:
        """Blocking: returns one row value per input text, in order.
        Raises :class:`EmbedOverloadError` when ``max_queue_rows`` is set and
        admitting these rows would exceed it. The engine serving path passes
        ``enforce_cap=False``: its requests were already admitted against the
        same cap at the REST boundary (``overloaded`` probe), and raising
        mid-commit would tear down the run instead of shedding one request."""
        if not texts:
            return []
        # the coalescer admission wait is a traced hop: a child of whatever
        # span the calling thread holds (the commit span on the engine serving
        # path), covering admission + the batching/encode wait
        with tracing.trace_span(
            "coalesce", f"coalesce {len(texts)}", attrs={"rows": len(texts)}
        ):
            return self._embed_traced(texts, enforce_cap=enforce_cap)

    def _embed_traced(self, texts: List[str], *, enforce_cap: bool = True) -> List[Any]:
        if self._service is not None:
            return self._embed_via_service(list(texts), enforce_cap)
        req = _Request(list(texts))
        with self._cond:
            if self._closed:
                raise RuntimeError("QueryCoalescer is closed")
            if (
                enforce_cap
                and self.max_queue_rows
                and self._queued_rows + len(texts) > self.max_queue_rows
            ):
                self.shed_requests += 1
                telemetry.stage_add("embed.shed")
                raise EmbedOverloadError(
                    f"embed queue full ({self._queued_rows} rows waiting, cap "
                    f"{self.max_queue_rows})",
                    retry_after_s=self.retry_after_s(len(texts)),
                )
            self._queue.append(req)
            self._queued_rows += len(texts)
            self.requests += 1
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, name="pathway:embed-coalescer", daemon=True
                )
                self._worker.start()
            self._cond.notify_all()
        self._await(req)
        if req.error is not None:
            raise req.error
        assert req.rows is not None
        return req.rows

    def _embed_via_service(self, texts: List[str], enforce_cap: bool) -> List[Any]:
        """Shim path: admission accounting + shed here (the PR-6 contract the
        REST plane depends on), batching in the service."""
        with self._cond:
            if self._closed:
                raise RuntimeError("QueryCoalescer is closed")
            if (
                enforce_cap
                and self.max_queue_rows
                and self._rows_pending() + len(texts) > self.max_queue_rows
            ):
                self.shed_requests += 1
                telemetry.stage_add("embed.shed")
                raise EmbedOverloadError(
                    f"embed queue full ({self._rows_pending()} rows pending, "
                    f"cap {self.max_queue_rows})",
                    retry_after_s=self.retry_after_s(len(texts)),
                )
            self.requests += 1
        return self._service.submit(texts, enforce_cap=False)

    def _await(self, req: _Request) -> None:
        """Abortable wait for a submitted request (the PWA102 contract: every
        runtime wait must wake periodically so teardown and the fence deadline
        can abort it — the previous untimed ``event.wait()`` wedged the engine
        thread forever when the coalescer died with the request still queued).
        The worker drains the queue on close, so the typed abort only fires
        when the request is still queued and no worker remains to take it;
        ``PATHWAY_EMBED_WAIT_TIMEOUT_S`` (0 = unbounded) additionally bounds
        the total wait against a wedged encoder device."""
        deadline = (
            time.monotonic() + self.wait_timeout_s if self.wait_timeout_s > 0 else None
        )
        while not req.event.wait(timeout=0.25):
            with self._cond:
                if req.event.is_set():
                    break
                worker = self._worker
                if (
                    self._closed
                    and req in self._queue
                    and (worker is None or not worker.is_alive())
                ):
                    self._queue.remove(req)
                    self._queued_rows -= len(req.texts)
                    req.error = RuntimeError(
                        "QueryCoalescer closed before this request was "
                        "dispatched (no worker left to drain the queue)"
                    )
                    req.event.set()
                    break
            if deadline is not None and time.monotonic() > deadline:
                with self._cond:
                    if req in self._queue:
                        self._queue.remove(req)
                        self._queued_rows -= len(req.texts)
                raise TimeoutError(
                    f"embed request not answered within "
                    f"{self.wait_timeout_s:.0f}s "
                    "(PATHWAY_EMBED_WAIT_TIMEOUT_S) — encoder wedged?"
                )

    def close(self) -> None:
        """Idempotent. A live worker drains the queue before exiting (every
        already-admitted request is still answered); requests stranded with no
        worker fail typed from :meth:`_await` instead of hanging."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- worker --------------------------------------------------------------

    def _gather(self) -> List[_Request]:
        """Wait for work, honor the batch window, take up to max_batch rows."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return []
                self._cond.wait(timeout=0.5)
            # the window anchors at the OLDEST queued request's arrival — time
            # it already spent waiting behind a busy encoder counts against the
            # deadline, so a request is dispatched no later than max_wait_ms
            # after submission (plus the in-flight batch, which is unavoidable).
            # Under brownout the window SHRINKS (engine/brownout.py): batching
            # efficiency is traded for latency while the queue is saturated.
            from pathway_tpu.engine.brownout import get_brownout

            window_ms = self.max_wait_ms * get_brownout().coalesce_window_scale()
            deadline = self._queue[0].arrived + window_ms / 1000.0
            while sum(len(r.texts) for r in self._queue) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(timeout=remaining)
            take: List[_Request] = []
            rows = 0
            while self._queue and (
                not take or rows + len(self._queue[0].texts) <= self.max_batch
            ):
                req = self._queue.popleft()
                take.append(req)
                rows += len(req.texts)
            self._queued_rows -= rows
            return take

    def _run(self) -> None:
        while True:
            batch = self._gather()
            if not batch:
                if self._closed:
                    return
                continue
            texts = [t for r in batch for t in r.texts]
            # content dedup inside the coalesced batch: N clients asking the
            # same question pay one forward row
            first_of: Dict[str, int] = {}
            unique: List[str] = []
            slot_of = []
            for t in texts:
                j = first_of.setdefault(t, len(unique))
                if j == len(unique):
                    unique.append(t)
                slot_of.append(j)
            try:
                _t_enc = time.monotonic()
                with telemetry.stage_timer("embed.coalesce_encode"):
                    out = self._encode_rows(unique)
                # smoothed encode time feeds the Retry-After estimate
                self._encode_ewma_s = (
                    0.8 * self._encode_ewma_s + 0.2 * (time.monotonic() - _t_enc)
                    if self._encode_ewma_s
                    else time.monotonic() - _t_enc
                )
                rows = [out[j] for j in slot_of]
            except BaseException as exc:  # propagate to every waiter in the batch
                for r in batch:
                    r.error = exc
                    r.event.set()
                continue
            self.batches += 1
            self.coalesced_rows += len(texts)
            self.dedup_rows += len(texts) - len(unique)
            self.max_batch_rows = max(self.max_batch_rows, len(texts))
            telemetry.stage_add("embed.coalesce_batches")
            telemetry.stage_add("embed.coalesce_rows", len(texts))
            if len(texts) > len(unique):
                telemetry.stage_add("embed.coalesce_dedup_rows", len(texts) - len(unique))
            pos = 0
            for r in batch:
                r.rows = rows[pos : pos + len(r.texts)]
                pos += len(r.texts)
                r.event.set()
            if self._after_batch is not None:
                try:
                    self._after_batch(unique, out)
                except Exception:
                    pass  # cache fill is best-effort; responders already released

    def stats(self) -> Dict[str, int]:
        return {
            "coalesce_requests": self.requests,
            "coalesce_batches": self.batches,
            "coalesce_rows": self.coalesced_rows,
            "coalesce_dedup_rows": self.dedup_rows,
            "coalesce_max_batch_rows": self.max_batch_rows,
            "coalesce_shed_requests": self.shed_requests,
        }


class EmbedPipeline:
    """The embed runtime shared by ingest (``encode_batch``) and query
    (``embed_query_rows``) paths: caches → service/overlapped encode → fill.

    Knobs: ``max_wait_ms``/``max_batch`` (legacy coalescer window),
    ``sub_batch`` (length-sorted ingest sub-batch rows), ``cache_size`` (LRU
    entries; 0 disables), ``service_mode`` (None = ``PATHWAY_ENCSVC`` env,
    default on), ``semantic_mode``/``semantic_size``/``semantic_threshold``
    (None = ``PATHWAY_ENCSVC_SEMANTIC*`` env; exact/4096/0.95),
    ``tick_ms``/``max_in_flight``/``prewarm`` forwarded to the
    :class:`~pathway_tpu.models.encoder_service.EncoderService`."""

    def __init__(
        self,
        encoder: Any,
        *,
        model: str = "",
        max_wait_ms: float = 2.0,
        max_batch: int = 256,
        sub_batch: int = 128,
        cache_size: int = 50_000,
        max_queue_rows: "int | None" = None,
        service_mode: "bool | None" = None,
        semantic_mode: "str | None" = None,
        semantic_size: "int | None" = None,
        semantic_threshold: "float | None" = None,
        tick_ms: "float | None" = None,
        max_in_flight: "int | None" = None,
        prewarm: "bool | None" = None,
    ):
        from pathway_tpu.models.encoder_service import (
            EncoderService,
            SemanticQueryCache,
            _env_flag,
            _env_float,
            _env_int,
            default_canonicalize,
        )

        self.encoder = encoder
        self.sub_batch = int(sub_batch)
        # the encoder's quantized-tower mode joins the content-hash salt AND
        # the semantic keys: embeddings cached under one geometry can never
        # answer a query encoded under the other (a mode flip misses, it
        # does not serve stale lattice points)
        quant_tag = getattr(encoder, "quant_tag", "") or ""
        self.cache = EmbedCache(
            cache_size, model=f"{model}|{quant_tag}" if quant_tag else model
        )
        self._pad_padded = 0.0
        self._pad_real = 0.0
        if max_queue_rows is None:
            # coalescer admission cap (rows waiting for the encoder): the REST
            # plane probes it pre-admission and sheds with 429 + Retry-After;
            # 0 disables. Second line of defense behind the per-route
            # max_pending request cap — rows, not requests, are what the
            # encoder actually queues.
            max_queue_rows = int(
                os.environ.get("PATHWAY_EMBED_MAX_QUEUE_ROWS", "4096")
            )
        if service_mode is None:
            service_mode = _env_flag("PATHWAY_ENCSVC", True)
        self.service = (
            EncoderService(
                encoder,
                tick_ms=tick_ms,
                max_in_flight=max_in_flight,
                prewarm=prewarm,
                after_batch=self._fill_cache_from_device,
            )
            if service_mode
            else None
        )
        # semantic query cache (query path ONLY — ingest and retraction rows
        # never consult it): exact mode keys on the tokenizer's canonical form
        # so hits stay bitwise-honest; cosine is opt-in; disabled entirely when
        # the content cache is disabled (cache_size=0 means "no caching")
        if semantic_mode is None:
            semantic_mode = os.environ.get("PATHWAY_ENCSVC_SEMANTIC", "exact") or "exact"
        if semantic_mode not in ("exact", "cosine", "off"):
            semantic_mode = "exact"
        if cache_size <= 0:
            semantic_mode = "off"
        if semantic_size is None:
            semantic_size = _env_int("PATHWAY_ENCSVC_SEMANTIC_SIZE", 4096)
        if semantic_threshold is None:
            semantic_threshold = _env_float("PATHWAY_ENCSVC_SEMANTIC_THRESHOLD", 0.95)
        self.semantic_cache = SemanticQueryCache(
            semantic_size,
            mode=semantic_mode,
            threshold=semantic_threshold,
            canonicalize=getattr(encoder, "canonicalize", None) or default_canonicalize,
            key_tag=quant_tag,
        )
        self.coalescer = QueryCoalescer(
            self._encode_device_rows,
            max_wait_ms=max_wait_ms,
            max_batch=max_batch,
            max_queue_rows=max_queue_rows,
            after_batch=self._fill_cache_from_device,
            service=self.service,
        )

    # -- ingest path ---------------------------------------------------------

    def encode_batch(self, texts: List[str]) -> np.ndarray:
        """Host float32 (n, dim) embeddings for a commit batch: cache hits skip
        the forward; misses ride the overlapped length-sorted sub-batch path."""
        n = len(texts)
        dim = self.encoder.dim
        out = np.empty((n, dim), dtype=np.float32)
        miss_idx: List[int] = []
        with telemetry.stage_timer("embed.cache_lookup"):
            for i, t in enumerate(texts):
                hit = self.cache.get(t)
                if hit is None:
                    miss_idx.append(i)
                else:
                    out[i] = hit
        self._stage_cache_counts(n - len(miss_idx), len(miss_idx))
        if miss_idx:
            with telemetry.stage_timer("embed.ingest_encode"):
                vecs, stats = self.encoder.encode_pipelined(
                    [str(texts[i]) for i in miss_idx], sub_batch=self.sub_batch
                )
            telemetry.stage_add("embed.tokenize_s", stats["tokenize_s"])
            telemetry.stage_add("embed.padded_tokens", stats["padded_tokens"])
            telemetry.stage_add("embed.real_tokens", stats["real_tokens"])
            self._pad_padded += stats["padded_tokens"]
            self._pad_real += stats["real_tokens"]
            for j, i in enumerate(miss_idx):
                out[i] = vecs[j]
                self.cache.put(texts[i], vecs[j])
        return out

    # -- query path ----------------------------------------------------------

    def embed_query_rows(self, texts: List[str]) -> List[Any]:
        """Per-row embedding values for the serving path. Cache hits (content
        hash first, then the semantic query cache) return host rows; misses
        ride the encoder service's continuous batch (or the legacy coalescer)
        and return DEVICE-resident jax slices (the downstream KNN kernel
        consumes either without an extra round trip)."""
        rows: List[Any] = [None] * len(texts)
        miss_idx: List[int] = []
        sem_hits = 0
        for i, t in enumerate(texts):
            hit = self.cache.get(t)
            if hit is None:
                hit = self.semantic_cache.get(str(t))
                if hit is not None:
                    sem_hits += 1
                    # promote: future lookups of THIS raw text hit the cheaper
                    # content-hash layer directly
                    self.cache.put(t, hit)
            else:
                # promote the other way: a content hit (possibly filled by the
                # INGEST path for identical chunk text) seeds the semantic
                # layer so canonical variants of this query hit too (no-op
                # once the key exists — steady-state hits stay a single read)
                self.semantic_cache.seed(str(t), hit)
            if hit is None:
                miss_idx.append(i)
            else:
                rows[i] = hit
        self._stage_cache_counts(len(texts) - len(miss_idx), len(miss_idx))
        if sem_hits:
            telemetry.stage_add("embed.svc.semantic_hits", sem_hits)
        if miss_idx and self.semantic_cache.max_entries > 0:
            telemetry.stage_add("embed.svc.semantic_misses", len(miss_idx))
        if miss_idx:
            # enforce_cap=False: REST admission already probed the cap; raising
            # here would kill the engine commit instead of shedding one request
            got = self.coalescer.embed(
                [str(texts[i]) for i in miss_idx], enforce_cap=False
            )
            for i, v in zip(miss_idx, got):
                rows[i] = v
        return rows

    def _encode_device_rows(self, texts: List[str]) -> List[Any]:
        dev = self.encoder.encode_device(texts)
        return [dev[i] for i in range(len(texts))]

    def _fill_cache_from_device(self, texts: List[str], rows: Sequence[Any]) -> None:
        """Runs on the service/coalescer worker AFTER responders are released:
        ONE device→host fetch of the whole batch (restacked from the rows the
        responders got — no hidden state shared with the encode call) fills
        the content-hash AND semantic caches without adding a sync to any
        query's latency."""
        if self.cache.max_entries <= 0 or not texts:
            return
        import jax.numpy as jnp

        host = np.asarray(jnp.stack(list(rows[: len(texts)])), dtype=np.float32)
        for t, v in zip(texts, host):
            self.cache.put(t, v)
            self.semantic_cache.put(t, v)

    def _stage_cache_counts(self, hits: int, misses: int) -> None:
        """ONE batch-level telemetry add per counter per commit (the telemetry
        module's stated granularity) instead of a global-lock hit per row."""
        if self.cache.max_entries <= 0:
            return  # cache disabled: keep telemetry consistent with stats()
        if hits:
            telemetry.stage_add("embed.cache_hits", hits)
        if misses:
            telemetry.stage_add("embed.cache_misses", misses)

    # -- reporting -----------------------------------------------------------

    def pad_waste_ratio(self) -> float:
        """Fraction of encoded tokens that were padding (ingest path)."""
        if self._pad_padded <= 0:
            return 0.0
        return 1.0 - self._pad_real / self._pad_padded

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        out.update(self.cache.stats())
        out.update(self.coalescer.stats())
        out.update(self.semantic_cache.stats())
        if self.service is not None:
            out.update(self.service.stats())
        out["pad_waste_ratio"] = round(self.pad_waste_ratio(), 4)
        return out
