"""Flax models used by the xpack (sentence encoders re-hosted TPU-side)."""
