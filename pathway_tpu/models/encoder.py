"""TPU-native sentence encoder (MiniLM/BERT family) in Flax.

This re-hosts the reference's torch-backed ``SentenceTransformerEmbedder``
(``xpacks/llm/embedders.py:270-328``, ``model.encode`` at ``:315``) as a jit'd JAX module:
token ids in, mean-pooled L2-normalized sentence embeddings out, bfloat16 matmuls on the MXU.
Weights convert from a local HuggingFace checkpoint when available (zero-egress environments
fall back to deterministic random init — fine for benchmarks measuring throughput and for
tests using mock embedders).

Architecture = all-MiniLM-L6-v2 defaults: 6 layers, hidden 384, 12 heads, FFN 1536,
vocab 30522, max_len 512.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from flax import linen as nn

from pathway_tpu.internals.shapes import next_pow2


def quant_encode_enabled() -> bool:
    """``PATHWAY_IVF_QUANT_ENCODE``: quantized query-tower encode mode.
    ``auto`` (default) follows ``PATHWAY_IVF_QUANT`` — the encoder rounds its
    embeddings onto the per-row symmetric int8 lattice exactly when the index
    scores in int8, so query vectors arrive pre-scaled for the int8 scorer
    and its re-quantization is code-stable (zero additional rounding).
    ``on``/``off`` force the mode independently of the index."""
    mode = os.environ.get("PATHWAY_IVF_QUANT_ENCODE", "auto").strip().lower()
    if mode in ("on", "1", "true", "yes", "int8"):
        return True
    if mode in ("off", "0", "false", "no"):
        return False
    from pathway_tpu.ops.knn_quant import quant_mode

    return quant_mode() == "int8"


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    num_layers: int = 6
    num_heads: int = 12
    intermediate_size: int = 1536
    max_position: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16  # activations/matmuls on the MXU; params stay f32


class TransformerLayer(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, hidden: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.config
        attention_out = nn.MultiHeadDotProductAttention(
            num_heads=cfg.num_heads,
            dtype=cfg.dtype,
            param_dtype=jnp.float32,
            name="attention",
        )(hidden, hidden, mask=mask)
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="attention_norm")(
            hidden + attention_out
        )
        ff = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="intermediate")(hidden)
        ff = nn.gelu(ff, approximate=False)
        ff = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="output")(ff)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="output_norm")(hidden + ff)


class SentenceEncoder(nn.Module):
    """BERT-style encoder with mean pooling + L2 normalization."""

    config: EncoderConfig = EncoderConfig()

    @nn.compact
    def __call__(self, input_ids: jax.Array, attention_mask: jax.Array) -> jax.Array:
        cfg = self.config
        positions = jnp.arange(input_ids.shape[1])[None, :]
        embeddings = (
            nn.Embed(cfg.vocab_size, cfg.hidden_size, name="word_embeddings")(input_ids)
            + nn.Embed(cfg.max_position, cfg.hidden_size, name="position_embeddings")(positions)
            + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, name="token_type_embeddings")(
                jnp.zeros_like(input_ids)
            )
        )
        hidden = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="embeddings_norm")(embeddings)
        hidden = hidden.astype(cfg.dtype)
        attn_mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(cfg.num_layers):
            hidden = TransformerLayer(cfg, name=f"layer_{i}")(hidden, attn_mask)
        hidden = hidden.astype(jnp.float32)
        # mean pooling over valid tokens, then L2 normalize (sentence-transformers recipe)
        mask_f = attention_mask[:, :, None].astype(jnp.float32)
        pooled = jnp.sum(hidden * mask_f, axis=1) / jnp.maximum(
            jnp.sum(mask_f, axis=1), 1e-9
        )
        return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)


class HashTokenizer:
    """Deterministic fallback tokenizer for zero-egress environments: word-hash into the
    vocab. NOT wordpiece — embeddings differ from the HF checkpoint, but throughput-identical
    (same shapes/FLOPs), which is what the benchmark measures.

    Vectorized: ids assemble through numpy scatter over a flat id array, and the
    word→id hash is memoized (``_word_ids``) so steady-state batches pay zero
    xxhash calls for repeated vocabulary — the per-word python loop + hash call
    per token was the host-side bottleneck in zero-egress benches. Output is
    trimmed to the batch's longest row (like the HF tokenizer) rather than
    padded to ``max_length``, so short batches stop paying 128-token pad FLOPs
    downstream."""

    _WORD_CACHE_MAX = 1 << 20  # unbounded ingest vocab must not grow the memo forever

    def __init__(self, vocab_size: int = 30522, max_length: int = 128):
        assert vocab_size > 3000, "hash ids live in [2000, vocab_size-1000)"
        self.vocab_size = vocab_size
        self.max_length = max_length
        self._word_ids: dict[str, int] = {}

    def _id_of(self, word: str) -> int:
        import xxhash

        return 2000 + (xxhash.xxh32_intdigest(word) % (self.vocab_size - 3000))

    def __call__(self, texts: list[str]) -> Tuple[np.ndarray, np.ndarray]:
        n = len(texts)
        limit = self.max_length - 2
        words_per = [str(t).lower().split()[:limit] for t in texts]
        cache = self._word_ids
        missing = {w for ws in words_per for w in ws if w not in cache}
        if missing:
            if len(cache) + len(missing) > self._WORD_CACHE_MAX:
                # overflow reset: re-hash EVERY word of the current batch, not
                # just `missing` — the clear just evicted the batch's cached ones
                cache.clear()
                missing = {w for ws in words_per for w in ws}
            for w in missing:
                cache[w] = self._id_of(w)
        lens = np.fromiter((len(ws) for ws in words_per), dtype=np.int64, count=n)
        width = int(lens.max()) + 2 if n else 2
        cols = np.arange(width)
        mask = (cols[None, :] < (lens + 2)[:, None]).astype(np.int32)
        ids = np.zeros((n, width), dtype=np.int32)
        if n:
            ids[:, 0] = 101  # [CLS]
            total = int(lens.sum())
            flat = np.fromiter(
                (cache[w] for ws in words_per for w in ws), dtype=np.int32, count=total
            )
            inner = cols[None, 1:] < (lens + 1)[:, None]
            ids[:, 1:][inner] = flat  # row-major boolean scatter keeps word order
            ids[np.arange(n), lens + 1] = 102  # [SEP]
        return ids, mask


def _hf_offline() -> None:
    # zero-egress environment: never let transformers hit the network (it retries for ~80s)
    import os

    os.environ.setdefault("HF_HUB_OFFLINE", "1")
    os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")


def _load_hf_tokenizer(model_name: str) -> Any:
    try:
        _hf_offline()
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(model_name, local_files_only=True)
    except Exception:
        return None


def convert_hf_weights(model_name: str, config: EncoderConfig) -> Optional[Dict]:
    """Convert a locally cached HF BERT checkpoint to this module's param tree."""
    try:
        _hf_offline()
        import torch
        from transformers import AutoModel

        hf = AutoModel.from_pretrained(model_name, local_files_only=True)
    except Exception:
        return None
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    p: Dict[str, Any] = {}
    p["word_embeddings"] = {"embedding": sd["embeddings.word_embeddings.weight"]}
    p["position_embeddings"] = {"embedding": sd["embeddings.position_embeddings.weight"]}
    p["token_type_embeddings"] = {"embedding": sd["embeddings.token_type_embeddings.weight"]}
    p["embeddings_norm"] = {
        "scale": sd["embeddings.LayerNorm.weight"],
        "bias": sd["embeddings.LayerNorm.bias"],
    }
    h, nh = config.hidden_size, config.num_heads
    hd = h // nh
    for i in range(config.num_layers):
        pre = f"encoder.layer.{i}."
        attn = {}
        for name, hf_name in (("query", "query"), ("key", "key"), ("value", "value")):
            w = sd[pre + f"attention.self.{hf_name}.weight"]  # (h, h) torch layout
            b = sd[pre + f"attention.self.{hf_name}.bias"]
            attn[name] = {
                "kernel": w.T.reshape(h, nh, hd),
                "bias": b.reshape(nh, hd),
            }
        wo = sd[pre + "attention.output.dense.weight"]
        attn["out"] = {
            "kernel": wo.T.reshape(nh, hd, h),
            "bias": sd[pre + "attention.output.dense.bias"],
        }
        p[f"layer_{i}"] = {
            "attention": attn,
            "attention_norm": {
                "scale": sd[pre + "attention.output.LayerNorm.weight"],
                "bias": sd[pre + "attention.output.LayerNorm.bias"],
            },
            "intermediate": {
                "kernel": sd[pre + "intermediate.dense.weight"].T,
                "bias": sd[pre + "intermediate.dense.bias"],
            },
            "output": {
                "kernel": sd[pre + "output.dense.weight"].T,
                "bias": sd[pre + "output.dense.bias"],
            },
            "output_norm": {
                "scale": sd[pre + "output.LayerNorm.weight"],
                "bias": sd[pre + "output.LayerNorm.bias"],
            },
        }
    return {"params": jax.tree.map(jnp.asarray, p)}


class JaxSentenceEncoder:
    """Batched text → embedding pipeline: tokenize on host, encode jit'd on TPU.

    Pads batch length to power-of-two buckets so XLA compiles a handful of shapes.
    """

    def __init__(
        self,
        model_name: str = "sentence-transformers/all-MiniLM-L6-v2",
        config: EncoderConfig | None = None,
        max_length: int = 128,
        seed: int = 0,
        transfer_dtype: str = "float16",
        weights_dtype: str = "bfloat16",
    ):
        """``transfer_dtype``: wire format of returned embeddings. The default
        ``float16`` halves host<->device bytes (decisive on tunneled TPUs); its
        ~5e-4 quantization sits BELOW the bfloat16 compute noise the forward pass
        already carries, so retrieval quality is unchanged. Pass ``float32`` to
        ship the pooled output unquantized.

        ``weights_dtype``: resident dtype of the matmul weights. The default
        ``bfloat16`` pre-casts ONCE at load — halving the HBM weight traffic per
        step and deleting the per-call f32->bf16 cast the mixed-precision module
        would otherwise do — standard inference precision for this model family
        (the forward pass already computes in bf16 either way). LayerNorm/bias
        params stay f32 via the module's ``param_dtype``. Pass ``float32`` to
        keep full-precision residency."""
        self.config = config or EncoderConfig()
        self.model = SentenceEncoder(self.config)
        self.max_length = max_length
        hf_tok = _load_hf_tokenizer(model_name)
        if hf_tok is not None:
            self._tokenize = lambda texts: self._hf_tokenize(hf_tok, texts)
            self._tokenizer_lowercases = bool(getattr(hf_tok, "do_lower_case", False))
            # whitespace-run collapse is id-preserving ONLY for BERT-family
            # basic tokenization (splits on any whitespace); byte-level BPE
            # (RoBERTa-style) encodes the runs, so the canonical form must
            # stay identity there or exact-mode cache hits stop being bitwise
            self._tokenizer_ws_invariant = (
                hasattr(hf_tok, "do_lower_case") or "Bert" in type(hf_tok).__name__
            )
        else:
            self._tokenize = HashTokenizer(self.config.vocab_size, max_length)
            self._tokenizer_lowercases = True  # HashTokenizer lower()s every word
            self._tokenizer_ws_invariant = True  # str.split() collapses runs
        params = convert_hf_weights(model_name, self.config)
        if params is None:
            ids = jnp.zeros((1, 8), dtype=jnp.int32)
            params = self.model.init(jax.random.PRNGKey(seed), ids, jnp.ones_like(ids))
        if weights_dtype == "bfloat16":
            # kernels/embeddings to bf16; norms and biases keep f32 for stability
            def _cast(path: tuple, leaf: Any) -> Any:
                name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
                if name in ("kernel", "embedding") and leaf.dtype == jnp.float32:
                    return leaf.astype(jnp.bfloat16)
                return leaf

            params = jax.tree_util.tree_map_with_path(_cast, params)
        self.params = params
        self.transfer_dtype = jnp.float16 if transfer_dtype == "float16" else jnp.float32
        # transfer-lean kernel: the attention mask derives on-device from the pad
        # id (BERT-family [PAD]=0; no real token is id 0), and the normalized
        # embeddings ship in transfer_dtype — on a tunneled TPU the host<->device
        # bytes, not the FLOPs, bound throughput
        out_dtype = self.transfer_dtype
        # quantized query tower (PATHWAY_IVF_QUANT_ENCODE): fold a per-row
        # symmetric int8 lattice round into the jitted forward — s = max|v|/127,
        # v -> round(v/s)*s in f32 BEFORE the wire cast. The row max is itself
        # a lattice point, so the int8 scorer's re-quantization reproduces the
        # codes exactly (|k| <= 127 keeps even the f16 wire perturbation under
        # half a code step); geometry served from cache must key on this mode
        self.quant_encode = quant_encode_enabled()
        self.quant_tag = "quant:int8" if self.quant_encode else ""
        if self.quant_encode:
            def _fwd(params: Any, ids: jax.Array) -> jax.Array:
                out = self.model.apply(
                    params, ids, (ids != 0).astype(jnp.int32)
                ).astype(jnp.float32)
                s = jnp.maximum(
                    jnp.max(jnp.abs(out), axis=1, keepdims=True), 1e-30
                ) / 127.0
                return (jnp.round(out / s) * s).astype(out_dtype)

            self._encode_ids = jax.jit(_fwd)
        else:
            self._encode_ids = jax.jit(
                lambda params, ids: self.model.apply(
                    params, ids, (ids != 0).astype(jnp.int32)
                ).astype(out_dtype)
            )

    def _hf_tokenize(self, tok: Any, texts: list[str]) -> Tuple[np.ndarray, np.ndarray]:
        out = tok(
            [str(t) for t in texts],
            padding=True,
            truncation=True,
            max_length=self.max_length,
            return_tensors="np",
        )
        return out["input_ids"].astype(np.int32), out["attention_mask"].astype(np.int32)

    @property
    def dim(self) -> int:
        return self.config.hidden_size

    def canonicalize(self, text: str) -> str:
        """Tokenizer-equivalence canonical form: two texts with equal
        canonical forms tokenize to IDENTICAL ids, hence bitwise-identical
        embeddings. Whitespace runs collapse only when the active tokenizer is
        whitespace-invariant (BERT-family basic tokenization / the hash
        fallback) and case folds only when it is uncased; for any other
        tokenizer family the canonical form is the identity — no equivalence
        is claimed that the tokenizer does not actually provide. The semantic
        query cache's exact mode keys on this, which is what makes an
        exact-mode hit bitwise-honest."""
        s = str(text)
        if not self._tokenizer_ws_invariant:
            return s
        s = " ".join(s.split())
        return s.lower() if self._tokenizer_lowercases else s

    def encode_device(self, texts: list[str]) -> Any:
        """Embeddings as a DEVICE-resident (n, dim) jax array — no host sync.

        Serving paths chain this straight into the KNN search kernel so a query
        pays exactly one device round-trip (dispatches pipeline; only the final
        fetch blocks — load-bearing on tunneled TPUs where each RPC costs ~65 ms)."""
        if not texts:
            return jnp.zeros((0, self.config.hidden_size), dtype=jnp.float32)
        ids, mask = self._tokenize(texts)
        out = self._dispatch(ids, mask)
        return out[: ids.shape[0]]

    def _dispatch(self, ids: np.ndarray, mask: np.ndarray) -> Any:
        """Pad a tokenized batch to pow2 (seq, batch) buckets and dispatch the
        jit'd forward WITHOUT blocking (JAX async dispatch: the returned array
        is a future; only reading it syncs). Rows beyond ``ids.shape[0]`` are
        zero padding."""
        seq = _next_pow2(ids.shape[1])
        batch = _next_pow2(ids.shape[0])
        ids_p = np.zeros((batch, seq), dtype=np.int32)
        ids_p[: ids.shape[0], : ids.shape[1]] = ids * mask  # padding -> id 0
        return self._encode_ids(self.params, jnp.asarray(ids_p))

    def encode(self, texts: list[str]) -> np.ndarray:
        if not texts:
            return np.zeros((0, self.config.hidden_size), dtype=np.float32)
        return np.asarray(self.encode_device(texts), dtype=np.float32)

    def encode_pipelined(
        self, texts: list[str], sub_batch: int = 128
    ) -> Tuple[np.ndarray, Dict[str, float]]:
        """Overlapped length-sorted encode: host-tokenize sub-batch k+1 while the
        device computes k.

        The batch sorts by a cheap whitespace length proxy and splits into
        ``sub_batch``-row sub-batches, each padded only to ITS longest row's
        pow2 bucket — short rows stop paying the global longest row's pad
        FLOPs. Dispatches are JAX-async: the loop never blocks on a forward, so
        tokenization of sub-batch k+1 runs while the device works on k (double
        buffering without explicit streams); the single sync point is the final
        fetch. Per-row results are bitwise-identical to :meth:`encode` (masked
        attention/pooling make each row invariant to pad width — regression-
        tested on CPU).

        Returns ``(embeddings (n, dim) float32 in input order, stats)`` where
        stats carries ``padded_tokens``/``real_tokens`` (the pad-waste ratio),
        ``tokenize_s`` and ``sub_batches``."""
        n = len(texts)
        dim = self.config.hidden_size
        stats: Dict[str, float] = {
            "padded_tokens": 0.0, "real_tokens": 0.0, "tokenize_s": 0.0,
            "sub_batches": 0.0,
        }
        out = np.empty((n, dim), dtype=np.float32)
        if n == 0:
            return out, stats
        import time as _time

        order = sorted(range(n), key=lambda i: len(str(texts[i]).split()))
        inflight = []  # (device future, original indices) — fetched after all dispatches
        for start in range(0, n, max(1, sub_batch)):
            idx = order[start : start + max(1, sub_batch)]
            t0 = _time.perf_counter()
            ids, mask = self._tokenize([texts[i] for i in idx])
            stats["tokenize_s"] += _time.perf_counter() - t0
            dev = self._dispatch(ids, mask)
            stats["padded_tokens"] += float(dev.shape[0] * _next_pow2(ids.shape[1]))
            stats["real_tokens"] += float(mask.sum())
            stats["sub_batches"] += 1
            inflight.append((dev, idx))
        for dev, idx in inflight:
            out[idx] = np.asarray(dev[: len(idx)], dtype=np.float32)
        return out, stats


def _next_pow2(n: int) -> int:
    """Device shape bucket (floor 8) — the shared pow2 rule from
    ``internals/shapes.py``; kept as a named helper because the bench's FLOP
    accounting imports it to mirror the exact shapes executed."""
    return next_pow2(n, floor=8)
