"""Extension packs (parity: reference ``python/pathway/xpacks``)."""
