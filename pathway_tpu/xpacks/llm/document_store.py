"""DocumentStore — index-agnostic document pipeline + query surface.

Parity: reference ``xpacks/llm/document_store.py:32``: docs sources → parse → post-process →
split → index (via a retriever factory); query methods ``retrieve_query`` /
``statistics_query`` / ``inputs_query`` with the reference's request/response schemas.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory


class DocumentStore:
    class RetrieveQuerySchema(pw.Schema):
        query: str
        k: int = pw.column_definition(default_value=3, dtype=int)
        metadata_filter: str | None = pw.column_definition(default_value=None)
        filepath_globpattern: str | None = pw.column_definition(default_value=None)

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(pw.Schema):
        metadata_filter: str | None = pw.column_definition(default_value=None)
        filepath_globpattern: str | None = pw.column_definition(default_value=None)

    def __init__(
        self,
        docs: Table | Iterable[Table],
        retriever_factory: AbstractRetrieverFactory,
        parser: Any = None,
        splitter: Any = None,
        doc_post_processors: list[Callable] | None = None,
    ):
        from pathway_tpu.xpacks.llm.parsers import ParseUtf8
        from pathway_tpu.xpacks.llm.splitters import NullSplitter

        self.docs = [docs] if isinstance(docs, Table) else list(docs)
        if not self.docs:
            raise ValueError(
                "DocumentStore requires at least one document source table"
            )
        self.retriever_factory = retriever_factory
        self.parser = parser if parser is not None else ParseUtf8()
        self.splitter = splitter if splitter is not None else NullSplitter()
        self.doc_post_processors = doc_post_processors or []
        self._build_graph()

    # -- pipeline -----------------------------------------------------------

    def _build_graph(self) -> None:
        docs = self.docs[0] if len(self.docs) == 1 else self.docs[0].concat_reindex(
            *self.docs[1:]
        )
        if "_metadata" not in docs.column_names():
            docs = docs.with_columns(_metadata=expr.apply_with_type(lambda: Json({}), dt.JSON))
        self.input_docs = docs

        # parse: data -> [(text, meta)]
        parsed = docs.select(
            _pw_parsed=self.parser(docs.data),
            _pw_input_meta=docs._metadata,
        )
        flat = parsed.flatten(parsed._pw_parsed, origin_id="_pw_doc_id")
        parsed_docs = flat.select(
            text=flat._pw_parsed[0],
            metadata=expr.apply_with_type(
                _merge_meta, dt.JSON, flat._pw_input_meta, flat._pw_parsed[1]
            ),
        )
        for post in self.doc_post_processors:
            parsed_docs = parsed_docs.select(
                text=expr.apply_with_type(post, str, parsed_docs.text),
                metadata=parsed_docs.metadata,
            )
        self.parsed_docs = parsed_docs

        # split: text -> [(chunk, meta)]
        splitted = parsed_docs.select(
            _pw_chunks=self.splitter(parsed_docs.text, parsed_docs.metadata),
        )
        chunk_flat = splitted.flatten(splitted._pw_chunks, origin_id="_pw_parsed_id")
        chunked_docs = chunk_flat.select(
            text=chunk_flat._pw_chunks[0],
            metadata=expr.apply_with_type(
                lambda m: m if isinstance(m, Json) else Json(m if m is not None else {}),
                dt.JSON,
                chunk_flat._pw_chunks[1],
            ),
        )
        self.chunked_docs = chunked_docs.filter(chunked_docs.text.str.len() > 0)

        self.index = self.retriever_factory.build_index(
            self.chunked_docs.text,
            self.chunked_docs,
            metadata_column=self.chunked_docs.metadata,
        )

    # -- queries ------------------------------------------------------------

    def retrieve_query(self, retrieval_queries: Table) -> Table:
        """queries(query, k, metadata_filter, filepath_globpattern) → result column."""
        names = retrieval_queries.column_names()
        queries = retrieval_queries.select(
            query=retrieval_queries.query,
            k=expr.coalesce(retrieval_queries.k, 3) if "k" in names else 3,
            _pw_filter=expr.apply_with_type(
                _combined_filter,
                dt.Optional_(dt.STR),
                retrieval_queries.metadata_filter if "metadata_filter" in names else None,
                retrieval_queries.filepath_globpattern
                if "filepath_globpattern" in names
                else None,
            ),
        )
        result = self.index.query_as_of_now(
            queries.query,
            number_of_matches=queries.k,
            collapse_rows=True,
            metadata_filter=queries._pw_filter,
        )
        return result.select(
            result=expr.apply_with_type(
                _format_retrieved,
                dt.JSON,
                result.text,
                result.metadata,
                result._pw_index_reply_score,
            )
        )

    def statistics_query(self, info_queries: Table) -> Table:
        counted = self.input_docs.reduce(
            count=reducers.count(),
            last_modified=reducers.max(
                expr.apply_with_type(_modified_ts, dt.Optional_(dt.INT), self.input_docs._metadata)
            ),
            last_indexed=reducers.max(
                expr.apply_with_type(_seen_ts, dt.Optional_(dt.INT), self.input_docs._metadata)
            ),
        )

        def _payload(c: Any, m: Any, i: Any) -> Json:
            payload = {"file_count": c or 0, "last_modified": m, "last_indexed": i}
            # live embed-pipeline counters (cache hit/miss, coalescing, pad
            # waste) when the embedder exposes them — read at answer time so
            # /v1/statistics doubles as the serving-path observability endpoint
            stats_fn = getattr(
                getattr(self.retriever_factory, "embedder", None), "pipeline_stats", None
            )
            if stats_fn is not None:
                try:
                    payload["embedder"] = stats_fn()
                except Exception:
                    pass
            # the same snapshot /metrics exports: commit latency percentiles
            # + top operators by cumulative wall time (engine/profile.py).
            # Pinned PER COMMIT, not per run: within one commit every
            # re-derivation (cross-ref re-evaluation) must see the identical
            # value — the snapshot moves with every commit of every runner in
            # the process, and a value that changed between two evaluations
            # of the same row churns nondeterministic update pairs. Across
            # commits it reads FRESH, so a long-running server keeps serving
            # live numbers (retraction rows replay the evaluator's memo and
            # never re-invoke this)
            try:
                from pathway_tpu.engine.expression_evaluator import get_runtime
                from pathway_tpu.engine.profile import get_profiler

                token = get_runtime().get("commit_token")
                if (
                    token is None
                    or getattr(self, "_engine_snapshot_token", None) != token
                ):
                    self._engine_snapshot_cache = get_profiler().snapshot()
                    self._engine_snapshot_token = token
                payload["engine"] = self._engine_snapshot_cache
            except Exception:
                pass
            return Json(payload)

        joined = info_queries.join_left(counted, id=info_queries.id).select(
            result=expr.apply_with_type(
                _payload,
                dt.JSON,
                counted.count,
                counted.last_modified,
                counted.last_indexed,
            )
        )
        return joined

    def inputs_query(self, input_queries: Table) -> Table:
        files = self.input_docs.reduce(
            metadatas=reducers.tuple(self.input_docs._metadata)
        )
        joined = input_queries.join_left(files, id=input_queries.id).select(
            result=expr.apply_with_type(
                lambda metas: Json(
                    [m.value if isinstance(m, Json) else m for m in (metas or ())]
                ),
                dt.JSON,
                files.metadatas,
            )
        )
        return joined

    # parity aliases
    retrieve = retrieve_query
    statistics = statistics_query
    inputs = inputs_query


class SlidesDocumentStore(DocumentStore):
    """Reference variant returning slide-specific metadata; shares the pipeline."""


def _merge_meta(input_meta: Any, parse_meta: Any) -> Json:
    out = {}
    if isinstance(input_meta, Json):
        value = input_meta.value
        if isinstance(value, dict):
            out.update(value)
    elif isinstance(input_meta, dict):
        out.update(input_meta)
    if isinstance(parse_meta, Json):
        parse_meta = parse_meta.value
    if isinstance(parse_meta, dict):
        out.update(parse_meta)
    return Json(out)


def _combined_filter(metadata_filter: Any, globpattern: Any) -> str | None:
    parts = []
    if metadata_filter:
        parts.append(f"({metadata_filter})")
    if globpattern:
        escaped = str(globpattern).replace("'", "\\'")
        parts.append(f"globmatch('{escaped}', path)")
    return " && ".join(parts) if parts else None


def _format_retrieved(texts: tuple, metadatas: tuple, scores: tuple) -> Json:
    out = []
    for text, meta, score in zip(texts, metadatas, scores):
        out.append(
            {
                "text": text,
                "metadata": meta.value if isinstance(meta, Json) else meta,
                "dist": -float(score),
            }
        )
    return Json(out)


def _modified_ts(meta: Any) -> int | None:
    if isinstance(meta, Json) and isinstance(meta.value, dict):
        return meta.value.get("modified_at")
    return None


def _seen_ts(meta: Any) -> int | None:
    if isinstance(meta, Json) and isinstance(meta.value, dict):
        return meta.value.get("seen_at")
    return None
