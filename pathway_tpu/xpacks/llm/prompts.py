"""RAG prompt templates (parity: reference ``xpacks/llm/prompts.py``)."""

from __future__ import annotations

from typing import Any


def prompt_qa(
    query: str,
    docs: tuple,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
) -> str:
    context = "\n\n".join(_doc_text(d) for d in docs)
    return (
        "Please provide an answer based solely on the provided sources. "
        "Keep your answer concise and accurate. "
        f"If the sources do not contain the answer, say: {information_not_found_response}\n"
        f"{additional_rules}\n"
        f"Sources:\n{context}\n\n"
        f"Question: {query}\n"
        "Answer:"
    )


def prompt_short_qa(query: str, docs: tuple, additional_rules: str = "") -> str:
    return prompt_qa(
        query, docs, additional_rules=additional_rules + "\nAnswer with as few words as possible."
    )


def prompt_citing_qa(query: str, docs: tuple, additional_rules: str = "") -> str:
    context = "\n\n".join(f"[{i}] {_doc_text(d)}" for i, d in enumerate(docs))
    return (
        "Answer the question based on the numbered sources, citing them like [0].\n"
        f"{additional_rules}\n"
        f"Sources:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


def prompt_summarize(text_list: tuple) -> str:
    text = "\n".join(str(t) for t in text_list)
    return f"Summarize the following text concisely:\n\n{text}\n\nSummary:"


def prompt_query_rewrite(query: str) -> str:
    return (
        "Rewrite the following search query to be clearer and more specific, "
        f"keeping its meaning:\n{query}\nRewritten query:"
    )


def rerank_prompt(doc: str, query: str) -> str:
    return (
        "Rate the relevance of the document to the query on a scale from 1 to 5, "
        "where 5 means highly relevant. Respond with a single digit.\n"
        f"Query: {query}\nDocument: {doc}\nRating:"
    )


def _doc_text(d: Any) -> str:
    from pathway_tpu.internals.json import Json

    if isinstance(d, Json):
        d = d.value
    if isinstance(d, dict):
        return str(d.get("text", d))
    return str(d)
