"""LLM xpack: RAG pipeline components (parity: reference ``xpacks/llm``)."""

from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    parsers,
    prompts,
    rerankers,
    splitters,
)

__all__ = ["embedders", "llms", "parsers", "prompts", "rerankers", "splitters"]


def __getattr__(name: str):
    # heavier modules lazily (vector_store pulls the whole engine graph machinery)
    if name in ("vector_store", "document_store", "question_answering", "servers"):
        import importlib

        return importlib.import_module(f"pathway_tpu.xpacks.llm.{name}")
    raise AttributeError(name)
