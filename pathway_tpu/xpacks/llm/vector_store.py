"""VectorStoreServer — the reference's flagship RAG service.

Parity: reference ``xpacks/llm/vector_store.py:39`` (graph ``:227-310``, REST ``run_server:478``):
document sources → parse → split → TPU embedder → KNN index; REST endpoints
``/v1/retrieve``, ``/v1/statistics``, ``/v1/inputs``. Plus ``VectorStoreClient``.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Iterable, List, Optional

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.nearest_neighbors import BruteForceKnnFactory, BruteForceKnnMetricKind
from pathway_tpu.xpacks.llm.document_store import DocumentStore


class VectorStoreServer:
    """docs sources + embedder → served KNN index (reference ``vector_store.py:39``)."""

    def __init__(
        self,
        *docs: Table,
        embedder: Any,
        parser: Any = None,
        splitter: Any = None,
        doc_post_processors: list[Callable] | None = None,
        index_factory: Any = None,
    ):
        self.embedder = embedder
        if index_factory is None:
            index_factory = BruteForceKnnFactory(
                embedder=embedder, metric=BruteForceKnnMetricKind.COS
            )
        elif index_factory == "ivf":
            # sublinear serving at large corpora: the IVF-Flat index's fused
            # probe→gather→score kernel (ops/knn_ivf.py) end-to-end — embed →
            # probe centroids → stream candidate pages → top-k, one device
            # round-trip per query batch
            from pathway_tpu.stdlib.indexing.nearest_neighbors import IvfKnnFactory

            index_factory = IvfKnnFactory(
                embedder=embedder, metric=BruteForceKnnMetricKind.COS
            )
        self.docs = list(docs)
        self.store = DocumentStore(
            self.docs,
            retriever_factory=index_factory,
            parser=parser,
            splitter=splitter,
            doc_post_processors=doc_post_processors,
        )

    # reference schema names
    class QuerySchema(pw.Schema):
        query: str
        k: int = pw.column_definition(default_value=3, dtype=int)
        metadata_filter: str | None = pw.column_definition(default_value=None)
        filepath_globpattern: str | None = pw.column_definition(default_value=None)

    class StatisticsSchema(pw.Schema):
        pass

    class InputsQuerySchema(pw.Schema):
        metadata_filter: str | None = pw.column_definition(default_value=None)
        filepath_globpattern: str | None = pw.column_definition(default_value=None)

    def retrieve_query(self, queries: Table) -> Table:
        return self.store.retrieve_query(queries)

    def statistics_query(self, queries: Table) -> Table:
        return self.store.statistics_query(queries)

    def inputs_query(self, queries: Table) -> Table:
        return self.store.inputs_query(queries)

    @property
    def index(self) -> Any:
        return self.store.index

    def run_server(
        self,
        host: str = "0.0.0.0",
        port: int = 8000,
        *,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
    ) -> Any:
        """Serve /v1/retrieve, /v1/statistics, /v1/inputs (reference ``:478``)."""
        from pathway_tpu.io.http import PathwayWebserver, rest_connector

        webserver = PathwayWebserver(host=host, port=port)
        # retrieve is the embed-bound route: cap admitted-but-unanswered
        # queries so an embed stampede sheds (429 + Retry-After, counted as
        # pathway_stage_total{stage="embed.shed"}) instead of queueing without
        # bound in front of the encoder
        import os as _os

        max_pending = int(_os.environ.get("PATHWAY_EMBED_MAX_PENDING", "1024"))
        coalescer = getattr(
            getattr(self.store, "embedder", None) or self.embedder, "pipeline", None
        )
        coalescer = getattr(coalescer, "coalescer", None)
        retrieve_queries, retrieve_writer = rest_connector(
            webserver=webserver,
            route="/v1/retrieve",
            schema=self.QuerySchema,
            methods=("GET", "POST"),
            delete_completed_queries=True,
            max_pending=max_pending,
            shed_stage="embed.shed",
            retry_after=(
                coalescer.retry_after_s if coalescer is not None else None
            ),
            # second line of defense: the coalescer's row-queue cap
            # (PATHWAY_EMBED_MAX_QUEUE_ROWS) probed pre-admission, so a slow
            # encoder sheds on queued ROWS even while fewer than max_pending
            # REQUESTS are in flight
            overload_probe=(
                coalescer.overloaded if coalescer is not None else None
            ),
        )
        retrieve_writer(self.retrieve_query(retrieve_queries))

        stats_queries, stats_writer = rest_connector(
            webserver=webserver,
            route="/v1/statistics",
            schema=self.StatisticsSchema,
            methods=("GET", "POST"),
            delete_completed_queries=True,
        )
        stats_writer(self.statistics_query(stats_queries))

        inputs_queries, inputs_writer = rest_connector(
            webserver=webserver,
            route="/v1/inputs",
            schema=self.InputsQuerySchema,
            methods=("GET", "POST"),
            delete_completed_queries=True,
        )
        inputs_writer(self.inputs_query(inputs_queries))

        def run() -> None:
            pw.run(
                monitoring_level=pw.MonitoringLevel.NONE,
                terminate_on_error=terminate_on_error,
            )

        if threaded:
            thread = threading.Thread(target=run, daemon=True, name="pathway:vector-server")
            thread.start()
            return thread
        run()
        return None


class VectorStoreClient:
    """HTTP client for VectorStoreServer (reference ``vector_store.py`` client)."""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: int = 15,
        additional_headers: dict | None = None,
    ):
        self.url = url if url is not None else f"http://{host}:{port}"
        self.timeout = timeout
        self.headers = {"Content-Type": "application/json", **(additional_headers or {})}

    def query(
        self, query: str, k: int = 3, metadata_filter: str | None = None, filepath_globpattern: str | None = None
    ) -> list:
        import requests

        data = {"query": query, "k": k}
        if metadata_filter is not None:
            data["metadata_filter"] = metadata_filter
        if filepath_globpattern is not None:
            data["filepath_globpattern"] = filepath_globpattern
        response = requests.post(
            self.url + "/v1/retrieve", json=data, headers=self.headers, timeout=self.timeout
        )
        response.raise_for_status()
        return response.json()

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        import requests

        response = requests.post(
            self.url + "/v1/statistics", json={}, headers=self.headers, timeout=self.timeout
        )
        response.raise_for_status()
        return response.json()

    def get_input_files(
        self, metadata_filter: str | None = None, filepath_globpattern: str | None = None
    ) -> list:
        import requests

        response = requests.post(
            self.url + "/v1/inputs",
            json={"metadata_filter": metadata_filter, "filepath_globpattern": filepath_globpattern},
            headers=self.headers,
            timeout=self.timeout,
        )
        response.raise_for_status()
        return response.json()
