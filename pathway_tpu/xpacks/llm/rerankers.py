"""Rerankers (parity: reference ``xpacks/llm/rerankers.py:58-172``)."""

from __future__ import annotations

from typing import Any

import numpy as np

import pathway_tpu.internals.expression as expr
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.reducers import reducers
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.udfs import UDF
from pathway_tpu.xpacks.llm.llms import BaseChat
from pathway_tpu.xpacks.llm import prompts


class LLMReranker(UDF):
    """Score query/doc relevance 1-5 via a chat model (reference ``:58``)."""

    def __init__(self, llm: BaseChat, *, retry_strategy: Any = None, cache_strategy: Any = None, use_logit_bias: bool | None = None):
        super().__init__(cache_strategy=cache_strategy)
        self.llm = llm

        def rerank(doc: str, query: str) -> float:
            raise RuntimeError("LLMReranker is applied via __call__, not func")

        self.func = rerank

    def __call__(self, doc: Any, query: Any, **kwargs: Any) -> expr.ColumnExpression:
        from pathway_tpu.internals.json import Json

        prompt = expr.apply_with_type(
            lambda d, q: Json(
                [{"role": "user", "content": prompts.rerank_prompt(d, q)}]
            ),
            dt.JSON,
            doc,
            query,
        )
        raw = self.llm(prompt)

        def parse_score(response: Any) -> float:
            try:
                import re

                m = re.search(r"[1-5]", str(response))
                return float(m.group()) if m else 1.0
            except Exception:
                return 1.0

        return expr.apply_with_type(parse_score, float, raw)


class CrossEncoderReranker(UDF):
    """sentence-transformers CrossEncoder (torch CPU; reference ``:118``)."""

    def __init__(self, model_name: str, *, cache_strategy: Any = None, **init_kwargs: Any):
        super().__init__(cache_strategy=cache_strategy)
        import os

        os.environ.setdefault("HF_HUB_OFFLINE", "1")
        from sentence_transformers import CrossEncoder

        self.model = CrossEncoder(model_name, **init_kwargs)

        def rerank(doc: str, query: str) -> float:
            return float(self.model.predict((query, doc)))

        self.func = rerank


class EncoderReranker(UDF):
    """Bi-encoder cosine scoring on the TPU encoder (reference ``:152``)."""

    def __init__(self, model_name: str = "sentence-transformers/all-MiniLM-L6-v2", *, cache_strategy: Any = None, **init_kwargs: Any):
        super().__init__(cache_strategy=cache_strategy)
        from pathway_tpu.models.encoder import JaxSentenceEncoder

        self.encoder = JaxSentenceEncoder(model_name)

        def rerank(doc: str, query: str) -> float:
            vectors = self.encoder.encode([str(doc), str(query)])
            return float(np.dot(vectors[0], vectors[1]))

        self.func = rerank


def rerank_topk_filter(
    doc: expr.ColumnExpression, score: expr.ColumnExpression, k: int = 5
) -> expr.ColumnExpression:
    """Keep the top-k (docs, scores) from tuple columns (reference ``:172``)."""

    def topk(docs: tuple, scores: tuple) -> tuple:
        order = np.argsort(-np.asarray(scores, dtype=np.float64))[:k]
        return (
            tuple(docs[i] for i in order),
            tuple(float(scores[i]) for i in order),
        )

    return expr.apply_with_type(topk, tuple, doc, score)
