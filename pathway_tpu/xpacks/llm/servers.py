"""REST servers for RAG apps (parity: reference ``xpacks/llm/servers.py:16-227``)."""

from __future__ import annotations

import threading
from typing import Any, Optional

import pathway_tpu as pw
from pathway_tpu.internals.table import Table


class BaseRestServer:
    """Builds rest_connector endpoints over a webserver (reference ``:16``)."""

    def __init__(self, host: str, port: int, **rest_kwargs: Any):
        from pathway_tpu.io.http import PathwayWebserver

        self.host = host
        self.port = port
        self.webserver = PathwayWebserver(host=host, port=port, **rest_kwargs)

    def serve(
        self,
        route: str,
        schema: type,
        handler: Any,
        *,
        methods: tuple = ("POST",),
        retry_strategy: Any = None,
        cache_strategy: Any = None,
        **additional_endpoint_kwargs: Any,
    ) -> None:
        import warnings

        from pathway_tpu.io.http import rest_connector

        if retry_strategy is not None or cache_strategy is not None:
            # reference applies these to the endpoint's response path; engine-level UDF
            # caching isn't wired yet (TODO.md) — configure the strategies on the LLM /
            # embedder UDFs instead, which does work
            warnings.warn(
                "retry_strategy/cache_strategy on serve() are not applied yet; set them "
                "on the UDFs (e.g. OpenAIChat(retry_strategy=...)) instead",
                stacklevel=2,
            )
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            schema=schema,
            methods=methods,
            delete_completed_queries=True,
            **additional_endpoint_kwargs,
        )
        writer(handler(queries))

    def run(
        self,
        *,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
        **kwargs: Any,
    ) -> Any:
        # with_cache/cache_backend configure UDF caching in the reference; here caching is
        # set per-UDF via cache_strategy (see internals/udfs), so they are accepted for
        # API parity but have no engine-level effect yet (TODO.md).
        def target() -> None:
            pw.run(monitoring_level=pw.MonitoringLevel.NONE, terminate_on_error=terminate_on_error)

        if threaded:
            thread = threading.Thread(target=target, daemon=True, name="pathway:rest-server")
            thread.start()
            return thread
        target()
        return None


class DocumentStoreServer(BaseRestServer):
    """Serves retrieve/statistics/inputs of a DocumentStore (reference ``:92``)."""

    def __init__(self, host: str, port: int, document_store: Any, **rest_kwargs: Any):
        super().__init__(host, port, **rest_kwargs)
        store = document_store.store if hasattr(document_store, "store") else document_store
        self.serve(
            "/v1/retrieve", store.RetrieveQuerySchema, store.retrieve_query, methods=("GET", "POST")
        )
        self.serve(
            "/v1/statistics",
            store.StatisticsQuerySchema,
            store.statistics_query,
            methods=("GET", "POST"),
        )
        self.serve(
            "/v1/inputs", store.InputsQuerySchema, store.inputs_query, methods=("GET", "POST")
        )


class QARestServer(BaseRestServer):
    """Serves answer/retrieve/statistics/list_documents of a QuestionAnswerer (``:140``)."""

    def __init__(self, host: str, port: int, rag_question_answerer: Any, **rest_kwargs: Any):
        super().__init__(host, port, **rest_kwargs)
        qa = rag_question_answerer
        self.serve("/v1/pw_ai_answer", qa.AnswerQuerySchema, qa.answer_query)
        self.serve("/v2/answer", qa.AnswerQuerySchema, qa.answer_query)
        self.serve("/v1/retrieve", qa.RetrieveQuerySchema, qa.retrieve, methods=("GET", "POST"))
        self.serve("/v2/list_documents", qa.InputsQuerySchema, qa.list_documents, methods=("GET", "POST"))
        self.serve("/v1/statistics", qa.StatisticsQuerySchema, qa.statistics, methods=("GET", "POST"))


class QASummaryRestServer(QARestServer):
    """Adds the summarize endpoint (reference ``:193``)."""

    def __init__(self, host: str, port: int, rag_question_answerer: Any, **rest_kwargs: Any):
        super().__init__(host, port, rag_question_answerer, **rest_kwargs)
        qa = rag_question_answerer
        self.serve("/v1/pw_ai_summary", qa.SummarizeQuerySchema, qa.summarize_query)
        self.serve("/v2/summarize", qa.SummarizeQuerySchema, qa.summarize_query)
