"""Document parsers (parity: reference ``xpacks/llm/parsers.py:53-885``).

``ParseUtf8`` is always available; binary-format parsers (``ParseUnstructured``, ``OpenParse``,
``PypdfParser``, ``ImageParser``, ``SlideParser``) are gated on their libraries at call time
with the same constructor surfaces.
"""

from __future__ import annotations

import json
from typing import Any, Callable, List, Optional

from pathway_tpu.internals.json import Json
from pathway_tpu.internals.udfs import UDF


class ParseUtf8(UDF):
    """bytes/str → [(text, metadata)] (reference ``:53``)."""

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)

        def parse(contents: Any) -> list:
            if isinstance(contents, bytes):
                text = contents.decode("utf-8", errors="replace")
            else:
                text = str(contents)
            return [(text, {})]

        self.func = parse


Utf8Parser = ParseUtf8


class PypdfParser(UDF):
    """PDF → per-page docs via pypdf (reference ``:746``)."""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs: Any):
        super().__init__(**kwargs)
        self.apply_text_cleanup = apply_text_cleanup

        def parse(contents: bytes) -> list:
            try:
                import io

                from pypdf import PdfReader
            except ImportError as e:
                raise ImportError("pypdf is not installed in this environment") from e
            reader = PdfReader(io.BytesIO(contents))
            docs = []
            for page_num, page in enumerate(reader.pages):
                text = page.extract_text() or ""
                if self.apply_text_cleanup:
                    text = " ".join(text.split())
                docs.append((text, {"page": page_num}))
            return docs

        self.func = parse


class ParseUnstructured(UDF):
    """unstructured.io partitioning (reference ``:79``); gated on the library."""

    def __init__(self, mode: str = "single", post_processors: list | None = None, **unstructured_kwargs: Any):
        super().__init__()
        self.mode = mode
        self.post_processors = post_processors or []
        self.kwargs = dict(unstructured_kwargs)

        def parse(contents: Any) -> list:
            try:
                from unstructured.partition.auto import partition
            except ImportError as e:
                raise ImportError(
                    "unstructured is not installed; use ParseUtf8 or PypdfParser"
                ) from e
            import io

            elements = partition(
                file=io.BytesIO(contents) if isinstance(contents, bytes) else None,
                text=contents if isinstance(contents, str) else None,
                **self.kwargs,
            )
            for el in elements:
                for proc in self.post_processors:
                    el.apply(proc)
            if self.mode == "single":
                text = "\n\n".join(str(el) for el in elements)
                return [(text, {})]
            return [(str(el), el.metadata.to_dict() if el.metadata else {}) for el in elements]

        self.func = parse


UnstructuredParser = ParseUnstructured


class ImageParser(UDF):
    def __init__(self, llm: Any = None, parse_prompt: str | None = None, **kwargs: Any):
        super().__init__()
        raise NotImplementedError(
            "ImageParser needs a vision LLM client; not available in this environment "
            "(reference parsers.py:396)"
        )


class SlideParser(UDF):
    def __init__(self, **kwargs: Any):
        super().__init__()
        raise NotImplementedError(
            "SlideParser is licensed/vision-dependent in the reference (parsers.py:569)"
        )
