"""Document parsers (parity: reference ``xpacks/llm/parsers.py:53-885``).

``ParseUtf8`` is always available; binary-format parsers (``ParseUnstructured``, ``OpenParse``,
``PypdfParser``, ``ImageParser``, ``SlideParser``) are gated on their libraries at call time
with the same constructor surfaces.
"""

from __future__ import annotations

import json
from typing import Any, Callable, List, Optional

from pathway_tpu.internals.json import Json
from pathway_tpu.internals.udfs import UDF


class ParseUtf8(UDF):
    """bytes/str → [(text, metadata)] (reference ``:53``)."""

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)

        def parse(contents: Any) -> list:
            if isinstance(contents, bytes):
                text = contents.decode("utf-8", errors="replace")
            else:
                text = str(contents)
            return [(text, {})]

        self.func = parse


Utf8Parser = ParseUtf8


class PypdfParser(UDF):
    """PDF → per-page docs via pypdf (reference ``:746``)."""

    def __init__(self, apply_text_cleanup: bool = True, **kwargs: Any):
        super().__init__(**kwargs)
        self.apply_text_cleanup = apply_text_cleanup

        def parse(contents: bytes) -> list:
            try:
                import io

                from pypdf import PdfReader
            except ImportError as e:
                raise ImportError("pypdf is not installed in this environment") from e
            reader = PdfReader(io.BytesIO(contents))
            docs = []
            for page_num, page in enumerate(reader.pages):
                text = page.extract_text() or ""
                if self.apply_text_cleanup:
                    text = " ".join(text.split())
                docs.append((text, {"page": page_num}))
            return docs

        self.func = parse


class ParseUnstructured(UDF):
    """unstructured.io partitioning (reference ``:79``); gated on the library."""

    def __init__(self, mode: str = "single", post_processors: list | None = None, **unstructured_kwargs: Any):
        super().__init__()
        self.mode = mode
        self.post_processors = post_processors or []
        self.kwargs = dict(unstructured_kwargs)

        def parse(contents: Any) -> list:
            try:
                from unstructured.partition.auto import partition
            except ImportError as e:
                raise ImportError(
                    "unstructured is not installed; use ParseUtf8 or PypdfParser"
                ) from e
            import io

            elements = partition(
                file=io.BytesIO(contents) if isinstance(contents, bytes) else None,
                text=contents if isinstance(contents, str) else None,
                **self.kwargs,
            )
            for el in elements:
                for proc in self.post_processors:
                    el.apply(proc)
            if self.mode == "single":
                text = "\n\n".join(str(el) for el in elements)
                return [(text, {})]
            return [(str(el), el.metadata.to_dict() if el.metadata else {}) for el in elements]

        self.func = parse


UnstructuredParser = ParseUnstructured


DEFAULT_IMAGE_PARSE_PROMPT = (
    "Describe the contents of this image precisely, including any visible text, "
    "tables, and figures."
)


def _image_to_b64(img: Any, fmt: str = "PNG") -> str:
    import base64
    import io

    buf = io.BytesIO()
    img.save(buf, format=fmt)
    return base64.b64encode(buf.getvalue()).decode()


def _vision_describe(llm: Any, prompt: str, b64: str) -> str:
    """One vision-LLM call in the OpenAI image_url message shape (the wire format
    the reference's ImageParser builds, ``parsers.py:396``)."""
    messages = [
        {
            "role": "user",
            "content": [
                {"type": "text", "text": prompt},
                {
                    "type": "image_url",
                    "image_url": {"url": f"data:image/png;base64,{b64}"},
                },
            ],
        }
    ]
    fn = getattr(llm, "func", None) or llm
    return str(fn(messages))


class ImageParser(UDF):
    """image bytes → [(description, metadata)] via a vision LLM (reference ``:396``).

    The image decodes with PIL, optionally downsizes to ``downsize_horizontal_width``
    (vision-token budget control, as in the reference), encodes to base64, and goes to
    ``llm`` as an OpenAI-style ``image_url`` chat message. ``llm``: any chat UDF or
    callable taking a messages list (tests inject fakes).
    """

    def __init__(
        self,
        llm: Any = None,
        parse_prompt: str = DEFAULT_IMAGE_PARSE_PROMPT,
        downsize_horizontal_width: int | None = 1280,
        include_metadata: bool = True,
        **kwargs: Any,
    ):
        super().__init__()
        self.llm = llm
        self.parse_prompt = parse_prompt
        self.downsize_horizontal_width = downsize_horizontal_width
        self.include_metadata = include_metadata

        def parse(contents: bytes) -> list:
            if self.llm is None:
                raise ValueError(
                    "ImageParser needs a vision-capable `llm` (a chat UDF or any "
                    "callable accepting an OpenAI-style messages list)"
                )
            import io

            from PIL import Image

            img = Image.open(io.BytesIO(contents))
            img.load()
            width, height = img.size
            if (
                self.downsize_horizontal_width
                and width > self.downsize_horizontal_width
            ):
                ratio = self.downsize_horizontal_width / width
                img = img.resize(
                    (self.downsize_horizontal_width, max(1, int(height * ratio)))
                )
            if img.mode not in ("RGB", "L"):
                img = img.convert("RGB")
            text = _vision_describe(self.llm, self.parse_prompt, _image_to_b64(img))
            meta = (
                {"width": width, "height": height, "format": "png"}
                if self.include_metadata
                else {}
            )
            return [(text, meta)]

        self.func = parse


def _default_rasterizer(contents: bytes) -> list:
    """PDF/slide bytes → list of PIL images, one per slide/page."""
    try:
        from pdf2image import convert_from_bytes
    except ImportError as e:
        raise ImportError(
            "SlideParser needs a slide rasterizer: install pdf2image (poppler) or "
            "pass _rasterizer=... (bytes -> list of PIL images)"
        ) from e
    return convert_from_bytes(contents)


class SlideParser(UDF):
    """slide-deck bytes → one vision-parsed doc per slide (reference ``:569``;
    entitlement-gated there, open here).

    Each slide rasterizes to an image and goes through the same vision-LLM path as
    ``ImageParser``; metadata carries the slide number and count. Rasterization is
    injectable (``_rasterizer``) so tests run without poppler.
    """

    def __init__(
        self,
        llm: Any = None,
        parse_prompt: str = DEFAULT_IMAGE_PARSE_PROMPT,
        downsize_horizontal_width: int | None = 1280,
        _rasterizer: Callable[[bytes], list] | None = None,
        **kwargs: Any,
    ):
        super().__init__()
        self.llm = llm
        self.parse_prompt = parse_prompt
        self.downsize_horizontal_width = downsize_horizontal_width
        self.rasterizer = _rasterizer or _default_rasterizer

        def parse(contents: bytes) -> list:
            if self.llm is None:
                raise ValueError(
                    "SlideParser needs a vision-capable `llm` (a chat UDF or any "
                    "callable accepting an OpenAI-style messages list)"
                )
            images = self.rasterizer(contents)
            docs = []
            for i, img in enumerate(images):
                if (
                    self.downsize_horizontal_width
                    and img.size[0] > self.downsize_horizontal_width
                ):
                    ratio = self.downsize_horizontal_width / img.size[0]
                    img = img.resize(
                        (
                            self.downsize_horizontal_width,
                            max(1, int(img.size[1] * ratio)),
                        )
                    )
                text = _vision_describe(
                    self.llm, self.parse_prompt, _image_to_b64(img)
                )
                docs.append((text, {"slide": i, "slide_count": len(images)}))
            return docs

        self.func = parse
