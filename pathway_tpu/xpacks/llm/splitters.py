"""Text splitters (parity: reference ``xpacks/llm/splitters.py:34`` TokenCountSplitter)."""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

from pathway_tpu.internals.udfs import UDF


def _get_tokenizer(encoding_name: str) -> Tuple[Callable, Callable]:
    """(encode, decode); tiktoken when its BPE files are cached, whitespace fallback else."""
    try:
        import tiktoken

        tokenizer = tiktoken.get_encoding(encoding_name)
        probe = tokenizer.encode_ordinary("probe")  # may hit network for BPE files
        return tokenizer.encode_ordinary, tokenizer.decode
    except Exception:
        def encode(text: str) -> list:
            return text.split()

        def decode(tokens: list) -> str:
            return " ".join(tokens)

        return encode, decode


class TokenCountSplitter(UDF):
    """Split text into chunks of [min_tokens, max_tokens] tokens, preferring sentence
    boundaries (reference semantics)."""

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.encoding_name = encoding_name
        self._codec: Tuple[Callable, Callable] | None = None

        def split(txt: str, metadata: Any = None) -> list:
            if self._codec is None:
                self._codec = _get_tokenizer(self.encoding_name)
            encode, decode = self._codec
            tokens = encode(str(txt))
            meta = metadata if metadata is not None else {}
            output: list = []
            i = 0
            while i < len(tokens):
                window = tokens[i : i + self.max_tokens]
                chunk = decode(window)
                cut_chars = len(chunk)
                n_consumed = len(window)
                if i + self.max_tokens < len(tokens):
                    min_chars = len(decode(window[: self.min_tokens]))
                    for punct in (". ", "\n\n", "\n", "; ", ", ", " "):
                        pos = chunk.rfind(punct)
                        if pos > min_chars:
                            cut_chars = pos + len(punct)
                            n_consumed = max(1, len(encode(chunk[:cut_chars])))
                            break
                piece = chunk[:cut_chars].strip()
                if piece:
                    output.append((piece, meta))
                i += n_consumed
            return output or [("", meta)]

        self.func = split


class NullSplitter(UDF):
    """Pass the document through as a single chunk."""

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)

        def split(txt: str, metadata: Any = None) -> list:
            return [(str(txt), metadata if metadata is not None else {})]

        self.func = split
