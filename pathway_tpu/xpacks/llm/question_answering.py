"""RAG question answering (parity: reference ``xpacks/llm/question_answering.py:288-736``).

``BaseRAGQuestionAnswerer`` (``:314``): answer / retrieve / statistics / list_documents over a
DocumentStore + chat model; ``AdaptiveRAGQuestionAnswerer`` (``:620``) grows the retrieved
context geometrically until the model answers.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm import prompts
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.llms import BaseChat, prompt_chat_single_qa


class BaseQuestionAnswerer:
    """Abstract query surfaces used by the REST servers (reference ``:288``)."""

    AnswerQuerySchema: type = pw.Schema
    RetrieveQuerySchema: type = pw.Schema
    StatisticsQuerySchema: type = pw.Schema
    InputsQuerySchema: type = pw.Schema

    def answer_query(self, queries: Table) -> Table:
        raise NotImplementedError

    def retrieve(self, queries: Table) -> Table:
        raise NotImplementedError

    def statistics(self, queries: Table) -> Table:
        raise NotImplementedError

    def list_documents(self, queries: Table) -> Table:
        raise NotImplementedError


class SummaryQuestionAnswerer(BaseQuestionAnswerer):
    SummarizeQuerySchema: type = pw.Schema

    def summarize_query(self, queries: Table) -> Table:
        raise NotImplementedError


class BaseRAGQuestionAnswerer(SummaryQuestionAnswerer):
    """Standard RAG: retrieve k docs, build prompt, ask the chat model (reference ``:314``)."""

    class AnswerQuerySchema(pw.Schema):
        prompt: str
        filters: str | None = pw.column_definition(default_value=None)
        model: str | None = pw.column_definition(default_value=None)
        return_context_docs: bool = pw.column_definition(default_value=False, dtype=bool)

    class SummarizeQuerySchema(pw.Schema):
        text_list: pw.Json

    RetrieveQuerySchema = DocumentStore.RetrieveQuerySchema
    StatisticsQuerySchema = DocumentStore.StatisticsQuerySchema
    InputsQuerySchema = DocumentStore.InputsQuerySchema

    def __init__(
        self,
        llm: BaseChat,
        indexer: DocumentStore | Any,
        *,
        default_llm_name: str | None = None,
        short_prompt_template: Callable = prompts.prompt_short_qa,
        long_prompt_template: Callable = prompts.prompt_qa,
        summarize_template: Callable = prompts.prompt_summarize,
        search_topk: int = 6,
        prompt_template: Callable | None = None,
    ):
        self.llm = llm
        self.indexer = indexer.store if hasattr(indexer, "store") else indexer
        self.search_topk = search_topk
        self.prompt_template = prompt_template or long_prompt_template
        self.short_prompt_template = short_prompt_template
        self.summarize_template = summarize_template
        self.default_llm_name = default_llm_name
        self._server_thread = None

    def _model_expr(self, queries: Table) -> Any:
        """Per-query model override, falling back to ``default_llm_name`` (the chat UDF
        drops a None model and uses its own default)."""
        default = self.default_llm_name
        if "model" in queries.column_names():
            return expr.apply_with_type(
                lambda m: m if m is not None else default,
                dt.Optional_(dt.STR),
                queries.model,
            )
        return default

    # -- query surfaces -----------------------------------------------------

    def answer_query(self, queries: Table) -> Table:
        names = queries.column_names()
        retrieval_queries = queries.select(
            query=queries.prompt,
            k=self.search_topk,
            metadata_filter=queries.filters if "filters" in names else None,
            filepath_globpattern=None,
        )
        retrieved = self.indexer.retrieve_query(retrieval_queries)
        # retrieved shares the queries' key set (DataIndex joins back on the query id)
        with_docs = queries.with_columns(_pw_docs=retrieved.result)
        template = self.prompt_template
        prompt_col = expr.apply_with_type(
            lambda q, docs: prompt_chat_single_qa(
                template(q, tuple(docs.value if isinstance(docs, Json) else docs))
            ),
            dt.JSON,
            queries.prompt,
            with_docs._pw_docs,
        )
        raw_answer = self.llm(prompt_col, model=self._model_expr(queries))
        result = with_docs.select(
            response=expr.apply_with_type(
                _format_answer,
                dt.JSON,
                raw_answer,
                with_docs._pw_docs,
                queries.return_context_docs if "return_context_docs" in names else False,
            ),
        )
        return result.with_columns(result=result.response)

    # reference naming
    answer = answer_query

    def summarize_query(self, queries: Table) -> Table:
        template = self.summarize_template
        prompt_col = expr.apply_with_type(
            lambda tl: prompt_chat_single_qa(
                template(tuple(tl.value if isinstance(tl, Json) else tl))
            ),
            dt.JSON,
            queries.text_list,
        )
        raw = self.llm(prompt_col)
        return queries.select(result=raw)

    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, queries: Table) -> Table:
        return self.indexer.statistics_query(queries)

    def list_documents(self, queries: Table) -> Table:
        return self.indexer.inputs_query(queries)

    # -- serving ------------------------------------------------------------

    def build_server(self, host: str, port: int, **kwargs: Any) -> None:
        from pathway_tpu.xpacks.llm.servers import QASummaryRestServer

        self.server = QASummaryRestServer(host, port, self, **kwargs)

    def run_server(self, *args: Any, **kwargs: Any) -> Any:
        if not hasattr(self, "server"):
            raise ValueError("run build_server first")
        return self.server.run(*args, **kwargs)


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Geometric context growth (reference ``:620``): try n_starting_documents, re-ask with
    factor× more docs until the model finds an answer or max_iterations is hit."""

    def __init__(
        self,
        llm: BaseChat,
        indexer: Any,
        *,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        not_found_response: str = "No information",
        **kwargs: Any,
    ):
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        # strict_prompt forces the terse template (fewer tokens per adaptive round,
        # reference ``question_answering.py:620`` behavior switch)
        if strict_prompt and kwargs.get("prompt_template") is None:
            self.prompt_template = self.short_prompt_template
        # the adaptive loop grows context while answers contain this marker; keep it in
        # sync with the prompt's information_not_found_response
        self.not_found_response = not_found_response

    def answer_query(self, queries: Table) -> Table:
        names = queries.column_names()
        max_k = self.n_starting_documents * (self.factor ** (self.max_iterations - 1))
        retrieval_queries = queries.select(
            query=queries.prompt,
            k=max_k,
            metadata_filter=queries.filters if "filters" in names else None,
            filepath_globpattern=None,
        )
        retrieved = self.indexer.retrieve_query(retrieval_queries)
        with_docs = queries.with_columns(_pw_docs=retrieved.result)

        # wrapped fn keeps the UDF's capacity/retry/cache behavior
        llm_fun, _llm_is_async = self.llm._wrapped_fun()
        template = self.prompt_template
        not_found = self.not_found_response
        n0, factor, max_iter = self.n_starting_documents, self.factor, self.max_iterations

        @pw.udf
        async def adaptive_answer(q: str, docs: Any) -> str:
            import asyncio

            doc_list = list(docs.value if isinstance(docs, Json) else docs)
            n = n0
            answer = None
            for _ in range(max_iter):
                subset = tuple(doc_list[:n])
                prompt = prompt_chat_single_qa(template(q, subset))
                result = llm_fun(prompt)
                if asyncio.iscoroutine(result):
                    result = await result
                answer = result
                if answer and not_found not in str(answer):
                    return str(answer)
                if n >= len(doc_list):
                    break
                n *= factor
            return str(answer)

        result = with_docs.select(result=adaptive_answer(queries.prompt, with_docs._pw_docs))
        return result


class DeckRetriever(BaseQuestionAnswerer):
    """Slide-deck retrieval preset (reference ``:736``)."""

    def __init__(self, *args: Any, **kwargs: Any):
        raise NotImplementedError(
            "DeckRetriever depends on SlideParser (licensed in the reference)"
        )


def _format_answer(answer: Any, docs: Any, return_context: Any) -> Json:
    payload: dict = {"response": answer}
    if return_context:
        payload["context_docs"] = docs.value if isinstance(docs, Json) else list(docs)
    return Json(payload)
