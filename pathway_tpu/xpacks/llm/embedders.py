"""Embedders (parity: reference ``xpacks/llm/embedders.py:64-401``).

``SentenceTransformerEmbedder`` is the TPU-native flagship: the HF encoder re-hosted as a
jit'd Flax module (``pathway_tpu/models/encoder.py``) with column-batched dispatch — the whole
commit batch crosses host→device once. API-backed embedders (OpenAI/LiteLLM/Gemini) are async
UDFs with capacity/retry/cache, gated on their client libraries.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.udfs import (
    AsyncRetryStrategy,
    CacheStrategy,
    UDF,
    async_executor,
)


class BaseEmbedder(UDF):
    # Embedders that know their output width up front set this (constructor
    # table/kwarg) so graph build never pays a real encode of "." — for the
    # API-backed embedders that probe was a NETWORK call (and an asyncio.run)
    # per index construction.
    _dimension: int | None = None

    def get_embedding_dimension(self, **kwargs: Any) -> int:
        if self._dimension is not None and not kwargs:
            return int(self._dimension)
        result = self.func(".", **kwargs)  # type: ignore[misc]
        import asyncio

        if asyncio.iscoroutine(result):
            result = asyncio.run(result)
        return len(result)


# Output widths of the fixed-dimension API models (the reference docs' values):
# consulted at graph-build time so known models skip the probe encode entirely.
_KNOWN_EMBED_DIMS = {
    "text-embedding-3-small": 1536,
    "text-embedding-3-large": 3072,
    "text-embedding-ada-002": 1536,
    "models/embedding-001": 768,
    "models/text-embedding-004": 768,
}


def _known_dim(model: str | None) -> int | None:
    if model is None:
        return None
    # litellm routes as "provider/model": match on the tail as well
    return _KNOWN_EMBED_DIMS.get(model) or _KNOWN_EMBED_DIMS.get(
        model.rsplit("/", 1)[-1]
    )


class SentenceTransformerEmbedder(BaseEmbedder):
    """Local encoder on the TPU (reference ``:270`` — torch ``model.encode`` at ``:315``)."""

    def __init__(
        self,
        model: str = "sentence-transformers/all-MiniLM-L6-v2",
        *,
        call_kwargs: "dict | None" = None,
        device: str = "tpu",
        batch_size: int = 1024,
        max_wait_ms: float = 2.0,
        max_coalesce_batch: int = 256,
        sub_batch: int = 128,
        embed_cache_size: int = 50_000,
        encoder_config: Any = None,
        encoder_service: "bool | None" = None,
        semantic_cache: "str | None" = None,
        semantic_cache_size: "int | None" = None,
        semantic_threshold: "float | None" = None,
        encsvc_tick_ms: "float | None" = None,
        encsvc_max_in_flight: "int | None" = None,
        encsvc_prewarm: "bool | None" = None,
        **kwargs: Any,
    ):
        """``max_wait_ms``/``max_coalesce_batch``: legacy query-coalescer batch
        window (only used with the encoder service off); ``sub_batch``:
        length-sorted ingest sub-batch rows; ``embed_cache_size``:
        content-hash LRU entries (0 disables); ``encoder_config``: override
        ``EncoderConfig`` (tests use a tiny architecture);
        ``encoder_service``: persistent continuously-batched encoder worker on
        the query path (None = ``PATHWAY_ENCSVC`` env, default on);
        ``semantic_cache``: ``exact``/``cosine``/``off`` (None =
        ``PATHWAY_ENCSVC_SEMANTIC``, default exact — bitwise-honest) with
        ``semantic_cache_size``/``semantic_threshold``;
        ``encsvc_tick_ms``/``encsvc_max_in_flight``/``encsvc_prewarm``:
        service tick bound, rows packed per tick, and startup jit pre-warm
        (None = ``PATHWAY_ENCSVC_TICK_MS``/``_MAX_INFLIGHT``/``_PREWARM``)."""
        super().__init__(**kwargs)
        from pathway_tpu.models.embed_pipeline import EmbedPipeline
        from pathway_tpu.models.encoder import JaxSentenceEncoder

        if device not in ("tpu", None):
            import warnings

            warnings.warn(
                f"device={device!r} ignored: the encoder runs on the default JAX backend "
                "(TPU when available)",
                stacklevel=2,
            )
        if call_kwargs:
            import warnings

            warnings.warn(
                f"call_kwargs {sorted(call_kwargs)} are torch SentenceTransformer options "
                "with no JAX equivalent; ignored",
                stacklevel=2,
            )
        self.encoder = JaxSentenceEncoder(model, config=encoder_config)
        self.batch_size = batch_size
        self.pipeline = EmbedPipeline(
            self.encoder,
            model=model,
            max_wait_ms=max_wait_ms,
            max_batch=max_coalesce_batch,
            sub_batch=sub_batch,
            cache_size=embed_cache_size,
            service_mode=encoder_service,
            semantic_mode=semantic_cache,
            semantic_size=semantic_cache_size,
            semantic_threshold=semantic_threshold,
            tick_ms=encsvc_tick_ms,
            max_in_flight=encsvc_max_in_flight,
            prewarm=encsvc_prewarm,
        )

        def embed_one(text: str) -> np.ndarray:
            return self.pipeline.encode_batch([str(text)])[0]

        self.func = embed_one

    def __call__(self, *args: Any, **kwargs: Any) -> expr.ColumnExpression:
        pipeline = self.pipeline

        def embed_batch(texts: List[str]) -> List[np.ndarray]:
            vectors = pipeline.encode_batch(texts)
            return [vectors[i] for i in range(len(texts))]

        return expr.BatchApplyExpression(
            embed_batch,
            np.ndarray,
            False,
            True,
            args,
            kwargs,
            max_batch_size=self.batch_size,
        )

    def device_expression(self, *args: Any, **kwargs: Any) -> expr.ColumnExpression:
        """Query-path variant: embedding cells are DEVICE-resident jax slices so
        downstream device kernels (KNN search) chain without a host round-trip.
        Runs through the pipeline's content-hash + semantic caches and submits
        misses into the persistent encoder service's continuous batch (the
        coalescer admission shim), so a solo query dispatches immediately into
        a pre-warmed jit bucket, concurrent retrieve queries share one encoder
        dispatch, and repeated/equivalent texts skip the forward entirely.

        Declared ``deterministic=False`` so the engine memoizes each query row's
        embedding and REPLAYS it on retraction (the rest connector's
        delete-completed-queries cleanup) instead of re-running the encoder — one
        encode per query, with the memo entry popped on retraction. The content
        cache sits BELOW that memo: it never answers retraction rows, it only
        dedups forward work across distinct rows with equal text."""
        pipeline = self.pipeline

        def embed_batch(texts: List[str]) -> List[Any]:
            return pipeline.embed_query_rows([str(t) for t in texts])

        return expr.BatchApplyExpression(
            embed_batch,
            np.ndarray,
            False,
            False,
            args,
            kwargs,
            max_batch_size=self.batch_size,
        )

    def pipeline_stats(self) -> dict:
        """Cache/coalescer/pad-waste counters (surfaced by
        ``DocumentStore.statistics_query`` and the bench's embedpipe section)."""
        return self.pipeline.stats()

    def get_embedding_dimension(self, **kwargs: Any) -> int:
        return self.encoder.dim


class OpenAIEmbedder(BaseEmbedder):
    """OpenAI embeddings API (reference ``:85``)."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "text-embedding-3-small",
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        api_key: str | None = None,
        **openai_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(openai_kwargs)
        # graph build learns the dim WITHOUT a network call: an explicit
        # ``dimensions=`` request (v3 models) wins, else the model table
        if "dimensions" in self.kwargs:
            self._dimension = int(self.kwargs["dimensions"])
        else:
            self._dimension = _known_dim(model)
        self.api_key = api_key
        self._client: Any = None
        self._client_loop: Any = None

        async def embed(input: str, **kwargs: Any) -> list:
            import asyncio

            # cache per event loop: each commit batch runs under its own asyncio.run()
            loop = asyncio.get_running_loop()
            if self._client is None or self._client_loop is not loop:
                try:
                    import openai
                except ImportError as e:
                    raise ImportError("openai client library is not installed") from e
                from pathway_tpu.xpacks.llm._utils import close_async_client

                await close_async_client(self._client)
                self._client = openai.AsyncOpenAI(api_key=self.api_key)
                self._client_loop = loop
            response = await self._client.embeddings.create(
                input=[input or "."], model=kwargs.get("model", self.model), **self.kwargs
            )
            return response.data[0].embedding

        self.func = embed


class LiteLLMEmbedder(BaseEmbedder):
    """LiteLLM multi-provider embeddings (reference ``:180``)."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        **litellm_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(litellm_kwargs)
        if "dimensions" in self.kwargs:
            self._dimension = int(self.kwargs["dimensions"])
        else:
            self._dimension = _known_dim(model)

        async def embed(input: str, **kwargs: Any) -> list:
            try:
                import litellm
            except ImportError as e:
                raise ImportError("litellm is not installed") from e
            response = await litellm.aembedding(
                input=[input or "."], model=kwargs.get("model", self.model), **self.kwargs
            )
            return response.data[0]["embedding"]

        self.func = embed


class GeminiEmbedder(BaseEmbedder):
    """Google Gemini embeddings (reference ``:330``)."""

    def __init__(
        self,
        model: str | None = "models/embedding-001",
        capacity: int | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        **genai_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(genai_kwargs)
        if "output_dimensionality" in self.kwargs:
            self._dimension = int(self.kwargs["output_dimensionality"])
        else:
            self._dimension = _known_dim(model)

        async def embed(input: str, **kwargs: Any) -> list:
            try:
                import google.generativeai as genai
            except ImportError as e:
                raise ImportError("google-generativeai is not installed") from e
            response = genai.embed_content(
                content=input or ".", model=kwargs.get("model", self.model), **self.kwargs
            )
            return response["embedding"]

        self.func = embed
