"""Embedders (parity: reference ``xpacks/llm/embedders.py:64-401``).

``SentenceTransformerEmbedder`` is the TPU-native flagship: the HF encoder re-hosted as a
jit'd Flax module (``pathway_tpu/models/encoder.py``) with column-batched dispatch — the whole
commit batch crosses host→device once. API-backed embedders (OpenAI/LiteLLM/Gemini) are async
UDFs with capacity/retry/cache, gated on their client libraries.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.udfs import (
    AsyncRetryStrategy,
    CacheStrategy,
    UDF,
    async_executor,
)


class BaseEmbedder(UDF):
    def get_embedding_dimension(self, **kwargs: Any) -> int:
        result = self.func(".", **kwargs)  # type: ignore[misc]
        import asyncio

        if asyncio.iscoroutine(result):
            result = asyncio.run(result)
        return len(result)


class SentenceTransformerEmbedder(BaseEmbedder):
    """Local encoder on the TPU (reference ``:270`` — torch ``model.encode`` at ``:315``)."""

    def __init__(
        self,
        model: str = "sentence-transformers/all-MiniLM-L6-v2",
        *,
        call_kwargs: dict = {},
        device: str = "tpu",
        batch_size: int = 1024,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        from pathway_tpu.models.encoder import JaxSentenceEncoder

        if device not in ("tpu", None):
            import warnings

            warnings.warn(
                f"device={device!r} ignored: the encoder runs on the default JAX backend "
                "(TPU when available)",
                stacklevel=2,
            )
        if call_kwargs:
            import warnings

            warnings.warn(
                f"call_kwargs {sorted(call_kwargs)} are torch SentenceTransformer options "
                "with no JAX equivalent; ignored",
                stacklevel=2,
            )
        self.encoder = JaxSentenceEncoder(model)
        self.batch_size = batch_size

        def embed_one(text: str) -> np.ndarray:
            return self.encoder.encode([str(text)])[0]

        self.func = embed_one

    def __call__(self, *args: Any, **kwargs: Any) -> expr.ColumnExpression:
        encoder = self.encoder

        def embed_batch(texts: List[str]) -> List[np.ndarray]:
            vectors = encoder.encode([str(t) for t in texts])
            return [vectors[i] for i in range(len(texts))]

        return expr.BatchApplyExpression(
            embed_batch,
            np.ndarray,
            False,
            True,
            args,
            kwargs,
            max_batch_size=self.batch_size,
        )

    def device_expression(self, *args: Any, **kwargs: Any) -> expr.ColumnExpression:
        """Query-path variant: embedding cells are DEVICE-resident jax slices so
        downstream device kernels (KNN search) chain without a host round-trip.

        Declared ``deterministic=False`` so the engine memoizes each query row's
        embedding and REPLAYS it on retraction (the rest connector's
        delete-completed-queries cleanup) instead of re-running the encoder — one
        encode per query, with the memo entry popped on retraction."""
        encoder = self.encoder

        def embed_batch(texts: List[str]) -> List[Any]:
            vectors = encoder.encode_device([str(t) for t in texts])
            return [vectors[i] for i in range(len(texts))]

        return expr.BatchApplyExpression(
            embed_batch,
            np.ndarray,
            False,
            False,
            args,
            kwargs,
            max_batch_size=self.batch_size,
        )

    def get_embedding_dimension(self, **kwargs: Any) -> int:
        return self.encoder.dim


class OpenAIEmbedder(BaseEmbedder):
    """OpenAI embeddings API (reference ``:85``)."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "text-embedding-3-small",
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        api_key: str | None = None,
        **openai_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(openai_kwargs)
        self.api_key = api_key
        self._client: Any = None
        self._client_loop: Any = None

        async def embed(input: str, **kwargs: Any) -> list:
            import asyncio

            # cache per event loop: each commit batch runs under its own asyncio.run()
            loop = asyncio.get_running_loop()
            if self._client is None or self._client_loop is not loop:
                try:
                    import openai
                except ImportError as e:
                    raise ImportError("openai client library is not installed") from e
                from pathway_tpu.xpacks.llm._utils import close_async_client

                await close_async_client(self._client)
                self._client = openai.AsyncOpenAI(api_key=self.api_key)
                self._client_loop = loop
            response = await self._client.embeddings.create(
                input=[input or "."], model=kwargs.get("model", self.model), **self.kwargs
            )
            return response.data[0].embedding

        self.func = embed


class LiteLLMEmbedder(BaseEmbedder):
    """LiteLLM multi-provider embeddings (reference ``:180``)."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        **litellm_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(litellm_kwargs)

        async def embed(input: str, **kwargs: Any) -> list:
            try:
                import litellm
            except ImportError as e:
                raise ImportError("litellm is not installed") from e
            response = await litellm.aembedding(
                input=[input or "."], model=kwargs.get("model", self.model), **self.kwargs
            )
            return response.data[0]["embedding"]

        self.func = embed


class GeminiEmbedder(BaseEmbedder):
    """Google Gemini embeddings (reference ``:330``)."""

    def __init__(
        self,
        model: str | None = "models/embedding-001",
        capacity: int | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        **genai_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(genai_kwargs)

        async def embed(input: str, **kwargs: Any) -> list:
            try:
                import google.generativeai as genai
            except ImportError as e:
                raise ImportError("google-generativeai is not installed") from e
            response = genai.embed_content(
                content=input or ".", model=kwargs.get("model", self.model), **self.kwargs
            )
            return response["embedding"]

        self.func = embed
