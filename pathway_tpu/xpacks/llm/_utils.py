"""Shared helpers for the LLM xpack (reference ``xpacks/llm/_utils.py``)."""

from __future__ import annotations

from typing import Any


async def close_async_client(client: Any) -> None:
    """Best-effort close of a loop-bound async API client being replaced.

    The engine runs each commit batch under its own ``asyncio.run()`` loop, so clients
    cache per loop; when the loop changes the stale client's connection pool must be
    released rather than abandoned (it would otherwise leak sockets/fds every batch)."""
    if client is None:
        return
    try:
        await client.close()
    except Exception:
        # the old pool was bound to a dead loop; fall back to closing the raw transport
        inner = getattr(client, "_client", None)
        try:
            if inner is not None and hasattr(inner, "_transport"):
                await inner._transport.aclose()
        except Exception:
            pass
