"""LLM chat wrappers (parity: reference ``xpacks/llm/llms.py:27-654``).

``OpenAIChat`` (``:84``), ``LiteLLMChat`` (``:313``), ``HFPipelineChat`` (``:441``),
``CohereChat`` (``:544``) — async UDFs with capacity/retry/cache; clients gated at call time.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional

from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.udfs import (
    AsyncRetryStrategy,
    CacheStrategy,
    UDF,
    async_executor,
)


class BaseChat(UDF):
    """Common surface: call on a messages column (list of {role, content} dicts)."""

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return True


def _coerce_messages(messages: Any) -> List[dict]:
    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, str):
        return [{"role": "user", "content": messages}]
    out = []
    for m in messages:
        if isinstance(m, Json):
            m = m.value
        out.append(dict(m))
    return out


class OpenAIChat(BaseChat):
    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "gpt-4o-mini",
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        api_key: str | None = None,
        **openai_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(openai_kwargs)
        self.api_key = api_key
        self._client: Any = None
        self._client_loop: Any = None

        async def chat(messages: Any, **kwargs: Any) -> str | None:
            import asyncio

            # the engine runs each commit batch under its own asyncio.run() loop — a
            # client's connection pool is loop-bound, so cache per loop, reuse per batch
            loop = asyncio.get_running_loop()
            if self._client is None or self._client_loop is not loop:
                try:
                    import openai
                except ImportError as e:
                    raise ImportError("openai client library is not installed") from e
                from pathway_tpu.xpacks.llm._utils import close_async_client

                await close_async_client(self._client)
                self._client = openai.AsyncOpenAI(api_key=self.api_key)
                self._client_loop = loop
            merged = {k: v for k, v in {**self.kwargs, **kwargs}.items() if v is not None}
            merged.setdefault("model", self.model)
            response = await self._client.chat.completions.create(
                messages=_coerce_messages(messages), **merged
            )
            return response.choices[0].message.content

        self.func = chat


class LiteLLMChat(BaseChat):
    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        **litellm_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(litellm_kwargs)

        async def chat(messages: Any, **kwargs: Any) -> str | None:
            try:
                import litellm
            except ImportError as e:
                raise ImportError("litellm is not installed") from e
            merged = {k: v for k, v in {**self.kwargs, **kwargs}.items() if v is not None}
            merged.setdefault("model", self.model)
            response = await litellm.acompletion(messages=_coerce_messages(messages), **merged)
            return response.choices[0].message.content

        self.func = chat


class HFPipelineChat(BaseChat):
    """Local HuggingFace text-generation pipeline (CPU; reference ``:441``)."""

    def __init__(
        self,
        model: str | None = None,
        call_kwargs: "dict | None" = None,
        device: str = "cpu",
        cache_strategy: CacheStrategy | None = None,
        **pipeline_kwargs: Any,
    ):
        super().__init__(cache_strategy=cache_strategy)
        import os

        os.environ.setdefault("HF_HUB_OFFLINE", "1")
        os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")
        from transformers import pipeline

        self.pipeline = pipeline("text-generation", model=model, device=device, **pipeline_kwargs)
        self.call_kwargs = dict(call_kwargs or {})

        def chat(messages: Any, **kwargs: Any) -> str | None:
            coerced = _coerce_messages(messages)
            merged = {k: v for k, v in {**self.call_kwargs, **kwargs}.items() if v is not None}
            output = self.pipeline(coerced, **merged)
            result = output[0]["generated_text"]
            if isinstance(result, list):
                return result[-1]["content"]
            return result

        self.func = chat

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        tokens = self.pipeline.tokenizer.tokenize(input_string)
        if len(tokens) > max_prompt_length:
            tokens = tokens[-max_prompt_length:]
        return self.pipeline.tokenizer.convert_tokens_to_string(tokens)


class CohereChat(BaseChat):
    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "command",
        retry_strategy: AsyncRetryStrategy | None = None,
        cache_strategy: CacheStrategy | None = None,
        **cohere_kwargs: Any,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            retry_strategy=retry_strategy,
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(cohere_kwargs)

        async def chat(messages: Any, **kwargs: Any) -> tuple:
            try:
                import cohere
            except ImportError as e:
                raise ImportError("cohere client library is not installed") from e
            merged = {k: v for k, v in {**self.kwargs, **kwargs}.items() if v is not None}
            merged.setdefault("model", self.model)
            coerced = _coerce_messages(messages)
            client = cohere.AsyncClient()
            response = await client.chat(
                message=coerced[-1]["content"],
                chat_history=coerced[:-1],
                **merged,
            )
            cited_documents = [dict(d) for d in (response.documents or [])]
            return response.text, cited_documents

        self.func = chat


def prompt_chat_single_qa(question: str) -> Json:
    """Wrap a question into a single-message chat prompt (reference helper)."""
    return Json([{"role": "user", "content": str(question)}])
