"""``pathway_tpu`` command-line launcher.

Parity: reference ``python/pathway/cli.py`` — ``spawn`` (multi-process launcher setting
``PATHWAY_*`` env vars, ``:53-110``), ``spawn-from-env`` (``:284``), record/``replay``
(``:166,252``). Run as ``python -m pathway_tpu.cli <command>``.

Processes launched by ``spawn -n N`` form a cluster: each is told its
``PATHWAY_PROCESS_ID``/``PATHWAY_PROCESSES``/``PATHWAY_FIRST_PORT``, connectors shard
their source partitions (the reference's ``parallel_readers``), and key-partitioned
operators (groupby, join) hash-route every commit's rows to their key's owner process
over the full-mesh TCP exchange (``parallel/cluster.py`` — the reference's
``CommunicationConfig::Cluster``), so global aggregates are exact and each key is
owned by exactly one process. On-device scale-out uses the JAX mesh
(``pathway_tpu.parallel``) within each process.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import NoReturn

import click


def _plural(n: int, singular: str, plural: str) -> str:
    return f"1 {singular}" if n == 1 else f"{n} {plural}"


def _spawn_program(
    *, threads, processes, first_port, program, arguments, env_base,
    max_restarts=0, restart_mode="surgical", scale=None, control_port=None,
    autoscale=None,
):
    """Launch the cluster under the supervisor (``parallel/supervisor.py``):
    child exit codes and per-rank heartbeat status are monitored. On a worker
    crash the supervisor walks the escalation ladder — surgically relaunch
    just the dead rank into the live cluster (persistence on, ``--max-restarts``
    budget, ``--restart-mode surgical``), else restart the whole cluster from
    the persistence journal, else tear everything down with a per-rank
    post-mortem — never a hang."""
    from pathway_tpu.parallel.supervisor import Supervisor

    processes_str = _plural(processes, "process", "processes")
    workers_str = _plural(processes * threads, "total worker", "total workers")
    click.echo(f"Preparing {processes_str} ({workers_str})", err=True)
    scale_plan = None
    if scale:
        # `--scale N`: an elastic membership change to N once the cluster has
        # made its first commits (PATHWAY_SCALE_PLAN carries richer schedules)
        scale_plan = [{"after_commit": 1, "n": scale}]
    supervisor = Supervisor(
        processes=processes,
        threads=threads,
        first_port=first_port,
        program=program,
        arguments=arguments,
        env_base=env_base,
        max_restarts=max_restarts,
        restart_mode=restart_mode,
        scale_plan=scale_plan,
        control_port=control_port,
        autoscale=autoscale,
    )
    sys.exit(supervisor.run())


@click.group
def cli() -> None:
    pass


_SPAWN_SETTINGS = {"allow_interspersed_args": False, "show_default": True}


@cli.command(context_settings=_SPAWN_SETTINGS)
@click.option("-t", "--threads", metavar="N", type=int, default=1, help="number of threads per process")
@click.option("-n", "--processes", metavar="N", type=int, default=1, help="number of processes")
@click.option("--first-port", type=int, metavar="PORT", default=10000, help="first port to use for communication")
@click.option("--record", is_flag=True, help="record data in the input connectors")
@click.option("--record-path", type=str, default="record", help="directory in which record will be saved")
@click.option(
    "--max-restarts",
    type=int,
    metavar="N",
    default=0,
    help="relaunch workers up to N times after a crash, resuming from the "
    "persistence journal (requires the program to run with a persistence "
    "backend; 0 = fail fast with a post-mortem)",
)
@click.option(
    "--restart-mode",
    type=click.Choice(["surgical", "all"], case_sensitive=False),
    default="surgical",
    help="'surgical' relaunches only the dead rank and rejoins it into the "
    "live cluster (survivors hold at an epoch fence; falls back to restarting "
    "the whole cluster when the rejoin itself fails, and finally to a loud "
    "teardown); 'all' always restarts the whole cluster",
)
@click.option(
    "--scale",
    type=int,
    metavar="N",
    default=None,
    help="elastically resize the running cluster to N worker processes once "
    "it is up: the supervisor issues an epoch-fenced MEMBERSHIP_CHANGE — the "
    "workers quiesce at a commit boundary, reshard key ownership, hand off "
    "state through the checkpoint store, and admit joiners / drain leavers "
    "without stopping ingestion (requires persistence; interacts with "
    "--max-restarts: a crash mid-transition recovers by restart-all at "
    "whichever topology the membership manifest committed)",
)
@click.option(
    "--control-port",
    type=int,
    metavar="PORT",
    default=None,
    help="supervisor control endpoint: `echo 'scale N' | nc 127.0.0.1 PORT` "
    "resizes the live cluster; `echo status | nc ...` reports topology + "
    "autoscale-controller state (0 = pick a free port)",
)
@click.option(
    "--autoscale",
    is_flag=True,
    default=False,
    help="closed-loop autoscaler: the supervisor samples the workers' load "
    "signals (ingest rate, shed counters, barrier waits, brownout rung) and "
    "resizes the cluster through the elastic-membership path with no "
    "operator input — damped by hysteresis bands, per-direction cooldowns, "
    "refusal backoff, and a flap lock (PATHWAY_AUTOSCALE_* env knobs tune; "
    "PATHWAY_AUTOSCALE=on enables without this flag)",
)
@click.argument("program")
@click.argument("arguments", nargs=-1)
def spawn(threads, processes, first_port, record, record_path, max_restarts,
          restart_mode, scale, control_port, autoscale, program, arguments):
    env = os.environ.copy()
    if record:
        env["PATHWAY_REPLAY_STORAGE"] = record_path
        env["PATHWAY_SNAPSHOT_ACCESS"] = "record"
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    _spawn_program(
        threads=threads,
        processes=processes,
        first_port=first_port,
        program=program,
        arguments=arguments,
        env_base=env,
        max_restarts=max_restarts,
        restart_mode=restart_mode.lower(),
        scale=scale,
        control_port=control_port,
        autoscale=True if autoscale else None,
    )


@cli.command(context_settings=_SPAWN_SETTINGS)
@click.option("-t", "--threads", metavar="N", type=int, default=1, help="number of threads per process")
@click.option("-n", "--processes", metavar="N", type=int, default=1, help="number of processes")
@click.option("--first-port", type=int, metavar="PORT", default=10000, help="first port to use for communication")
@click.option("--record-path", type=str, default="record", help="directory in which recording is stored")
@click.option("--mode", type=click.Choice(["batch", "speedrun"], case_sensitive=False), help="mode of replaying data")
@click.option(
    "--continue",
    "continue_after_replay",
    is_flag=True,
    help="continue with realtime data from connectors after stored recording is replayed",
)
@click.argument("program")
@click.argument("arguments", nargs=-1)
def replay(threads, processes, first_port, record_path, mode, continue_after_replay, program, arguments):
    env = os.environ.copy()
    env["PATHWAY_REPLAY_STORAGE"] = record_path
    env["PATHWAY_SNAPSHOT_ACCESS"] = "replay"
    if mode:
        env["PATHWAY_PERSISTENCE_MODE"] = mode
    if continue_after_replay:
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    _spawn_program(
        threads=threads,
        processes=processes,
        first_port=first_port,
        program=program,
        arguments=arguments,
        env_base=env,
    )


@cli.command(context_settings=_SPAWN_SETTINGS)
@click.option(
    "--format",
    "fmt",
    type=click.Choice(["text", "json"], case_sensitive=False),
    default="text",
    show_default=True,
    help="diagnostic output format (json is stable for CI parsing)",
)
@click.option(
    "--strict",
    is_flag=True,
    help="treat warnings as errors for the exit code (exit 2 instead of 1)",
)
@click.option(
    "--runtime",
    is_flag=True,
    help="lint the runtime's own modules (PWA101-PWA104 concurrency passes "
    "plus PWA201-PWA205 resource-lifecycle/exception-contract passes: "
    "lock-order cycles, unbounded waits, unlocked shared writes, thread "
    "lifecycle, acquire/release pairing, typed-error swallowing, write-only "
    "state, finally masking, telemetry drift) instead of a user program; "
    "PROGRAM is not required",
)
@click.argument("program", required=False)
@click.argument("arguments", nargs=-1)
def analyze(fmt, strict, runtime, program, arguments):
    """Static graph lint: build PROGRAM's dataflow graph without running it and
    report PWA001-PWA005 diagnostics (or, with ``--runtime``, lint the
    runtime's own source: PWA101-PWA104 concurrency over the threaded modules
    plus PWA201-PWA205 resource-lifecycle/exception contracts).

    Exit-code contract (CI-gateable without parsing text): 0 = clean,
    1 = warnings only (2 with --strict), 2 = errors, 3 = PROGRAM itself crashed
    while building its graph (nothing was analyzed). The program executes up to
    its first ``pw.run`` call; the dataflow itself never starts."""
    import traceback

    from pathway_tpu.analysis import analyze_graph, capture_program_graph

    if runtime:
        if program is not None:
            # a typo'd `analyze --runtime my_graph.py` must not exit 0 with
            # the user's program silently never linted
            raise click.UsageError(
                "--runtime lints the runtime itself and takes no PROGRAM; "
                "run `analyze PROGRAM` separately for the graph lint"
            )
        from pathway_tpu.analysis import analyze_runtime_full

        report = analyze_runtime_full()
        report.emit_telemetry()
        if fmt.lower() == "json":
            click.echo(report.to_json())
        else:
            for diagnostic in report.diagnostics:
                click.echo(diagnostic.format())
            click.echo(report.summary_line())
        sys.exit(report.exit_code(strict=strict))
    if program is None:
        raise click.UsageError("PROGRAM is required unless --runtime is given")
    try:
        graph, persistence = capture_program_graph(program, tuple(arguments))
    except Exception:
        # a crash in the analyzed program must not collide with the 0/1/2
        # diagnostic contract (an uncaught ImportError would exit 1 — the
        # "warnings only, acceptable" code)
        traceback.print_exc()
        click.echo(f"analyze: {program} crashed before its graph was built", err=True)
        sys.exit(3)
    report = analyze_graph(graph, persistence=persistence)
    if fmt.lower() == "json":
        click.echo(report.to_json())
    else:
        for diagnostic in report.diagnostics:
            click.echo(diagnostic.format())
        click.echo(report.summary_line())
    sys.exit(report.exit_code(strict=strict))


@cli.command()
@click.option(
    "--trace-id",
    "trace_id",
    type=str,
    default=None,
    help="render only this trace (16-hex id); default: slowest roots first",
)
@click.option(
    "--limit",
    type=int,
    metavar="N",
    default=5,
    show_default=True,
    help="max traces to render when --trace-id is not given",
)
@click.argument(
    "directory", type=click.Path(exists=True, file_okay=False)
)
def trace(trace_id, limit, directory):
    """Merge per-rank trace files into causally-ordered trees.

    DIRECTORY is a supervise/flight dir holding ``trace-rank-N.jsonl``
    files (and, after a crash, ``flight-rank-N.json`` dumps whose trace
    rings are read as partial traces). Wall clocks are aligned to rank 0
    via the heartbeat-estimated offsets each rank recorded at flush, spans
    are joined across REST, encoder, mesh exchange, and replicas, and each
    rendered trace ends with its critical-path one-liner ("commit 4812:
    78% in rank 1 groupby; barrier held 41 ms by rank 3")."""
    import glob

    from pathway_tpu.engine.tracing import (
        critical_path,
        format_trace_tree,
        merge_trace_files,
    )

    paths = sorted(glob.glob(os.path.join(directory, "trace-rank-*.jsonl")))
    flights = sorted(glob.glob(os.path.join(directory, "flight-rank-*.json")))
    # replica processes flush into the replicas/ subdir of the supervise dir
    paths += sorted(
        glob.glob(os.path.join(directory, "replicas", "trace-rank-*.jsonl"))
    )
    flights += sorted(
        glob.glob(os.path.join(directory, "replicas", "flight-rank-*.json"))
    )
    if not paths and not flights:
        click.echo(
            f"trace: no trace-rank-*.jsonl or flight-rank-*.json under "
            f"{directory}",
            err=True,
        )
        sys.exit(1)
    merged = merge_trace_files(paths, flights)
    spans = merged["spans"]
    if not spans:
        click.echo(
            "trace: files merged but held no spans (sampling off? try "
            "PATHWAY_TRACE_SAMPLE=1.0)",
            err=True,
        )
        sys.exit(1)
    click.echo(
        f"{len(spans)} spans across ranks {merged['ranks']} "
        f"({len(paths)} trace files, {len(flights)} flight dumps)"
    )
    if trace_id is not None:
        trace_ids = [trace_id]
    else:
        # slowest roots first; traces that arrived only as flight-dump
        # partials (no root survived the crash) render after them
        roots = [s for s in spans if not s.get("parent_id")]
        roots.sort(key=lambda s: s.get("duration_s", 0.0), reverse=True)
        trace_ids = []
        for span in roots:
            if span["trace_id"] not in trace_ids:
                trace_ids.append(span["trace_id"])
        for span in spans:
            if span["trace_id"] not in trace_ids:
                trace_ids.append(span["trace_id"])
        trace_ids = trace_ids[: max(1, limit)]
    for tid in trace_ids:
        lines = format_trace_tree(merged, tid)
        if not lines:
            click.echo(f"trace {tid}: no spans")
            continue
        click.echo(f"trace {tid}:")
        for line in lines:
            click.echo(f"  {line}")
        result = critical_path(merged, tid)
        if result is not None:
            click.echo(f"  critical path: {result['line']}")


@cli.command()
def spawn_from_env():
    cli_spawn_arguments = os.environ.get("PATHWAY_SPAWN_ARGS")
    if cli_spawn_arguments is not None:
        args = ["spawn"] + cli_spawn_arguments.split(" ")
        os.execl(sys.executable, sys.executable, "-m", "pathway_tpu.cli", *args)
    else:
        logging.warning("PATHWAY_SPAWN_ARGS variable is unspecified, exiting...")


def main() -> NoReturn:
    cli.main()


if __name__ == "__main__":
    main()
