"""IVF-Flat approximate KNN over the HBM-resident store.

The reference serves approximate search through USearch's HNSW
(``src/external_integration/usearch_integration.rs:20``). A pointer-chasing graph
is the wrong shape for a TPU; the TPU-native equivalent of "sublinear candidate
selection + exact re-scoring" is IVF-Flat:

- **coarse quantizer**: k-means centroids live on device; probing is one small
  ``queries @ centroids.T`` matmul + ``top_k`` (MXU work, no host round-trip);
- **inverted lists**: a padded ``(n_clusters, bucket_width)`` int32 slot matrix on
  device — probing GATHERS candidate slots, then their vectors, then scores them
  exactly; the whole probe→gather→score→top-k chain is ONE jit'd kernel, so a
  tunneled chip pays a single round-trip per query batch;
- **training**: k-means iterations are themselves matmul + segment-sum on device;
  the index retrains when the corpus doubles, and assignments rebuild in one
  assign pass.

Recall is tunable via ``n_probe`` (``n_probe == n_clusters`` degenerates to exact
brute force). Search cost scales with ``n_probe * bucket_width`` instead of the
corpus size — the sublinearity HNSW buys the reference, bought the TPU way.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pathway_tpu.ops.knn import DenseKNNStore, pad_pow2


_KMEANS_CHUNK = 4096


@functools.partial(jax.jit, static_argnames=("n_iters",))
def _kmeans_kernel(vectors: jax.Array, valid: jax.Array, centroids: jax.Array, n_iters: int):
    """Lloyd iterations fully on device, memory-safe at large cluster counts:
    each iteration scans (chunk, d) blocks accumulating per-centroid sums and
    counts (one-hot matmul — MXU work, no scatter), so peak extra memory is
    O(chunk * C) instead of O(n * C). Callers pad ``vectors``/``valid`` to a
    multiple of ``_KMEANS_CHUNK`` with ``valid=False`` rows."""
    n, d = vectors.shape
    C = centroids.shape[0]
    vb = vectors.reshape(n // _KMEANS_CHUNK, _KMEANS_CHUNK, d)
    mb = valid.reshape(n // _KMEANS_CHUNK, _KMEANS_CHUNK)

    def step(cents, _):
        cn = jnp.sum(cents * cents, axis=1)
        cb = cents.astype(jnp.bfloat16)

        def acc(carry, blk):
            sums, counts = carry
            v, m = blk
            sim = 2.0 * (v.astype(jnp.bfloat16) @ cb.T).astype(jnp.float32) - cn[None, :]
            sim = jnp.where(m[:, None], sim, -jnp.inf)
            a = jnp.argmax(sim, axis=1)
            oh = jax.nn.one_hot(a, C, dtype=jnp.bfloat16) * m[:, None].astype(jnp.bfloat16)
            sums = sums + jnp.einsum(
                "nc,nd->cd", oh, v.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            counts = counts + jnp.sum(oh.astype(jnp.float32), axis=0)
            return (sums, counts), None

        init = (jnp.zeros((C, d), jnp.float32), jnp.zeros((C,), jnp.float32))
        (sums, counts), _ = lax.scan(acc, init, (vb, mb))
        new = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), cents
        )
        return new, None

    centroids, _ = lax.scan(step, centroids, None, length=n_iters)
    return centroids


@jax.jit
def _assign2_kernel(block: jax.Array, centroids: jax.Array) -> jax.Array:
    """Top-2 nearest centroids per row (primary + spill candidate), bf16
    affinity with f32 correction — near-ties may swap, which is harmless for
    coarse quantization (both clusters are close)."""
    cn = jnp.sum(centroids * centroids, axis=1)
    sim = (
        2.0 * (block.astype(jnp.bfloat16) @ centroids.astype(jnp.bfloat16).T).astype(jnp.float32)
        - cn[None, :]
    )
    _, idx = lax.top_k(sim, 2)
    return idx.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k", "n_probe", "metric"))
def _ivf_search_kernel(
    data: jax.Array,
    valid: jax.Array,
    norms: jax.Array,
    centroids: jax.Array,
    buckets: jax.Array,      # (C, B) slot ids, -1 padded
    queries: jax.Array,      # (q, d)
    k: int,
    n_probe: int,
    metric: str,
) -> Tuple[jax.Array, jax.Array]:
    """One fused pass: probe clusters -> gather candidate slots -> gather their
    vectors -> exact scores -> top-k. Single device round-trip per batch."""
    cn = jnp.sum(centroids * centroids, axis=1)
    qc = 2.0 * queries @ centroids.T - cn[None, :]  # L2 affinity to centroids
    _, probe = lax.top_k(qc, n_probe)  # (q, n_probe)
    cand = buckets[probe].reshape(queries.shape[0], -1)  # (q, n_probe*B)
    cand_ok = cand >= 0
    safe = jnp.maximum(cand, 0)
    vecs = data[safe]  # (q, m, d)
    scores = jnp.einsum(
        "qd,qmd->qm", queries.astype(vecs.dtype), vecs,
        preferred_element_type=jnp.float32,
    )
    # query norms in f32 regardless of storage dtype (bf16 self-products skew
    # l2 distances near ties)
    qf = queries.astype(jnp.float32)
    if metric == "l2sq":
        qn = jnp.sum(qf * qf, axis=1, keepdims=True)
        scores = -(qn + norms[safe] - 2.0 * scores)
    elif metric == "cos":
        qn = jnp.linalg.norm(qf, axis=1, keepdims=True)
        scores = scores / jnp.maximum(qn * jnp.sqrt(norms[safe]), 1e-30)
    scores = jnp.where(cand_ok & valid[safe], scores, -jnp.inf)
    k_eff = min(k, scores.shape[1])
    top_scores, top_pos = lax.top_k(scores, k_eff)
    top_slots = jnp.take_along_axis(cand, top_pos, axis=1)
    return top_scores, top_slots


class IvfKnnStore(DenseKNNStore):
    """Keyed IVF-Flat store: ``DenseKNNStore``'s storage management (staged
    scatters, capacity doubling, slot recycling) plus centroid assignments and
    device-resident inverted lists maintained through the flush/grow hooks."""

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        initial_capacity: int = 1024,
        n_clusters: int = 64,
        n_probe: int = 8,
        train_iters: int = 8,
        dtype: Any = jnp.float32,
    ):
        super().__init__(
            dim, metric=metric, initial_capacity=initial_capacity, dtype=dtype
        )
        self.n_clusters = max(2, n_clusters)
        self.n_probe = min(n_probe, self.n_clusters)
        # configured cluster count: retrains restart from it — n_clusters grows
        # via splits within ONE train, and must not compound across retrains
        # (the probed fraction would silently shrink every corpus doubling).
        # n_probe is NOT reset: it is the caller's tuning knob.
        self._n_clusters_base = self.n_clusters
        self.train_iters = train_iters
        self._centroids: jax.Array | None = None
        # host mirrors: primary assignment + spill candidate (2nd-nearest)
        self._assign = np.full(self.capacity, -1, dtype=np.int32)
        self._assign2 = np.full(self.capacity, -1, dtype=np.int32)
        self._buckets: jax.Array | None = None
        self._bucket_cap: int | None = None  # set by _split_oversized at train
        self._trained_at = 0  # corpus size at last (re)train
        self._host_cache: "tuple | None" = None  # f32 mirrors for the CPU path

    # -- DenseKNNStore hooks -------------------------------------------------

    def _after_grow(self, old_capacity: int, extra: int) -> None:
        pad = np.full(extra, -1, dtype=np.int32)
        self._assign = np.concatenate([self._assign, pad])
        self._assign2 = np.concatenate([self._assign2, pad.copy()])
        self._buckets = None  # geometry changed; rebuild lazily

    def _after_flush_adds(self, padded_slots: np.ndarray, vecs: jax.Array) -> None:
        # assign the new rows to centroids (chunked device passes) unless a
        # retrain will re-assign everything anyway
        if self._centroids is not None:
            top2 = self._assign_rows(vecs)
            self._assign[padded_slots] = top2[:, 0]
            self._assign2[padded_slots] = top2[:, 1]
        self._buckets = None
        self._host_cache = None

    def _after_flush_removals(self) -> None:
        self._buckets = None
        self._host_cache = None

    # training runs on a SAMPLE (faiss-style): k-means cost and its (chunk, C)
    # intermediates stay bounded however large the corpus grows
    _TRAIN_SAMPLE_PER_CLUSTER = 32

    def _assign_rows(self, rows: jax.Array) -> np.ndarray:
        """Top-2 centroid assignment for ``rows``, chunked so BOTH the
        (chunk, C) affinity and the (chunk, dim) block stay within a fixed
        memory budget at any cluster count / dimensionality."""
        chunk = max(1024, (1 << 28) // max(self.n_clusters, self.dim, 1))
        parts = []
        for start in range(0, rows.shape[0], chunk):
            parts.append(
                np.asarray(_assign2_kernel(rows[start : start + chunk], self._centroids))
            )
        return np.concatenate(parts) if parts else np.zeros((0, 2), dtype=np.int32)

    def _maybe_train(self) -> None:
        n = len(self.slot_of)
        if n == 0:
            return
        needs = self._centroids is None or n >= 2 * max(self._trained_at, 1)
        if not needs:
            return
        self.n_clusters = self._n_clusters_base
        rng = np.random.default_rng(0)
        live = np.fromiter(self.slot_of.values(), dtype=np.int64)
        seeds = rng.choice(live, size=self.n_clusters, replace=len(live) < self.n_clusters)
        # k-means accumulates means: always train in f32 even over a bf16 corpus
        init = self._data[jnp.asarray(seeds)].astype(jnp.float32)
        sample_cap = self.n_clusters * self._TRAIN_SAMPLE_PER_CLUSTER
        if len(live) > sample_cap:
            sample = np.sort(rng.choice(live, size=sample_cap, replace=False))
        else:
            # gather LIVE rows only: casting the whole preallocated buffer to
            # f32 would materialize capacity x dim (multi-GB for a large store)
            sample = np.sort(live)
        train_vecs = self._data[jnp.asarray(sample)].astype(jnp.float32)
        n_train = len(sample)
        pad = (-n_train) % _KMEANS_CHUNK
        if pad:
            train_vecs = jnp.concatenate(
                [train_vecs, jnp.zeros((pad, self.dim), jnp.float32)]
            )
        train_valid = jnp.arange(n_train + pad) < n_train
        self._centroids = _kmeans_kernel(train_vecs, train_valid, init, self.train_iters)
        # assign the FULL corpus to the trained centroids (chunked device passes)
        top2 = self._assign_rows(self._data)
        self._assign = top2[:, 0].copy()
        self._assign2 = top2[:, 1].copy()
        self._split_oversized(live)
        self._trained_at = n
        self._buckets = None

    @staticmethod
    def _cap_for(n_live: int, n_clusters: int) -> int:
        """Target per-cluster occupancy: ~1.5x the mean, rounded up to pow2 —
        the padded bucket width search pays for."""
        mean = max(1, n_live // max(n_clusters, 1))
        cap = 8
        while cap < (3 * mean + 1) // 2:
            cap *= 2
        return cap

    def _split_oversized(self, live: np.ndarray) -> None:
        """Bound the bucket width by SPLITTING oversized clusters instead of
        letting the padded (C, B) matrix track the most bloated one: each
        cluster past the cap gets a host-side 2-means over its members, the
        centroid is replaced by the pair, and siblings cross-link as each
        other's spill target. k-means over manifold-clustered corpora routinely
        leaves a handful of clusters at 3-4x the mean; without splits the whole
        inverted-list matrix doubles its width for them."""
        if not len(live):
            return
        cap = self._cap_for(len(live), self.n_clusters)
        self._bucket_cap = cap
        limit = 2 * self.n_clusters  # at most double the cluster count
        cents = np.array(self._centroids, dtype=np.float32)
        for _ in range(6):  # each round halves offenders; 6 covers 64x skew
            al = self._assign[live]
            counts = np.bincount(al, minlength=self.n_clusters)
            over = np.where(counts > cap)[0]
            if not len(over) or self.n_clusters + len(over) > limit:
                break
            order = np.argsort(al, kind="stable")
            starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
            new_rows: List[np.ndarray] = []
            for c in over:
                mem = live[order[starts[c] : starts[c] + counts[c]]]
                vecs = np.asarray(
                    self._data[jnp.asarray(mem)].astype(jnp.float32)
                )
                # 2-means, host-side (members are a few thousand rows at most)
                c0, c1 = vecs[0], vecs[len(vecs) // 2]
                for _it in range(6):
                    d0 = np.sum((vecs - c0) ** 2, axis=1)
                    d1 = np.sum((vecs - c1) ** 2, axis=1)
                    g1 = d1 < d0
                    if g1.all() or (~g1).all():
                        break
                    c0 = vecs[~g1].mean(axis=0)
                    c1 = vecs[g1].mean(axis=0)
                new_id = self.n_clusters
                self.n_clusters += 1
                self._assign[mem[g1]] = new_id
                self._assign2[mem[g1]] = c
                self._assign2[mem[~g1]] = new_id
                cents[c] = c0
                new_rows.append(c1[None, :])
            if new_rows:
                cents = np.concatenate([cents] + new_rows)
        self._centroids = jnp.asarray(cents)
        self.n_probe = min(self.n_probe, self.n_clusters)

    def _rebuild_buckets(self) -> None:
        """Pack live slots into the padded (C, B) inverted-list matrix — one
        vectorized sort + fancy-index pass (this reruns after every mutation
        batch, so it must not walk the corpus in Python).

        The padded width B is what search pays for (candidates per probe =
        n_probe * B), so oversized clusters are rebalanced first: overflow
        members past ~1.5x the mean spill to their 2nd-nearest centroid. A
        spilled point sits in a cluster whose centroid is nearly as close, so
        probes still find it; the win is a bounded B instead of B tracking the
        most bloated cluster."""
        live = np.fromiter(self.slot_of.values(), dtype=np.int64)
        counts = np.zeros(self.n_clusters, dtype=np.int64)
        a = np.zeros(0, dtype=np.int32)
        if len(live):
            a = self._assign[live].copy()
            a2 = self._assign2[live]
            counts = np.bincount(a, minlength=self.n_clusters)
            cap = self._bucket_cap or self._cap_for(len(live), self.n_clusters)
            over = np.where(counts > cap)[0]
            if len(over):
                order = np.argsort(a, kind="stable")
                starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
                for c in over:
                    tail = order[starts[c] + cap : starts[c] + counts[c]]
                    mv = tail[a2[tail] != c]
                    a[mv] = a2[mv]
                counts = np.bincount(a, minlength=self.n_clusters)
        width = max(8, int(counts.max()) if len(live) else 8)
        bucket_width = 8
        while bucket_width < width:
            bucket_width *= 2
        buckets = np.full((self.n_clusters, bucket_width), -1, dtype=np.int32)
        if len(live):
            order = np.argsort(a, kind="stable")
            sorted_a = a[order]
            sorted_slots = live[order]
            starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
            pos = np.arange(len(live)) - starts[sorted_a]
            buckets[sorted_a, pos] = sorted_slots
        self._buckets = jnp.asarray(buckets)

    def _search_numpy(
        self, queries: np.ndarray, k_eff: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Host BLAS path for CPU backends: XLA's gather on CPU is orders of
        magnitude slower than numpy fancy-indexing + batched matmul, and the
        algorithm (probe -> gather -> exact score -> top-k) is identical."""
        if self._host_cache is None:
            self._host_cache = (
                np.asarray(self._data.astype(jnp.float32)),
                np.asarray(self._valid),
                np.asarray(self._norms),
            )
        data, valid, norms = self._host_cache
        cents = np.asarray(self._centroids)
        buckets = np.asarray(self._buckets)
        cn = np.sum(cents * cents, axis=1)
        out_s: List[np.ndarray] = []
        out_i: List[np.ndarray] = []
        cand_per_q = self.n_probe * buckets.shape[1]
        q_chunk = max(1, (1 << 27) // max(cand_per_q * self.dim, 1))
        for start in range(0, queries.shape[0], q_chunk):
            q = queries[start : start + q_chunk]
            aff = 2.0 * q @ cents.T - cn[None, :]
            probe = np.argpartition(aff, -self.n_probe, axis=1)[:, -self.n_probe :]
            cand = buckets[probe].reshape(q.shape[0], -1)
            ok = cand >= 0
            safe = np.maximum(cand, 0)
            vecs = data[safe]  # (q, m, d)
            scores = np.matmul(vecs, q[:, :, None])[:, :, 0]
            if self.metric == "l2sq":
                qn = np.sum(q * q, axis=1, keepdims=True)
                scores = -(qn + norms[safe] - 2.0 * scores)
            elif self.metric == "cos":
                qn = np.linalg.norm(q, axis=1, keepdims=True)
                scores = scores / np.maximum(qn * np.sqrt(norms[safe]), 1e-30)
            scores = np.where(ok & valid[safe], scores, -np.inf)
            kk = min(k_eff, scores.shape[1])
            part = np.argpartition(scores, -kk, axis=1)[:, -kk:]
            psc = np.take_along_axis(scores, part, axis=1)
            order = np.argsort(-psc, axis=1)
            top_pos = np.take_along_axis(part, order, axis=1)
            out_s.append(np.take_along_axis(scores, top_pos, axis=1))
            out_i.append(np.take_along_axis(cand, top_pos, axis=1).astype(np.int64))
        return np.concatenate(out_s), np.concatenate(out_i), None  # type: ignore[return-value]

    def search_batch(self, queries: Any, k: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        self._flush()
        self._maybe_train()
        if self._centroids is None:
            n = int(np.asarray(queries).shape[0]) if not isinstance(queries, jax.Array) else queries.shape[0]
            return (
                np.full((n, max(1, k)), -np.inf, dtype=np.float32),
                np.full((n, max(1, k)), -1, dtype=np.int64),
                np.zeros((n, max(1, k)), dtype=bool),
            )
        if self._buckets is None:
            self._rebuild_buckets()
        k_eff = max(1, k)
        if jax.default_backend() == "cpu":
            q_np = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
            scores, idx, _ = self._search_numpy(q_np, k_eff)
            valid = np.isfinite(scores)
            if scores.shape[1] < k_eff:
                pad = k_eff - scores.shape[1]
                scores = np.pad(scores, ((0, 0), (0, pad)), constant_values=-np.inf)
                idx = np.pad(idx, ((0, 0), (0, pad)), constant_values=-1)
                valid = np.pad(valid, ((0, 0), (0, pad)), constant_values=False)
            return scores, idx, valid
        if isinstance(queries, jax.Array):
            if queries.dtype != jnp.float32:
                queries = queries.astype(jnp.float32)
            if queries.ndim != 2 or queries.shape[-1] != self.dim:
                queries = queries.reshape(-1, self.dim)
        else:
            queries = jnp.asarray(
                np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
            )
        # chunk the query batch so the (chunk, n_probe * bucket_width, dim)
        # candidate gather stays within a fixed HBM budget
        cand_per_q = self.n_probe * int(self._buckets.shape[1])
        budget_floats = 1 << 28  # ~1 GB of f32 candidate vectors
        q_chunk = max(1, budget_floats // max(cand_per_q * self.dim, 1))
        parts = []
        for start in range(0, queries.shape[0], q_chunk):
            parts.append(
                _ivf_search_kernel(
                    self._data,
                    self._valid,
                    self._norms,
                    self._centroids,
                    self._buckets,
                    queries[start : start + q_chunk],
                    k_eff,
                    self.n_probe,
                    self.metric,
                )
            )
        top_scores = jnp.concatenate([p[0] for p in parts])
        top_slots = jnp.concatenate([p[1] for p in parts])
        scores, idx = jax.device_get((top_scores, top_slots))
        valid = np.isfinite(scores)
        if scores.shape[1] < k_eff:  # fewer candidates than k: pad result shape
            pad = k_eff - scores.shape[1]
            scores = np.pad(scores, ((0, 0), (0, pad)), constant_values=-np.inf)
            idx = np.pad(idx, ((0, 0), (0, pad)), constant_values=-1)
            valid = np.pad(valid, ((0, 0), (0, pad)), constant_values=False)
        return scores, idx, valid
