"""IVF-Flat approximate KNN over the HBM-resident store.

The reference serves approximate search through USearch's HNSW
(``src/external_integration/usearch_integration.rs:20``). A pointer-chasing graph
is the wrong shape for a TPU; the TPU-native equivalent of "sublinear candidate
selection + exact re-scoring" is IVF-Flat:

- **coarse quantizer**: k-means centroids live on device; probing is one small
  ``queries @ centroids.T`` matmul + ``top_k`` (MXU work, no host round-trip);
- **inverted lists**: a padded ``(n_clusters, bucket_width)`` int32 slot matrix on
  device — probing GATHERS candidate slots, then their vectors, then scores them
  exactly; the whole probe→gather→score→top-k chain is ONE jit'd kernel, so a
  tunneled chip pays a single round-trip per query batch;
- **training**: k-means iterations are themselves matmul + segment-sum on device;
  the index retrains when the corpus doubles, and assignments rebuild in one
  assign pass.

Recall is tunable via ``n_probe`` (``n_probe == n_clusters`` degenerates to exact
brute force). Search cost scales with ``n_probe * bucket_width`` instead of the
corpus size — the sublinearity HNSW buys the reference, bought the TPU way.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pathway_tpu.ops.knn import DenseKNNStore, pad_pow2


@functools.partial(jax.jit, static_argnames=("n_iters",))
def _kmeans_kernel(vectors: jax.Array, valid: jax.Array, centroids: jax.Array, n_iters: int):
    """Lloyd iterations fully on device: assign (matmul + argmax) then update
    (segment-sum via one-hot matmul — MXU-friendly, no scatter)."""

    def step(carry, _):
        cents = carry
        # assign: nearest centroid by L2 == argmax of (2 x.c - ||c||^2)
        cn = jnp.sum(cents * cents, axis=1)
        sim = 2.0 * vectors @ cents.T - cn[None, :]
        sim = jnp.where(valid[:, None], sim, -jnp.inf)
        assign = jnp.argmax(sim, axis=1)
        onehot = jax.nn.one_hot(assign, cents.shape[0], dtype=vectors.dtype)
        onehot = onehot * valid[:, None]
        sums = onehot.T @ vectors
        counts = jnp.sum(onehot, axis=0)
        new = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), cents
        )
        return new, None

    centroids, _ = lax.scan(step, centroids, None, length=n_iters)
    cn = jnp.sum(centroids * centroids, axis=1)
    sim = 2.0 * vectors @ centroids.T - cn[None, :]
    assign = jnp.argmax(sim, axis=1)
    return centroids, assign


@functools.partial(jax.jit, static_argnames=("k", "n_probe", "metric"))
def _ivf_search_kernel(
    data: jax.Array,
    valid: jax.Array,
    norms: jax.Array,
    centroids: jax.Array,
    buckets: jax.Array,      # (C, B) slot ids, -1 padded
    queries: jax.Array,      # (q, d)
    k: int,
    n_probe: int,
    metric: str,
) -> Tuple[jax.Array, jax.Array]:
    """One fused pass: probe clusters -> gather candidate slots -> gather their
    vectors -> exact scores -> top-k. Single device round-trip per batch."""
    cn = jnp.sum(centroids * centroids, axis=1)
    qc = 2.0 * queries @ centroids.T - cn[None, :]  # L2 affinity to centroids
    _, probe = lax.top_k(qc, n_probe)  # (q, n_probe)
    cand = buckets[probe].reshape(queries.shape[0], -1)  # (q, n_probe*B)
    cand_ok = cand >= 0
    safe = jnp.maximum(cand, 0)
    vecs = data[safe]  # (q, m, d)
    scores = jnp.einsum(
        "qd,qmd->qm", queries.astype(vecs.dtype), vecs,
        preferred_element_type=jnp.float32,
    )
    # query norms in f32 regardless of storage dtype (bf16 self-products skew
    # l2 distances near ties)
    qf = queries.astype(jnp.float32)
    if metric == "l2sq":
        qn = jnp.sum(qf * qf, axis=1, keepdims=True)
        scores = -(qn + norms[safe] - 2.0 * scores)
    elif metric == "cos":
        qn = jnp.linalg.norm(qf, axis=1, keepdims=True)
        scores = scores / jnp.maximum(qn * jnp.sqrt(norms[safe]), 1e-30)
    scores = jnp.where(cand_ok & valid[safe], scores, -jnp.inf)
    k_eff = min(k, scores.shape[1])
    top_scores, top_pos = lax.top_k(scores, k_eff)
    top_slots = jnp.take_along_axis(cand, top_pos, axis=1)
    return top_scores, top_slots


class IvfKnnStore(DenseKNNStore):
    """Keyed IVF-Flat store: ``DenseKNNStore``'s storage management (staged
    scatters, capacity doubling, slot recycling) plus centroid assignments and
    device-resident inverted lists maintained through the flush/grow hooks."""

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        initial_capacity: int = 1024,
        n_clusters: int = 64,
        n_probe: int = 8,
        train_iters: int = 8,
        dtype: Any = jnp.float32,
    ):
        super().__init__(
            dim, metric=metric, initial_capacity=initial_capacity, dtype=dtype
        )
        self.n_clusters = n_clusters
        self.n_probe = min(n_probe, n_clusters)
        self.train_iters = train_iters
        self._centroids: jax.Array | None = None
        self._assign = np.full(self.capacity, -1, dtype=np.int32)  # host mirror
        self._buckets: jax.Array | None = None
        self._trained_at = 0  # corpus size at last (re)train

    # -- DenseKNNStore hooks -------------------------------------------------

    def _after_grow(self, old_capacity: int, extra: int) -> None:
        self._assign = np.concatenate(
            [self._assign, np.full(extra, -1, dtype=np.int32)]
        )
        self._buckets = None  # geometry changed; rebuild lazily

    def _after_flush_adds(self, padded_slots: np.ndarray, vecs: jax.Array) -> None:
        # assign the new rows to centroids (one small device pass) unless a
        # retrain will re-assign everything anyway
        if self._centroids is not None:
            cn = jnp.sum(self._centroids * self._centroids, axis=1)
            sim = 2.0 * vecs @ self._centroids.T - cn[None, :]
            self._assign[padded_slots] = np.asarray(
                jnp.argmax(sim, axis=1), dtype=np.int32
            )
        self._buckets = None

    def _after_flush_removals(self) -> None:
        self._buckets = None

    # training runs on a SAMPLE (faiss-style): k-means cost and its (n, C)
    # intermediates stay bounded however large the corpus grows
    _TRAIN_SAMPLE_PER_CLUSTER = 64

    def _maybe_train(self) -> None:
        n = len(self.slot_of)
        if n == 0:
            return
        needs = self._centroids is None or n >= 2 * max(self._trained_at, 1)
        if not needs:
            return
        rng = np.random.default_rng(0)
        live = np.fromiter(self.slot_of.values(), dtype=np.int64)
        seeds = rng.choice(live, size=self.n_clusters, replace=len(live) < self.n_clusters)
        # k-means accumulates means: always train in f32 even over a bf16 corpus
        init = self._data[jnp.asarray(seeds)].astype(jnp.float32)
        sample_cap = self.n_clusters * self._TRAIN_SAMPLE_PER_CLUSTER
        if len(live) > sample_cap:
            sample = rng.choice(live, size=sample_cap, replace=False)
            train_vecs = self._data[jnp.asarray(np.sort(sample))].astype(jnp.float32)
            train_valid = jnp.ones((sample_cap,), dtype=bool)
        else:
            # gather LIVE rows only: casting the whole preallocated buffer to
            # f32 would materialize capacity x dim (multi-GB for a large store)
            train_vecs = self._data[jnp.asarray(np.sort(live))].astype(jnp.float32)
            train_valid = jnp.ones((len(live),), dtype=bool)
        centroids, _ = _kmeans_kernel(
            train_vecs, train_valid, init, self.train_iters
        )
        self._centroids = centroids
        # assign the FULL corpus to the trained centroids, chunked so the
        # (chunk, C) affinity stays small
        assign = np.full(self.capacity, -1, dtype=np.int32)
        cn = jnp.sum(centroids * centroids, axis=1)
        chunk = max(1, (1 << 22) // max(self.n_clusters, 1))
        for start in range(0, self.capacity, chunk):
            block = self._data[start : start + chunk]
            sim = 2.0 * block @ centroids.T - cn[None, :]
            assign[start : start + chunk] = np.asarray(
                jnp.argmax(sim, axis=1), dtype=np.int32
            )
        self._assign = assign
        self._trained_at = n
        self._buckets = None

    def _rebuild_buckets(self) -> None:
        """Pack live slots into the padded (C, B) inverted-list matrix — one
        vectorized sort + fancy-index pass (this reruns after every mutation
        batch, so it must not walk the corpus in Python)."""
        live = np.fromiter(self.slot_of.values(), dtype=np.int64)
        counts = np.zeros(self.n_clusters, dtype=np.int64)
        if len(live):
            a = self._assign[live]
            counts = np.bincount(a, minlength=self.n_clusters)
        width = max(8, int(counts.max()) if len(live) else 8)
        bucket_width = 8
        while bucket_width < width:
            bucket_width *= 2
        buckets = np.full((self.n_clusters, bucket_width), -1, dtype=np.int32)
        if len(live):
            order = np.argsort(a, kind="stable")
            sorted_a = a[order]
            sorted_slots = live[order]
            starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
            pos = np.arange(len(live)) - starts[sorted_a]
            buckets[sorted_a, pos] = sorted_slots
        self._buckets = jnp.asarray(buckets)

    def search_batch(self, queries: Any, k: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        self._flush()
        self._maybe_train()
        if self._centroids is None:
            n = int(np.asarray(queries).shape[0]) if not isinstance(queries, jax.Array) else queries.shape[0]
            return (
                np.full((n, max(1, k)), -np.inf, dtype=np.float32),
                np.full((n, max(1, k)), -1, dtype=np.int64),
                np.zeros((n, max(1, k)), dtype=bool),
            )
        if self._buckets is None:
            self._rebuild_buckets()
        if isinstance(queries, jax.Array):
            if queries.dtype != jnp.float32:
                queries = queries.astype(jnp.float32)
            if queries.ndim != 2 or queries.shape[-1] != self.dim:
                queries = queries.reshape(-1, self.dim)
        else:
            queries = jnp.asarray(
                np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
            )
        k_eff = max(1, k)
        # chunk the query batch so the (chunk, n_probe * bucket_width, dim)
        # candidate gather stays within a fixed HBM budget
        cand_per_q = self.n_probe * int(self._buckets.shape[1])
        budget_floats = 1 << 28  # ~1 GB of f32 candidate vectors
        q_chunk = max(1, budget_floats // max(cand_per_q * self.dim, 1))
        parts = []
        for start in range(0, queries.shape[0], q_chunk):
            parts.append(
                _ivf_search_kernel(
                    self._data,
                    self._valid,
                    self._norms,
                    self._centroids,
                    self._buckets,
                    queries[start : start + q_chunk],
                    k_eff,
                    self.n_probe,
                    self.metric,
                )
            )
        top_scores = jnp.concatenate([p[0] for p in parts])
        top_slots = jnp.concatenate([p[1] for p in parts])
        scores, idx = jax.device_get((top_scores, top_slots))
        valid = np.isfinite(scores)
        if scores.shape[1] < k_eff:  # fewer candidates than k: pad result shape
            pad = k_eff - scores.shape[1]
            scores = np.pad(scores, ((0, 0), (0, pad)), constant_values=-np.inf)
            idx = np.pad(idx, ((0, 0), (0, pad)), constant_values=-1)
            valid = np.pad(valid, ((0, 0), (0, pad)), constant_values=False)
        return scores, idx, valid
