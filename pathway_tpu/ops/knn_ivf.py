"""IVF-Flat approximate KNN over the HBM-resident store.

The reference serves approximate search through USearch's HNSW
(``src/external_integration/usearch_integration.rs:20``). A pointer-chasing graph
is the wrong shape for a TPU; the TPU-native equivalent of "sublinear candidate
selection + exact re-scoring" is IVF-Flat:

- **coarse quantizer**: k-means centroids live on device; probing is one small
  ``queries @ centroids.T`` matmul + ``top_k`` (MXU work, no host round-trip);
- **inverted lists**: a CSR layout over live slots (see below); probing selects
  fixed-size candidate *pages*, streams their vectors, scores them exactly, and
  merges top-k — the whole probe→gather→score→top-k chain is ONE jit'd kernel,
  so a tunneled chip pays a single round-trip per query batch;
- **training**: k-means iterations are themselves matmul + segment-sum on device;
  the index retrains when the corpus doubles, and assignments rebuild in one
  assign pass.

Recall is tunable via ``n_probe`` (``n_probe == n_clusters`` degenerates to exact
brute force). Search cost scales with the probed fraction of the corpus instead
of the corpus size — the sublinearity HNSW buys the reference, bought the TPU way.

CSR bucket layout
-----------------
Inverted lists are stored as a host-side CSR pair — ``_csr_offsets`` (C+1,) and
``_csr_rows`` (n_live,), live slot ids sorted cluster-major — plus a *paged*
device mirror: each cluster's member list is padded up to a multiple of
``PAGE`` (128) rows and packed into a contiguous ``(n_pages * PAGE,)`` int32
``_page_rows`` array (-1 pads), with ``_first_page``/``_n_pages`` per cluster.
The page count is padded to a power of two (the last page is an all-pad
sentinel), so the packed geometry only changes shape when the corpus doubles —
every other mutation batch rebuilds *contents*, not shapes, and the query
kernel's jit cache keeps hitting. Oversized clusters are split at train time
and spill overflow members to their second-nearest centroid at rebuild time,
so the per-cluster page budget (``_max_pages``) tracks ~1.5x the mean
occupancy, not the most bloated cluster.

Shape-bucketing policy
----------------------
Query batches and ``k`` are padded to the next power of two (floor 8 queries)
before entering the jit'd query kernel, and results are sliced back. Together
with the pow2-padded page count this bounds the number of XLA compilations for
a store at steady geometry to O(log(max batch) * log(max k)) regardless of how
ragged the serving traffic is. ``search_shape_buckets`` records the distinct
(q_pow2, k_pow2) buckets a store has seen; ``pathway_tpu.ops.knn.
kernel_cache_sizes()`` exposes the actual jit cache sizes for regression tests
and the bench recompile counter.

Pallas / XLA fallback contract
------------------------------
The candidate scoring stage — the bandwidth-bound heart of the query — has two
implementations selected by the ``impl`` static of ``_ivf_query_fused``:

- ``"pallas"``: a ``pl.pallas_call`` TPU kernel (ragged-paged-attention shape:
  ``arxiv 2604.15464``). Per-query page indices are scalar-prefetched into
  SMEM; the grid walks (query, page-slot) pairs and each step DMAs ONE
  ``(PAGE, dim)`` candidate page HBM→VMEM, dots it against the query row, and
  writes a ``(1, PAGE)`` score tile. Candidate vectors are never materialized
  as a ``(q, n_probe * bucket_width, dim)`` gather — they stream through VMEM
  page by page. ``"pallas_interpret"`` runs the same kernel through the Pallas
  interpreter on any backend (used by the parity tests).
- ``"xla"``: a composite fallback — ``lax.scan`` over page slots, gathering one
  ``(q, PAGE, dim)`` tile per step. Bit-for-bit the same scoring math (f32
  accumulation, identical metric epilogue, identical -inf masking), so the two
  implementations are interchangeable; tests assert parity.

Both paths bound peak memory to one candidate tile instead of the full
candidate volume. On CPU backends ``search_batch`` instead takes a numpy path
that walks the SAME CSR cluster-major (one BLAS GEMM per probed cluster), which
beats XLA's CPU gather by orders of magnitude while computing the identical
probe → exact-score → top-k result.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pathway_tpu.ops.knn import DenseKNNStore, next_pow2, pad_queries_pow2, topk_rows
from pathway_tpu.ops.knn_quant import host_metric_scores

_KMEANS_CHUNK = 4096

# rows per packed candidate page: one MXU-width tile of candidates, and the
# granularity of the HBM→VMEM stream in both scoring implementations
PAGE = 128


@functools.partial(jax.jit, static_argnames=("n_iters",))
def _kmeans_kernel(vectors: jax.Array, valid: jax.Array, centroids: jax.Array, n_iters: int):
    """Lloyd iterations fully on device, memory-safe at large cluster counts:
    each iteration scans (chunk, d) blocks accumulating per-centroid sums and
    counts (one-hot matmul — MXU work, no scatter), so peak extra memory is
    O(chunk * C) instead of O(n * C). Callers pad ``vectors``/``valid`` to a
    multiple of ``_KMEANS_CHUNK`` with ``valid=False`` rows."""
    n, d = vectors.shape
    C = centroids.shape[0]
    vb = vectors.reshape(n // _KMEANS_CHUNK, _KMEANS_CHUNK, d)
    mb = valid.reshape(n // _KMEANS_CHUNK, _KMEANS_CHUNK)

    def step(cents, _):
        cn = jnp.sum(cents * cents, axis=1)
        cb = cents.astype(jnp.bfloat16)

        def acc(carry, blk):
            sums, counts = carry
            v, m = blk
            sim = 2.0 * (v.astype(jnp.bfloat16) @ cb.T).astype(jnp.float32) - cn[None, :]
            sim = jnp.where(m[:, None], sim, -jnp.inf)
            a = jnp.argmax(sim, axis=1)
            oh = jax.nn.one_hot(a, C, dtype=jnp.bfloat16) * m[:, None].astype(jnp.bfloat16)
            sums = sums + jnp.einsum(
                "nc,nd->cd", oh, v.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            counts = counts + jnp.sum(oh.astype(jnp.float32), axis=0)
            return (sums, counts), None

        init = (jnp.zeros((C, d), jnp.float32), jnp.zeros((C,), jnp.float32))
        (sums, counts), _ = lax.scan(acc, init, (vb, mb))
        new = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), cents
        )
        return new, None

    centroids, _ = lax.scan(step, centroids, None, length=n_iters)
    return centroids


@jax.jit
def _assign2_kernel(block: jax.Array, centroids: jax.Array) -> jax.Array:
    """Top-2 nearest centroids per row (primary + spill candidate), bf16
    affinity with f32 correction — near-ties may swap, which is harmless for
    coarse quantization (both clusters are close)."""
    cn = jnp.sum(centroids * centroids, axis=1)
    sim = (
        2.0 * (block.astype(jnp.bfloat16) @ centroids.astype(jnp.bfloat16).T).astype(jnp.float32)
        - cn[None, :]
    )
    _, idx = lax.top_k(sim, 2)
    return idx.astype(jnp.int32)


@jax.jit
def _pack_pages_kernel(
    data: jax.Array, norms: jax.Array, valid: jax.Array, page_rows: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Materialize the paged device mirror of the CSR: candidate vectors packed
    cluster-major into (n_pages * PAGE, d), their norms and an additive -inf
    mask reshaped (n_pages, PAGE) so the scoring stage addresses them by page
    id. One fused gather per index rebuild (amortized over mutation batches)."""
    safe = jnp.maximum(page_rows, 0)
    packed = data[safe]
    pn = norms[safe].reshape(-1, PAGE)
    ok = (page_rows >= 0) & valid[safe]
    pm = jnp.where(ok, 0.0, -jnp.inf).astype(jnp.float32).reshape(-1, PAGE)
    return packed, pn, pm


def _page_scores_epilogue(dot, pn, pm, qn, metric: str):
    """Shared metric epilogue — MUST stay identical between the Pallas kernel
    and the XLA composite (the parity tests pin this)."""
    if metric == "l2sq":
        s = 2.0 * dot - pn - qn
    elif metric == "cos":
        s = dot / jnp.maximum(jnp.sqrt(pn * qn), 1e-30)
    else:  # ip
        s = dot
    return s + pm


def _score_pages_xla(packed, pn, pm, queries, page_ids, metric: str) -> jax.Array:
    """Composite fallback: scan page slots, gathering ONE (q, PAGE, d) tile per
    step — peak memory is a single candidate tile, never the full
    (q, n_probe * bucket_width, d) volume."""
    qf = queries.astype(jnp.float32)
    qn = jnp.sum(qf * qf, axis=1)[:, None]  # (q, 1)
    qc = queries.astype(packed.dtype)

    def step(_, pid):  # pid: (q,) page id for this slot
        rows = pid[:, None] * PAGE + jnp.arange(PAGE)[None, :]
        vecs = packed[rows]  # (q, PAGE, d) — one streamed tile
        dot = jnp.einsum(
            "qd,qpd->qp", qc, vecs, preferred_element_type=jnp.float32
        )
        return 0, _page_scores_epilogue(dot, pn[pid], pm[pid], qn, metric)

    _, stacked = lax.scan(step, 0, page_ids.T)  # (P, q, PAGE)
    q = queries.shape[0]
    return stacked.transpose(1, 0, 2).reshape(q, -1)


def _score_pages_pallas(
    packed, pn, pm, queries, page_ids, metric: str, interpret: bool
) -> jax.Array:
    """Fused probe→gather→score streaming kernel (TPU): per-query page ids are
    scalar-prefetched, the grid walks (query, page-slot) pairs, and each step
    DMAs one (PAGE, d) candidate page into VMEM via the prefetched index map —
    the ragged-gather-by-pages shape of Ragged Paged Attention."""
    q, d = queries.shape
    n_slots = page_ids.shape[1]

    def kernel(ids_ref, q_ref, data_ref, pn_ref, pm_ref, out_ref):
        qv = q_ref[...].astype(jnp.float32)  # (1, d)
        page = data_ref[...].astype(jnp.float32)  # (PAGE, d)
        dot = lax.dot_general(
            qv, page, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (1, PAGE)
        qn = jnp.sum(qv * qv)
        out_ref[...] = _page_scores_epilogue(
            dot, pn_ref[...], pm_ref[...], qn, metric
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(q, n_slots),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, j, ids: (i, 0)),
            pl.BlockSpec((PAGE, d), lambda i, j, ids: (ids[i, j], 0)),
            pl.BlockSpec((1, PAGE), lambda i, j, ids: (ids[i, j], 0)),
            pl.BlockSpec((1, PAGE), lambda i, j, ids: (ids[i, j], 0)),
        ],
        out_specs=pl.BlockSpec((1, PAGE), lambda i, j, ids: (i, j)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((q, n_slots * PAGE), jnp.float32),
        interpret=interpret,
    )(page_ids, queries.astype(jnp.float32), packed, pn, pm)


@functools.partial(
    jax.jit, static_argnames=("k", "n_probe", "max_pages", "metric", "impl")
)
def _ivf_query_fused(
    centroids: jax.Array,   # (C, d) f32
    first_page: jax.Array,  # (C,) int32
    n_pages: jax.Array,     # (C,) int32
    packed: jax.Array,      # (n_pages_pow2 * PAGE, d) corpus dtype
    pn: jax.Array,          # (n_pages_pow2, PAGE) f32 row norms
    pm: jax.Array,          # (n_pages_pow2, PAGE) f32 additive mask (0 / -inf)
    packed_rows: jax.Array, # (n_pages_pow2 * PAGE,) int32 packed pos -> slot
    queries: jax.Array,     # (q, d) f32
    k: int,
    n_probe: int,
    max_pages: int,
    metric: str,
    impl: str,
) -> Tuple[jax.Array, jax.Array]:
    """ONE fused pass: probe clusters -> expand probed clusters to candidate
    pages -> stream-score the pages -> top-k -> map positions back to slots.
    Single device round-trip per query batch."""
    cn = jnp.sum(centroids * centroids, axis=1)
    aff = 2.0 * queries @ centroids.T - cn[None, :]  # L2 affinity to centroids
    _, probe = lax.top_k(aff, n_probe)  # (q, n_probe)
    base = first_page[probe]  # (q, n_probe)
    cnt = n_pages[probe]
    span = jnp.arange(max_pages, dtype=jnp.int32)
    ids = base[..., None] + span[None, None, :]  # (q, n_probe, max_pages)
    sentinel = pn.shape[0] - 1  # last page is all-pad by construction
    page_ids = jnp.where(span[None, None, :] < cnt[..., None], ids, sentinel)
    page_ids = page_ids.reshape(queries.shape[0], -1).astype(jnp.int32)
    if impl == "xla":
        scores = _score_pages_xla(packed, pn, pm, queries, page_ids, metric)
    else:
        scores = _score_pages_pallas(
            packed, pn, pm, queries, page_ids, metric,
            interpret=(impl == "pallas_interpret"),
        )
    k_eff = min(k, scores.shape[1])
    top_scores, pos = lax.top_k(scores, k_eff)
    pg = jnp.take_along_axis(page_ids, pos // PAGE, axis=1)
    top_slots = packed_rows[pg * PAGE + pos % PAGE]
    top_slots = jnp.where(jnp.isfinite(top_scores), top_slots, -1)
    return top_scores, top_slots


class IvfKnnStore(DenseKNNStore):
    """Keyed IVF-Flat store: ``DenseKNNStore``'s storage management (staged
    scatters, capacity doubling, slot recycling) plus centroid assignments and
    the CSR/paged inverted lists maintained through the flush/grow hooks."""

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        initial_capacity: int = 1024,
        n_clusters: int = 64,
        n_probe: int = 8,
        train_iters: int = 8,
        dtype: Any = jnp.float32,
        device: Any = None,
    ):
        super().__init__(
            dim, metric=metric, initial_capacity=initial_capacity, dtype=dtype,
            device=device,
        )
        self.n_clusters = max(2, n_clusters)
        self.n_probe = min(n_probe, self.n_clusters)
        # configured cluster count: retrains restart from it — n_clusters grows
        # via splits within ONE train, and must not compound across retrains
        # (the probed fraction would silently shrink every corpus doubling).
        # n_probe is NOT reset: it is the caller's tuning knob.
        self._n_clusters_base = self.n_clusters
        self.train_iters = train_iters
        self._centroids: jax.Array | None = None
        # host mirrors: primary assignment + spill candidate (2nd-nearest)
        self._assign = np.full(self.capacity, -1, dtype=np.int32)
        self._assign2 = np.full(self.capacity, -1, dtype=np.int32)
        self._bucket_cap: int | None = None  # set by _split_oversized at train
        self._trained_at = 0  # corpus size at last (re)train
        self._host_cache: "tuple | None" = None  # f32 mirrors for the CPU path
        # CSR + paged layout (built lazily by _ensure_index)
        self._index_dirty = True
        self._csr_offsets: np.ndarray | None = None
        self._csr_rows: np.ndarray | None = None
        self._first_page: np.ndarray | None = None
        self._n_pages: np.ndarray | None = None
        self._page_rows: np.ndarray | None = None
        self._max_pages = 1
        self._packed: "tuple | None" = None  # device mirror (packed, pn, pm, rows)
        # distinct (q_pow2, k_pow2) shape buckets this store has served — the
        # recompile-observability counter (bench + jit-cache regression test)
        self.search_shape_buckets: set = set()

    # -- DenseKNNStore hooks -------------------------------------------------

    def _after_grow(self, old_capacity: int, extra: int) -> None:
        pad = np.full(extra, -1, dtype=np.int32)
        self._assign = np.concatenate([self._assign, pad])
        self._assign2 = np.concatenate([self._assign2, pad.copy()])
        self._invalidate_index()  # geometry changed; rebuild lazily

    def _after_flush_adds(self, padded_slots: np.ndarray, vecs: jax.Array) -> None:
        # assign the new rows to centroids (chunked device passes) unless a
        # retrain will re-assign everything anyway
        if self._centroids is not None:
            top2 = self._assign_rows(vecs)
            self._assign[padded_slots] = top2[:, 0]
            self._assign2[padded_slots] = top2[:, 1]
        self._invalidate_index()

    def _after_flush_removals(self) -> None:
        self._invalidate_index()

    def _invalidate_index(self) -> None:
        self._index_dirty = True
        self._packed = None
        self._host_cache = None

    # training runs on a SAMPLE (faiss-style): k-means cost and its (chunk, C)
    # intermediates stay bounded however large the corpus grows
    _TRAIN_SAMPLE_PER_CLUSTER = 32

    def _assign_rows(self, rows: jax.Array) -> np.ndarray:
        """Top-2 centroid assignment for ``rows``, chunked so BOTH the
        (chunk, C) affinity and the (chunk, dim) block stay within a fixed
        memory budget at any cluster count / dimensionality."""
        chunk = max(1024, (1 << 28) // max(self.n_clusters, self.dim, 1))
        parts = []
        for start in range(0, rows.shape[0], chunk):
            parts.append(
                np.asarray(_assign2_kernel(rows[start : start + chunk], self._centroids))
            )
        return np.concatenate(parts) if parts else np.zeros((0, 2), dtype=np.int32)

    def _maybe_train(self) -> None:
        n = len(self.slot_of)
        if n == 0:
            return
        needs = self._centroids is None or n >= 2 * max(self._trained_at, 1)
        if not needs:
            return
        self.n_clusters = self._n_clusters_base
        rng = np.random.default_rng(0)
        live = np.fromiter(self.slot_of.values(), dtype=np.int64)
        seeds = rng.choice(live, size=self.n_clusters, replace=len(live) < self.n_clusters)
        # k-means accumulates means: always train in f32 even over a bf16 corpus
        init = self._data[jnp.asarray(seeds)].astype(jnp.float32)
        sample_cap = self.n_clusters * self._TRAIN_SAMPLE_PER_CLUSTER
        if len(live) > sample_cap:
            sample = np.sort(rng.choice(live, size=sample_cap, replace=False))
        else:
            # gather LIVE rows only: casting the whole preallocated buffer to
            # f32 would materialize capacity x dim (multi-GB for a large store)
            sample = np.sort(live)
        train_vecs = self._data[jnp.asarray(sample)].astype(jnp.float32)
        n_train = len(sample)
        pad = (-n_train) % _KMEANS_CHUNK
        if pad:
            train_vecs = jnp.concatenate(
                [train_vecs, jnp.zeros((pad, self.dim), jnp.float32)]
            )
        train_valid = jnp.arange(n_train + pad) < n_train
        self._centroids = _kmeans_kernel(train_vecs, train_valid, init, self.train_iters)
        # assign the FULL corpus to the trained centroids (chunked device passes)
        top2 = self._assign_rows(self._data)
        self._assign = top2[:, 0].copy()
        self._assign2 = top2[:, 1].copy()
        self._split_oversized(live)
        self._trained_at = n
        self._invalidate_index()

    @staticmethod
    def _cap_for(n_live: int, n_clusters: int) -> int:
        """Target per-cluster occupancy: ~1.5x the mean, rounded up to pow2 —
        the padded page budget search pays for."""
        mean = max(1, n_live // max(n_clusters, 1))
        cap = 8
        while cap < (3 * mean + 1) // 2:
            cap *= 2
        return cap

    def _split_oversized(self, live: np.ndarray) -> None:
        """Bound the bucket width by SPLITTING oversized clusters instead of
        letting the per-cluster page budget track the most bloated one: each
        cluster past the cap gets a host-side 2-means over its members, the
        centroid is replaced by the pair, and siblings cross-link as each
        other's spill target. k-means over manifold-clustered corpora routinely
        leaves a handful of clusters at 3-4x the mean; without splits the whole
        candidate volume doubles for them."""
        if not len(live):
            return
        cap = self._cap_for(len(live), self.n_clusters)
        self._bucket_cap = cap
        limit = 2 * self.n_clusters  # at most double the cluster count
        cents = np.array(self._centroids, dtype=np.float32)
        for _ in range(6):  # each round halves offenders; 6 covers 64x skew
            al = self._assign[live]
            counts = np.bincount(al, minlength=self.n_clusters)
            over = np.where(counts > cap)[0]
            if not len(over) or self.n_clusters + len(over) > limit:
                break
            order = np.argsort(al, kind="stable")
            starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
            new_rows: List[np.ndarray] = []
            for c in over:
                mem = live[order[starts[c] : starts[c] + counts[c]]]
                vecs = np.asarray(
                    self._data[jnp.asarray(mem)].astype(jnp.float32)
                )
                # 2-means, host-side (members are a few thousand rows at most)
                c0, c1 = vecs[0], vecs[len(vecs) // 2]
                for _it in range(6):
                    d0 = np.sum((vecs - c0) ** 2, axis=1)
                    d1 = np.sum((vecs - c1) ** 2, axis=1)
                    g1 = d1 < d0
                    if g1.all() or (~g1).all():
                        break
                    c0 = vecs[~g1].mean(axis=0)
                    c1 = vecs[g1].mean(axis=0)
                new_id = self.n_clusters
                self.n_clusters += 1
                self._assign[mem[g1]] = new_id
                self._assign2[mem[g1]] = c
                self._assign2[mem[~g1]] = new_id
                cents[c] = c0
                new_rows.append(c1[None, :])
            if new_rows:
                cents = np.concatenate([cents] + new_rows)
        self._centroids = jnp.asarray(cents)
        self.n_probe = min(self.n_probe, self.n_clusters)

    def _ensure_index(self) -> None:
        """Pack live slots into the CSR (+ paged) inverted-list layout — one
        vectorized sort + fancy-index pass (this reruns after every mutation
        batch, so it must not walk the corpus in Python).

        The per-cluster page budget is what search pays for (candidates per
        probe = max_pages * PAGE), so oversized clusters are rebalanced first:
        overflow members past ~1.5x the mean spill to their 2nd-nearest
        centroid. A spilled point sits in a cluster whose centroid is nearly as
        close, so probes still find it; the win is a bounded budget instead of
        one tracking the most bloated cluster."""
        if not self._index_dirty:
            return
        live = np.fromiter(self.slot_of.values(), dtype=np.int64)
        C = self.n_clusters
        counts = np.zeros(C, dtype=np.int64)
        a = np.zeros(0, dtype=np.int64)
        if len(live):
            a = self._assign[live].astype(np.int64)
            a2 = self._assign2[live]
            counts = np.bincount(a, minlength=C)
            cap = self._bucket_cap or self._cap_for(len(live), C)
            over = np.where(counts > cap)[0]
            if len(over):
                a = a.copy()
                order = np.argsort(a, kind="stable")
                starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
                for c in over:
                    tail = order[starts[c] + cap : starts[c] + counts[c]]
                    mv = tail[a2[tail] != c]
                    a[mv] = a2[mv]
                counts = np.bincount(a, minlength=C)
        offsets = np.zeros(C + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        order = np.argsort(a, kind="stable")
        sorted_a = a[order]
        sorted_slots = live[order].astype(np.int32)
        self._csr_offsets = offsets
        self._csr_rows = sorted_slots
        # paged mirror: per-cluster member lists padded to PAGE multiples and
        # packed contiguously; total page count padded pow2 with a trailing
        # all-pad sentinel page so the kernel shapes only change on doubling
        n_pages_c = -(-counts // PAGE)  # ceil; empty clusters get 0 pages
        first_page = np.zeros(C, dtype=np.int32)
        if C:
            np.cumsum(n_pages_c[:-1], out=first_page[1:])
        total = int(n_pages_c.sum()) + 1
        pages_pow2 = next_pow2(total)
        page_rows = np.full(pages_pow2 * PAGE, -1, dtype=np.int32)
        if len(live):
            within = np.arange(len(live), dtype=np.int64) - offsets[sorted_a]
            dest = first_page[sorted_a].astype(np.int64) * PAGE + within
            page_rows[dest] = sorted_slots
        self._first_page = first_page
        self._n_pages = n_pages_c.astype(np.int32)
        self._page_rows = page_rows
        self._max_pages = int(max(1, n_pages_c.max() if C else 1))
        self._index_dirty = False
        self._packed = None

    def _ensure_packed(self) -> None:
        """Device mirror of the paged layout (skipped entirely on the CPU
        numpy path): one fused gather per rebuild."""
        if self._packed is not None:
            return
        rows = jnp.asarray(self._page_rows)
        packed, pn, pm = _pack_pages_kernel(self._data, self._norms, self._valid, rows)
        # first_page/n_pages ride along so steady-state queries re-upload
        # nothing: the hot path stays one device round-trip per batch
        self._packed = (
            packed, pn, pm, rows,
            jnp.asarray(self._first_page), jnp.asarray(self._n_pages),
        )

    # -- query paths ---------------------------------------------------------

    def _effective_n_probe(self) -> int:
        """``n_probe`` after the brownout ladder's degradation shift
        (``engine/brownout.py``): under rung 2 the serving plane halves the
        probed clusters — recall degrades honestly instead of the embed/query
        queue growing without bound. Level 0 (the steady state) returns
        ``n_probe`` unchanged, so normal serving is bit-identical to the
        pre-brownout build. On the device path each shift level is one extra
        jit bucket (``n_probe`` is a static kernel argument) — bounded at the
        ladder's two rungs."""
        from pathway_tpu.engine.brownout import get_brownout

        return max(1, self.n_probe >> get_brownout().nprobe_shift())

    def _search_numpy(
        self, queries: np.ndarray, k_eff: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Host BLAS path for CPU backends, walking the CSR cluster-major: for
        every probed cluster, ONE GEMM of the queries probing it against that
        cluster's member block. Candidate vectors are read once per batch
        through BLAS instead of being materialized per query — the
        (q, n_probe * bucket_width, dim) gather this replaces was the 100x
        slowdown in BENCH_r05."""
        if self._host_cache is None:
            self._host_cache = (
                np.asarray(self._data.astype(jnp.float32)),
                np.asarray(self._norms),
                np.asarray(self._centroids, dtype=np.float32),
            )
        data, norms, cents = self._host_cache
        offsets, rows = self._csr_offsets, self._csr_rows
        counts_all = offsets[1:] - offsets[:-1]
        cn = np.sum(cents * cents, axis=1)
        n_probe = self._effective_n_probe()
        nq_total = queries.shape[0]
        out_scores = np.full((nq_total, k_eff), -np.inf, dtype=np.float32)
        out_slots = np.full((nq_total, k_eff), -1, dtype=np.int64)
        # chunk queries so the (chunk, worst-case candidates) buffers stay
        # within a fixed budget however skewed the cluster sizes are
        w_est = n_probe * int(max(counts_all.max() if len(counts_all) else 1, 1))
        CH = int(max(64, min(1024, (1 << 28) // max(8 * w_est, 1))))
        for start in range(0, nq_total, CH):
            q = queries[start : start + CH]
            nq = q.shape[0]
            aff = 2.0 * q @ cents.T - cn[None, :]
            probe = np.argpartition(aff, -n_probe, axis=1)[:, -n_probe:]
            pc = counts_all[probe]  # (nq, n_probe) candidate counts
            col0 = np.zeros_like(pc)
            np.cumsum(pc[:, :-1], axis=1, out=col0[:, 1:])
            W = int(pc.sum(axis=1).max()) if nq else 0
            if W == 0:
                continue
            buf_s = np.full((nq, W), -np.inf, dtype=np.float32)
            buf_i = np.full((nq, W), -1, dtype=np.int32)  # slots fit int32
            qn = np.sum(q * q, axis=1)
            # cluster-major iteration: group (query, probe) pairs by cluster
            flatc = probe.ravel()
            flatq = np.repeat(np.arange(nq), probe.shape[1])
            flats = col0.ravel()
            order = np.argsort(flatc, kind="stable")
            fc, fq, fs = flatc[order], flatq[order], flats[order]
            uniq, first = np.unique(fc, return_index=True)
            bounds = np.append(first, len(fc))
            for g in range(len(uniq)):
                c = int(uniq[g])
                mem = rows[offsets[c] : offsets[c + 1]]
                mc = len(mem)
                if mc == 0:
                    continue
                sel = slice(bounds[g], bounds[g + 1])
                qs, ds = fq[sel], fs[sel]
                sub = host_metric_scores(q[qs], data[mem], norms[mem], qn[qs], self.metric)
                cols = ds[:, None] + np.arange(mc)[None, :]
                buf_s[qs[:, None], cols] = sub
                buf_i[qs[:, None], cols] = mem
            ts, ti = topk_rows(buf_s, buf_i, k_eff)
            out_scores[start : start + nq] = ts
            out_slots[start : start + nq] = ti
        return out_scores, out_slots

    def _search_device_launch(
        self, queries: Any, k_eff: int, impl: str | None = None
    ) -> Tuple[jax.Array, jax.Array]:
        """Dispatch the fused device path WITHOUT blocking on the result — the
        sharded store launches every shard's kernel before fetching any, so
        query latency is max-over-shards, not sum. ``impl`` overrides the
        scoring implementation (tests force ``"xla"``/``"pallas_interpret"``)."""
        self._ensure_packed()
        if impl is None:
            impl = "pallas" if jax.default_backend() == "tpu" else "xla"
        packed, pn, pm, rows, first_page, n_pages = self._packed
        if isinstance(queries, jax.Array):
            q_dev = queries.astype(jnp.float32)
        else:
            q_dev = jnp.asarray(np.asarray(queries, dtype=np.float32))
        nq = q_dev.shape[0]
        n_probe = self._effective_n_probe()
        cand = n_probe * self._max_pages * PAGE
        k_used = min(next_pow2(max(1, k_eff)), cand)
        # chunk the query batch so the streamed tile + the (chunk, cand) score
        # matrix stay within a fixed HBM budget
        q_chunk = next_pow2(max(8, min(nq, (1 << 26) // max(cand, 1))))
        parts = []
        for start in range(0, max(nq, 1), q_chunk):
            sl, _n = pad_queries_pow2(q_dev[start : start + q_chunk], self.dim)
            self.search_shape_buckets.add((sl.shape[0], k_used))
            parts.append(
                _ivf_query_fused(
                    self._centroids, first_page, n_pages, packed, pn, pm, rows,
                    sl, k_used, n_probe, self._max_pages, self.metric, impl,
                )
            )
        top_scores = jnp.concatenate([p[0] for p in parts])[:nq, :k_eff]
        top_slots = jnp.concatenate([p[1] for p in parts])[:nq, :k_eff]
        return top_scores, top_slots

    def _search_device(
        self, queries: Any, k_eff: int, impl: str | None = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        top_scores, top_slots = self._search_device_launch(queries, k_eff, impl)
        scores, idx = jax.device_get((top_scores, top_slots))
        return scores, idx.astype(np.int64)

    def _prepare_search(self) -> bool:
        """Flush mutations, (re)train if due, build the CSR/paged layout.
        False while the store is empty (nothing trained to search)."""
        self._flush()
        self._maybe_train()
        if self._centroids is None:
            return False
        self._ensure_index()
        return True

    def search_batch(self, queries: Any, k: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not self._prepare_search():
            n = int(np.asarray(queries).shape[0]) if not isinstance(queries, jax.Array) else queries.shape[0]
            return (
                np.full((n, max(1, k)), -np.inf, dtype=np.float32),
                np.full((n, max(1, k)), -1, dtype=np.int64),
                np.zeros((n, max(1, k)), dtype=bool),
            )
        k_eff = max(1, k)
        if jax.default_backend() == "cpu":
            q_np = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
            self.search_shape_buckets.add(
                (next_pow2(max(8, q_np.shape[0])), next_pow2(k_eff))
            )
            scores, idx = self._search_numpy(q_np, k_eff)
        else:
            if not isinstance(queries, jax.Array):
                queries = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
            elif queries.ndim != 2 or queries.shape[-1] != self.dim:
                queries = queries.reshape(-1, self.dim)
            scores, idx = self._search_device(queries, k_eff)
        valid = np.isfinite(scores)
        if scores.shape[1] < k_eff:  # fewer candidates than k: pad result shape
            pad = k_eff - scores.shape[1]
            scores = np.pad(scores, ((0, 0), (0, pad)), constant_values=-np.inf)
            idx = np.pad(idx, ((0, 0), (0, pad)), constant_values=-1)
            valid = np.pad(valid, ((0, 0), (0, pad)), constant_values=False)
        return scores, idx, valid
