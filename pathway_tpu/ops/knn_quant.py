"""Quantized retrieval tower: per-page symmetric int8 rows with an exact
fp32 rescore epilogue (ROADMAP item 5, the last retrieve-hot-path lever).

The PR-15 tiered store keeps every tier in fp32, so the
``PATHWAY_IVF_HBM_BUDGET_MB`` hot tier holds ~4x fewer documents than the
same bytes could. This module supplies the quantization layer the tiered
store (``ops/knn_tiers.py``) threads through host blocks, hot mirrors and
the frozen spill tier:

- **Per-page symmetric int8.** Each 128-row page (the PR-1 residency unit)
  carries one fp32 scale (``max|v| / 127``) and a zero-point slot (always
  ``0.0`` for the symmetric int8 scheme; the field exists so the reserved
  asymmetric/fp8 formats extend the sidecar, not the protocol) — the same
  shape paged-attention kernels use for per-page KV state.
- **Exact integer dot products.** The approximate pass accumulates the int8
  dot in float32 BLAS over the *cast* codes: every product is an integer
  ``<= 127^2`` and every partial sum stays below ``2^24`` for ``dim <=
  1024``, so f32 accumulation is EXACT whatever the accumulation order —
  which is precisely why hot/cold/spill residency stays bitwise-invariant
  under int8 without a parity ceremony (``_INT8_EXACT_DIM_LIMIT`` guards
  the bound; larger dims fall back to int32 accumulation).
- **Exact fp32 rescore epilogue.** The int8 pass only builds a
  ``PATHWAY_IVF_RESCORE_K``-deep shortlist; the scores a search RETURNS are
  recomputed from the fp32 source rows through :func:`rescore_pairs` — THE
  pinned epilogue the store, the tests and ``bench.py quant`` all share, so
  "returned scores are exact" holds by construction and a stale sidecar or
  a wrong gather is a bitwise diff, not a silent recall drop.

The fp32 rows remain the source of truth everywhere (export, rebuild,
descriptor replication, the rescore pass); int8 is a *derived mirror*, and
every derivation site is deterministic round-to-nearest (stochastic
rounding is a training trick — retrieval wants replayable bits).

Device kernels (:func:`quant_probe_kernel` / :func:`quant_score_block_kernel`)
are module-level jitted functions registered in ``kernel_cache_sizes()``
beside ``tiered_assign``/``tiered_score``; both take pow2-bucketed shapes so
their jit caches stay O(log) like every other search kernel.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

PAGE = 128  # one scale/zero-point pair per 128-row page (the residency unit)

#: largest dim for which the f32-accumulated int8 dot is exact: every partial
#: sum is an integer bounded by dim * 127^2 and f32 represents integers up to
#: 2^24 exactly, so accumulation order cannot change the result
_INT8_EXACT_DIM_LIMIT = (1 << 24) // (127 * 127)


class QuantConfigError(RuntimeError):
    """Typed misconfiguration of the quantized tower (unknown or reserved
    ``PATHWAY_IVF_QUANT`` mode, replica mode mismatch) — callers triage by
    type, never by repr."""


def quant_mode(raw: "str | None" = None) -> str:
    """Resolve the quantization mode: ``off`` (default) or ``int8``.

    ``fp8`` is a RESERVED mode (the sidecar format carries zero-points for
    it) — asking for it is a typed refusal, not a silent fp32 fallback, and
    so is any unknown value: a typo'd mode silently serving full precision
    would defeat the budget the operator thinks they configured."""
    if raw is None:
        raw = os.environ.get("PATHWAY_IVF_QUANT", "off")
    mode = (raw or "off").strip().lower()
    if mode in ("off", "0", "false", "no", "none", ""):
        return "off"
    if mode == "int8":
        return "int8"
    if mode == "fp8":
        raise QuantConfigError(
            "PATHWAY_IVF_QUANT=fp8 is reserved: the sidecar format supports "
            "it but no fp8 kernel ships yet — use int8 or off"
        )
    raise QuantConfigError(
        f"unknown PATHWAY_IVF_QUANT mode {raw!r}: expected off|int8 (fp8 reserved)"
    )


def rescore_k() -> int:
    """``PATHWAY_IVF_RESCORE_K``: exact-rescore shortlist depth (default 64).
    The effective depth is ``max(k, PATHWAY_IVF_RESCORE_K)`` clamped to the
    candidate count — the shortlist can never be shallower than the answer."""
    try:
        return max(1, int(os.environ.get("PATHWAY_IVF_RESCORE_K", "") or 64))
    except ValueError:
        return 64


# ---------------------------------------------------------------------------
# per-page quantization (host, deterministic)
# ---------------------------------------------------------------------------


def page_scale(rows: np.ndarray) -> float:
    """Symmetric scale of one page: ``max|v| / 127`` (1.0 for an all-zero
    page so dequantization stays well-defined)."""
    m = float(np.max(np.abs(rows))) if rows.size else 0.0
    return (m / 127.0) if m > 0.0 else 1.0


def quantize_rows(rows: np.ndarray, scale: float) -> np.ndarray:
    """Round-to-nearest int8 codes of ``rows`` at ``scale`` (clipped to
    [-127, 127]; -128 is never produced so negation stays closed)."""
    return np.clip(np.rint(rows / np.float32(scale)), -127, 127).astype(np.int8)


def quantize_block(
    vecs: np.ndarray, pages: "range | np.ndarray | None" = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Quantize a (cap, dim) block per page. Returns ``(qvecs int8 (cap,
    dim), qscale f32 (cap // PAGE,), qzero f32 (cap // PAGE,))``; ``pages``
    limits the work to the named page indices (the append/recalibrate hook —
    untouched pages keep their existing codes when the caller splices)."""
    cap = vecs.shape[0]
    n_pages = max(1, cap // PAGE)
    qvecs = np.zeros((cap, vecs.shape[1]), dtype=np.int8)
    qscale = np.ones(n_pages, dtype=np.float32)
    qzero = np.zeros(n_pages, dtype=np.float32)
    todo = range(n_pages) if pages is None else pages
    for p in todo:
        lo, hi = p * PAGE, min((p + 1) * PAGE, cap)
        if lo >= cap:
            continue
        s = page_scale(vecs[lo:hi])
        qscale[p] = np.float32(s)
        qvecs[lo:hi] = quantize_rows(vecs[lo:hi], s)
    return qvecs, qscale, qzero


def row_scales(qscale: np.ndarray, cap: int) -> np.ndarray:
    """Broadcast (n_pages,) page scales to (cap,) per-row scales."""
    return np.repeat(qscale, PAGE)[:cap].astype(np.float32)


# ---------------------------------------------------------------------------
# int8 scoring (host path — exact integer dots, order-invariant)
# ---------------------------------------------------------------------------


def quantize_queries(q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row symmetric int8 query codes: ``(codes int8 (nq, dim), scales
    f32 (nq,))``. Queries that already sit on the int8 lattice (the
    encoder's quantized tower) re-quantize with ZERO extra rounding error —
    the row max is itself a lattice point, so the scale reproduces."""
    q = np.asarray(q, dtype=np.float32)
    m = np.max(np.abs(q), axis=1)
    scales = np.where(m > 0.0, m / 127.0, 1.0).astype(np.float32)
    codes = np.clip(np.rint(q / scales[:, None]), -127, 127).astype(np.int8)
    return codes, scales


def int8_dot(q_codes: np.ndarray, d_codes: np.ndarray) -> np.ndarray:
    """Exact (nq, rows) integer dot of int8 code matrices. For ``dim <=
    _INT8_EXACT_DIM_LIMIT`` the codes are cast to f32 and accumulated
    through BLAS — every partial sum is an exactly-representable integer, so
    the result is bit-identical to integer accumulation in ANY order (this
    is what makes residency moves bitwise-invariant under int8 without a
    per-tier parity probe). Larger dims accumulate in int32.

    Accepts pre-cast f32 code matrices too (``copy=False`` makes the cast a
    no-op), so callers holding a cached cast skip the per-call copy."""
    if q_codes.shape[1] <= _INT8_EXACT_DIM_LIMIT:
        return (
            q_codes.astype(np.float32, copy=False)
            @ d_codes.astype(np.float32, copy=False).T
        )
    return (
        q_codes.astype(np.int32) @ d_codes.astype(np.int32).T
    ).astype(np.float32)


def approx_scores(
    q_codes: np.ndarray,
    q_scales: np.ndarray,
    qn: np.ndarray,
    d_codes: np.ndarray,
    d_row_scales: np.ndarray,
    d_norms: np.ndarray,
    metric: str,
    maskadd: "np.ndarray | None" = None,
    negnorm: "np.ndarray | None" = None,
) -> np.ndarray:
    """Approximate metric scores from int8 codes: the dequantized dot rides
    the SAME metric epilogue shape as the exact path, with the exact fp32
    norms (stored anyway — only the cross-term is approximate). Shortlist
    builder ONLY: returned scores never leave the store (the rescore pass
    replaces them).

    The epilogue runs in place on the dot buffer, and for l2sq the 2x folds
    into the query scales up front — multiplying by an exact power of two
    commutes through f32 products bit-for-bit, so the values stay identical
    to the device kernel's ``2.0 * (dot * (qs x srow)) - ...`` order while
    the host pays one pass fewer per block. ``maskadd`` (0/-inf additive
    validity, the device-mirror mask contract) folds dead-row masking into
    one vector add. ``negnorm`` (l2sq only) is the caller's pre-fused
    ``maskadd - d_norms`` vector: two epilogue passes collapse into one,
    bitwise-identical to the unfused order because adding exact 0 is a
    no-op, ``0 - x`` is exact negation, and -inf absorbs every finite
    add.

    l2sq scores here are AFFINITIES, not full scores: the exact path's
    ``-|q|^2`` term is a per-query constant that cannot change within-query
    ranking, so the shortlist builder omits it (the same convention the
    coarse probe uses) and saves a pass per block. The exact rescore
    epilogue puts the full metric back."""
    dot = int8_dot(q_codes, d_codes)
    if metric == "l2sq":
        dot *= (2.0 * q_scales)[:, None] * d_row_scales[None, :]
        if negnorm is not None:
            dot += negnorm[None, :]
        else:
            dot -= d_norms[None, :]
            if maskadd is not None:
                dot += maskadd[None, :]
        return dot
    if metric == "cos":
        dot *= q_scales[:, None] * d_row_scales[None, :]
        dot /= np.maximum(
            np.sqrt(qn)[:, None] * np.sqrt(d_norms)[None, :], 1e-30
        )
    else:  # ip
        dot *= q_scales[:, None] * d_row_scales[None, :]
    if maskadd is not None:
        dot += maskadd[None, :]
    return dot


# ---------------------------------------------------------------------------
# exact fp32 epilogues (host) — THE pinned rescore contract
# ---------------------------------------------------------------------------


def host_metric_scores(
    q: np.ndarray, vecs: np.ndarray, norms: np.ndarray, qn: np.ndarray, metric: str
) -> np.ndarray:
    """The exact fp32 cluster-block scores ``(group_q, rows)`` — the ONE
    host metric epilogue shared by ``knn_ivf._search_numpy`` and the tiered
    store's host path (factored here so the quant rescore and the fp32
    scorers can never drift apart)."""
    s = q @ vecs.T
    if metric == "l2sq":
        s = 2.0 * s - norms[None, :] - qn[:, None]
    elif metric == "cos":
        s = s / np.maximum(np.sqrt(qn)[:, None] * np.sqrt(norms)[None, :], 1e-30)
    return s


def rescore_pairs(
    q_rows: np.ndarray, vecs: np.ndarray, norms: np.ndarray, qn_rows: np.ndarray,
    metric: str,
) -> np.ndarray:
    """THE exact rescore epilogue: fp32 scores of (query, document) PAIRS
    (one score per row of the stacked inputs). The tiered store computes its
    returned scores through this function and nothing else; the bench/test
    honesty key recomputes it over the returned (query, slot) pairs from the
    fp32 source rows — bitwise equality is the contract, so a stale
    sidecar, a wrong gather or an approximate score leaking into the output
    is a byte diff, not a recall anecdote."""
    dot = np.einsum(
        "ij,ij->i", q_rows.astype(np.float32), vecs.astype(np.float32)
    )
    if metric == "l2sq":
        return (2.0 * dot - norms - qn_rows).astype(np.float32)
    if metric == "cos":
        return (
            dot / np.maximum(np.sqrt(qn_rows) * np.sqrt(norms), 1e-30)
        ).astype(np.float32)
    return dot.astype(np.float32)


# ---------------------------------------------------------------------------
# device kernels (non-CPU backends; pow2-bucketed, registered in
# kernel_cache_sizes() beside tiered_assign / tiered_score)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("metric",))
def quant_score_block_kernel(
    qvecs: jax.Array,      # (cap, dim) int8 codes — the hot mirror payload
    scales: jax.Array,     # (cap,) f32 per-row (page-broadcast) scales
    norms: jax.Array,      # (cap,) f32 exact norms
    mask: jax.Array,       # (cap,) additive 0/-inf validity mask
    q_codes: jax.Array,    # (q_pad, dim) int8 query codes
    q_scales: jax.Array,   # (q_pad,) f32 query scales
    qn: jax.Array,         # (q_pad,) f32 exact query norms
    metric: str,
) -> jax.Array:
    """Score one hot cluster block from int8 codes on device: the int8 dot
    accumulates in f32 (exact integers for dim <= 1024 — same invariance
    argument as the host path, so device/host parity is arithmetic, not
    luck), then the shared metric epilogue shape. Block capacities and query
    batches are pow2 so the jit cache stays O(log).

    The l2sq branch mirrors :func:`approx_scores` operation-for-operation —
    2x folded into the query scales (exact pow2 multiply), the per-query
    ``-|q|^2`` shift omitted (rank-invariant for the shortlist), validity
    mask and ``-|d|^2`` fused into one add — so the first-use parity probe
    holds by the same bitwise arguments the host path relies on."""
    dotq = jnp.dot(
        q_codes.astype(jnp.float32), qvecs.astype(jnp.float32).T,
        preferred_element_type=jnp.float32,
    )
    if metric == "l2sq":
        dot = dotq * ((2.0 * q_scales)[:, None] * scales[None, :])
        return dot + (mask - norms)[None, :]
    dot = dotq * (q_scales[:, None] * scales[None, :])
    if metric == "cos":
        scores = dot / jnp.maximum(
            jnp.sqrt(qn)[:, None] * jnp.sqrt(norms)[None, :], 1e-30
        )
    else:  # ip
        scores = dot
    return scores + mask[None, :]


@jax.jit
def quant_probe_kernel(
    qcents: jax.Array,     # (C_pad, dim) int8 centroid codes
    cscales: jax.Array,    # (C_pad,) f32 per-centroid scales
    cn: jax.Array,         # (C_pad,) f32 exact |c|^2 (+inf on pad rows)
    q_codes: jax.Array,    # (q_pad, dim) int8 query codes
    q_scales: jax.Array,   # (q_pad,) f32 query scales
) -> jax.Array:
    """Coarse-probe affinity ``2 q·c - |c|^2`` from int8 codes (l2sq-order
    affinity, the same ranking the fp32 coarse probe uses for every metric).
    Centroid count pads to pow2 with ``cn = +inf`` rows (affinity -inf, never
    probed) so the jit cache is O(log^2) over (C, q) buckets."""
    dot = jnp.dot(
        q_codes.astype(jnp.float32), qcents.astype(jnp.float32).T,
        preferred_element_type=jnp.float32,
    ) * (q_scales[:, None] * cscales[None, :])
    return 2.0 * dot - cn[None, :]


def coarse_affinity(
    q_codes: np.ndarray, q_scales: np.ndarray, qcents: np.ndarray,
    cscales: np.ndarray, cn: np.ndarray,
) -> np.ndarray:
    """Host twin of :func:`quant_probe_kernel` (CPU backends skip the jit
    dispatch; the device kernel parity test pins the two together)."""
    dot = int8_dot(q_codes, qcents) * (q_scales[:, None] * cscales[None, :])
    return 2.0 * dot - cn[None, :]
