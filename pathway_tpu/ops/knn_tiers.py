"""Tiered IVF residency: device-hot / host-cold / frozen-spill cluster pages.

ROADMAP item 4's missing piece: :class:`~pathway_tpu.ops.knn_ivf.IvfKnnStore`
keeps the whole packed corpus in one tier, retrains stop-the-world when the
corpus doubles, and rebuilds the full CSR after every mutation batch — fine up
to the HBM budget, hopeless past it and under ``join_churn``-rate ingestion.
This module makes the *page* (the pow2-padded 128-row unit the PR-1 layout was
built around) the unit of residency, the DrJAX array-redistribution view: page
sets move between tiers without per-row host round-trips.

Design
------
- **Primary storage is per-cluster page blocks**, not one monolithic device
  array: each cluster owns a pow2-capacity ``(rows, dim)`` host block (append
  in place, validity mask for removals, per-cluster compaction past 50% dead —
  churn touches only the clusters it names, never the global layout).
- **Three tiers.** *Hot*: clusters whose blocks also hold a device mirror,
  bounded by ``PATHWAY_IVF_HBM_BUDGET_MB`` (0 = unbounded — every cluster is
  promotable, the pre-tiered behavior). *Cold*: host-RAM blocks. *Frozen
  spill* (optional): idle, churn-free clusters serialized behind the existing
  persistence ``ObjectStore`` contract (``attach_spill`` or
  ``PATHWAY_IVF_SPILL_DIR``) and dropped from RAM.
- **Probe-frequency EWMA drives residency**: every ``search_batch`` folds the
  coarse-quantizer's probed cluster set into a per-cluster EWMA
  (``PATHWAY_IVF_EWMA_ALPHA``); hot promotion follows probes, demotion evicts
  the coldest hot blocks when the budget is exceeded. A browned-out probe set
  (``engine/brownout.py`` rung 2 halves ``n_probe``) NEVER triggers promotion
  churn — degradation must not thrash the tiers it is protecting.
- **Async prefetch**: the clusters named by the coarse top-``n_probe`` are
  staged by a background worker *before* the scoring loop needs them, so a
  cold/frozen hit costs one overlap window (hot clusters score while the
  stage runs), not a synchronous H2D / object-store stall. Stall time that
  does surface is measured (``pathway_ivf_prefetch_stall_seconds``).
- **Incremental centroid maintenance**: per-cluster drift counters (adds +
  removals vs. the size the cluster was last trained at) trigger per-cluster
  recenter / re-assign / split / merge only — bounded work per maintenance
  pass, no global retrain on the churn path.
- **Fence-riding background rebuild**: when cumulative churn reaches
  ``PATHWAY_IVF_REBUILD_DRIFT`` × the trained corpus size, a full re-train
  builds a NEW generation off to the side (background thread over an
  immutable snapshot; live churn keeps landing in the old generation and in
  a dirty-set) and the store swaps generations atomically at the next commit
  boundary — the swap reconciles the dirty-set, takes one bounded pause, and
  the OLD generation keeps serving until the instant it commits (chaos ops
  ``rebuild_kill`` / ``tier_swap_torn`` prove the crash windows). The
  protocol is modeled first (``tiered_index_model`` in
  ``internals/protocol_models.py``) per the PR-9 discipline.

Scoring is cluster-major exactly like the CPU BLAS path of ``knn_ivf``
(identical metric epilogue), so **residency never changes results**: the same
query over the same corpus is bitwise identical whatever tier each cluster
sits in — the honesty key ``bench.py ivfscale`` carries. On non-CPU backends
hot blocks score through a jitted pow2-bucketed device GEMM
(:func:`_score_block_kernel`); fusing the multi-page probe the PR-1 kernel
runs for the untiered store is named upside in ROADMAP item 4.
"""

from __future__ import annotations

import functools
import os
import pickle
import queue
import threading
import time
from itertools import repeat as _repeat
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.engine import telemetry
from pathway_tpu.internals.shapes import next_pow2
from pathway_tpu.ops import knn_quant
from pathway_tpu.ops.knn import topk_rows
from pathway_tpu.ops.knn_ivf import _KMEANS_CHUNK, _assign2_kernel, _kmeans_kernel
from pathway_tpu.ops.knn_quant import quant_mode, rescore_k

PAGE = 128  # residency granularity mirrors the packed-page layout of knn_ivf

# sentinel centroid for merged-away clusters: far enough that the coarse
# affinity is hugely negative, small enough that |c|^2 stays finite in f32
_DEAD_CENTROID = 1e18


class TieredIndexError(RuntimeError):
    """Typed failure of the tiered index machinery (spill tier unreachable,
    rebuild worker died) — callers triage by type, never by repr."""


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


def tiering_enabled() -> bool:
    """``PATHWAY_IVF_TIERED``: ``on`` / ``off`` / ``auto`` (default — tiered
    exactly when an HBM budget is configured OR a quantization mode is opted
    in, so existing deployments keep the untiered store bit-for-bit while
    ``PATHWAY_IVF_QUANT=int8`` alone engages the tower that hosts it; the
    alternative — silently serving fp32 under an int8 opt-in — would violate
    the loud-refusal contract)."""
    mode = _env("PATHWAY_IVF_TIERED", "auto").lower()
    if mode in ("on", "1", "true", "yes"):
        return True
    if mode in ("off", "0", "false", "no"):
        return False
    if hbm_budget_bytes() > 0:
        return True
    from pathway_tpu.ops.knn_quant import quant_mode

    return quant_mode() != "off"


def hbm_budget_bytes() -> int:
    """``PATHWAY_IVF_HBM_BUDGET_MB`` as bytes; 0 = unbounded hot tier."""
    try:
        return int(float(_env("PATHWAY_IVF_HBM_BUDGET_MB", "0")) * (1 << 20))
    except ValueError:
        return 0


def _prefetch_enabled() -> bool:
    return _env("PATHWAY_IVF_PREFETCH", "on").lower() not in (
        "off", "0", "false", "no",
    )


def _ewma_alpha() -> float:
    try:
        return min(1.0, max(0.01, float(_env("PATHWAY_IVF_EWMA_ALPHA", "0.2"))))
    except ValueError:
        return 0.2


def _cluster_drift_threshold() -> float:
    try:
        return max(0.05, float(_env("PATHWAY_IVF_CLUSTER_DRIFT", "0.5")))
    except ValueError:
        return 0.5


def _rebuild_drift_threshold() -> float:
    try:
        return max(0.1, float(_env("PATHWAY_IVF_REBUILD_DRIFT", "1.0")))
    except ValueError:
        return 1.0


def _spill_ewma_threshold() -> float:
    try:
        return float(_env("PATHWAY_IVF_SPILL_EWMA", "0.01"))
    except ValueError:
        return 0.01


# ---------------------------------------------------------------------------
# frozen-spill tier: a minimal filesystem ObjectStore (the persistence
# contract: put/get/list/delete) for the PATHWAY_IVF_SPILL_DIR knob; any
# real ObjectStore (S3/Azure/memory) attaches through attach_spill().
# ---------------------------------------------------------------------------


class DirSpillStore:
    """Directory-backed ``ObjectStore`` for the frozen tier. Writes are
    atomic (tmp + rename): a torn spill can never serve a half-written
    cluster block."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "__"))

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def get(self, key: str) -> "bytes | None":
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except OSError:
            return None

    def list(self, prefix: str) -> List[str]:
        pref = prefix.replace("/", "__")
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        return [n.replace("__", "/") for n in names if n.startswith(pref)]

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# cluster page blocks
# ---------------------------------------------------------------------------


class _ClusterPages:
    """One cluster's rows as an appendable pow2-capacity host block.

    ``vecs[:n]`` rows are write-once (an append lands past ``n``; a re-add is
    remove + append), so a background-rebuild snapshot that records
    ``(vecs, n, valid.copy())`` reads a consistent corpus without copying the
    vectors. ``valid`` flips in place on removal — the one mutable field, and
    the one the snapshot copies.

    With ``quant=True`` the block also carries the derived int8 mirror:
    ``qvecs`` codes plus per-page ``qscale``/``qzero`` sidecars
    (``knn_quant``). The fp32 rows stay the source of truth — codes
    re-derive on append for exactly the touched pages, and recalibration
    swaps whole sidecar arrays atomically."""

    __slots__ = (
        "slots", "vecs", "norms", "valid", "n", "n_live", "mutations",
        "quant", "qvecs", "qscale", "qzero", "_qf32", "_qsrow", "_maskadd",
        "_negn",
    )

    def __init__(self, dim: int, cap: int = PAGE, *, quant: bool = False):
        cap = next_pow2(max(PAGE, cap))
        self.slots = np.full(cap, -1, dtype=np.int64)
        self.vecs = np.zeros((cap, dim), dtype=np.float32)
        self.norms = np.zeros(cap, dtype=np.float32)
        self.valid = np.zeros(cap, dtype=bool)
        self.n = 0
        self.n_live = 0
        # bumped on every append/invalidate: a device mirror built off-lock is
        # only installable when the count it captured still matches (object
        # identity alone misses IN-PLACE churn during the stage)
        self.mutations = 0
        self.quant = bool(quant)
        if self.quant:
            n_pages = max(1, cap // PAGE)
            self.qvecs: "np.ndarray | None" = np.zeros((cap, dim), dtype=np.int8)
            self.qscale: "np.ndarray | None" = np.ones(n_pages, dtype=np.float32)
            self.qzero: "np.ndarray | None" = np.zeros(n_pages, dtype=np.float32)
        else:
            self.qvecs = None
            self.qscale = None
            self.qzero = None
        self._qf32: "np.ndarray | None" = None
        self._qsrow: "np.ndarray | None" = None
        self._maskadd: "Tuple[int, np.ndarray] | None" = None
        self._negn: "Tuple[int, np.ndarray] | None" = None

    @property
    def nbytes(self) -> int:
        if self.quant:
            # quant mode prices the QUANTIZED mirror payload (codes + sidecars
            # + exact norms): the hot budget buys ~(1 + 4/dim)x fewer bytes
            # per row than fp32, which IS the capacity multiple the bench
            # measures — the fp32 source rows live in host RAM regardless
            return int(
                self.qvecs.nbytes + self.qscale.nbytes + self.qzero.nbytes
                + self.norms.nbytes + self.slots.nbytes
            )
        return int(self.vecs.nbytes + self.norms.nbytes + self.slots.nbytes)

    def qvecs_f32(self) -> np.ndarray:
        """Cached f32 cast of the int8 codes for host BLAS scoring (numpy
        integer matmul bypasses BLAS entirely; the cast keeps the exact
        integer dots on the fast path). Host-only scratch — excluded from
        ``nbytes`` on purpose: the budget prices the device-mirror payload."""
        if self._qf32 is None:
            self._qf32 = self.qvecs.astype(np.float32)
        return self._qf32

    def qsrow(self, n: int) -> np.ndarray:
        """Cached per-ROW expansion of the per-page scales (host scoring
        multiplies it against every query batch; re-running ``np.repeat``
        per block per batch dominated solo-query latency). Invalidated with
        the f32 cast — both are derived views of the same sidecars."""
        if self._qsrow is None:
            self._qsrow = knn_quant.row_scales(self.qscale, len(self.slots))
        return self._qsrow[:n]

    def maskadd(self, n: int) -> np.ndarray:
        """Additive validity mask (0.0 live / -inf dead) over rows [0:n] —
        one vector add masks a score block, replacing a compare + ``np.where``
        pair per block per batch (the same additive contract the device
        mirrors carry). Keyed on ``mutations`` so any append/invalidate
        rebuilds it."""
        cached = self._maskadd
        if cached is None or cached[0] != self.mutations or len(cached[1]) != n:
            arr = np.where(
                self.valid[:n], np.float32(0.0), np.float32(-np.inf)
            ).astype(np.float32)
            self._maskadd = cached = (self.mutations, arr)
        return cached[1]

    def negn(self, n: int) -> np.ndarray:
        """Pre-fused ``maskadd - norms`` over rows [0:n] for the l2sq
        quant epilogue: the norm subtraction and the validity mask collapse
        into one vector add per block per batch. Bitwise-identical to the
        unfused order (``0 - x`` is exact negation, adding 0 is a no-op,
        -inf absorbs every finite add). Keyed on ``mutations`` + length,
        exactly like :meth:`maskadd`."""
        cached = self._negn
        if cached is None or cached[0] != self.mutations or len(cached[1]) != n:
            arr = (self.maskadd(n) - self.norms[:n]).astype(np.float32)
            self._negn = cached = (self.mutations, arr)
        return cached[1]

    def _drop_quant_caches(self) -> None:
        self._qf32 = None
        self._qsrow = None

    def _requantize_pages(self, pages: "range | np.ndarray") -> None:
        """Re-derive codes + scale for exactly the named pages (append touched
        them); untouched pages keep their existing codes bit-for-bit."""
        cap = len(self.slots)
        for p in pages:
            lo, hi = p * PAGE, min((p + 1) * PAGE, cap)
            s = knn_quant.page_scale(self.vecs[lo:hi])
            self.qscale[p] = np.float32(s)
            self.qvecs[lo:hi] = knn_quant.quantize_rows(self.vecs[lo:hi], s)
        self._drop_quant_caches()

    def append(self, slots: np.ndarray, vecs: np.ndarray, norms: np.ndarray) -> int:
        """Append rows; returns the first position. Grows pow2 (the old
        arrays stay valid for any rebuild snapshot holding them)."""
        need = self.n + len(slots)
        if need > len(self.slots):
            cap = next_pow2(need)
            dim = self.vecs.shape[1]
            new_slots = np.full(cap, -1, dtype=np.int64)
            new_vecs = np.zeros((cap, dim), dtype=np.float32)
            new_norms = np.zeros(cap, dtype=np.float32)
            new_valid = np.zeros(cap, dtype=bool)
            new_slots[: self.n] = self.slots[: self.n]
            new_vecs[: self.n] = self.vecs[: self.n]
            new_norms[: self.n] = self.norms[: self.n]
            new_valid[: self.n] = self.valid[: self.n]
            self.slots, self.vecs = new_slots, new_vecs
            self.norms, self.valid = new_norms, new_valid
            if self.quant:
                n_pages = max(1, cap // PAGE)
                new_qvecs = np.zeros((cap, dim), dtype=np.int8)
                new_qscale = np.ones(n_pages, dtype=np.float32)
                new_qzero = np.zeros(n_pages, dtype=np.float32)
                new_qvecs[: self.n] = self.qvecs[: self.n]
                old_pages = len(self.qscale)
                new_qscale[:old_pages] = self.qscale
                new_qzero[:old_pages] = self.qzero
                self.qvecs, self.qscale, self.qzero = new_qvecs, new_qscale, new_qzero
                self._drop_quant_caches()
        first = self.n
        self.slots[first:need] = slots
        self.vecs[first:need] = vecs
        self.norms[first:need] = norms
        self.valid[first:need] = True
        self.n = need
        self.n_live += len(slots)
        self.mutations += 1
        if self.quant:
            self._requantize_pages(range(first // PAGE, (need - 1) // PAGE + 1))
        return first

    def invalidate(self, pos: int) -> None:
        if self.valid[pos]:
            self.valid[pos] = False
            self.n_live -= 1
            self.mutations += 1

    def live_rows(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        mask = self.valid[: self.n]
        return self.slots[: self.n][mask], self.vecs[: self.n][mask], self.norms[: self.n][mask]

    def to_blob(self) -> bytes:
        slots, vecs, norms = self.live_rows()
        payload = {"slots": slots, "vecs": vecs, "norms": norms}
        if self.quant:
            # spill only freezes COMPACT blocks (n == n_live), so the live
            # rows ARE rows [0:n] in page order and the codes + sidecars
            # serialize verbatim: the round-trip is bit-exact by copy, never
            # by re-derivation (a recalibrated scale survives the freeze)
            payload["qvecs"] = self.qvecs[: self.n]
            payload["qscale"] = self.qscale
            payload["qzero"] = self.qzero
        return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)

    @classmethod
    def from_blob(cls, dim: int, blob: bytes, *, quant: bool = False) -> "_ClusterPages":
        raw = pickle.loads(blob)
        n = len(raw["slots"])
        block = cls(dim, cap=max(PAGE, n), quant=quant)
        if n:
            block.append(raw["slots"], raw["vecs"], raw["norms"])
        if quant and "qvecs" in raw:
            # restore the serialized codes/sidecars bit-for-bit over the
            # append-time re-derivation (identical unless a recalibration
            # tightened the scales pre-freeze — then the blob wins); a blob
            # written before quant was enabled simply keeps the re-derived
            # codes, so a mode flip thaws cleanly
            block.qvecs[:n] = raw["qvecs"]
            pages = min(len(raw["qscale"]), len(block.qscale))
            block.qscale[:pages] = raw["qscale"][:pages]
            block.qzero[:pages] = raw["qzero"][:pages]
            block._drop_quant_caches()
        return block


# ---------------------------------------------------------------------------
# tier manager: residency shared between the engine thread and the prefetcher
# ---------------------------------------------------------------------------


class TierManager:
    """Residency state for ONE index generation: which clusters are hot
    (device mirror within the HBM budget), which are host-cold, which are
    frozen in the spill store. Shared by the engine thread (scoring,
    promotion decisions) and the prefetch worker (staging) — every field
    below is guarded by ``_cv``'s lock."""

    def __init__(
        self,
        dim: int,
        generation: int,
        *,
        budget_bytes: int = 0,
        device: Any = None,
        spill_store: Any = None,
        spill_prefix: str = "ivf-spill",
        quant: str = "off",
    ):
        self.dim = dim
        self.generation = generation
        self.budget_bytes = budget_bytes
        self.device = device
        self.quant = quant
        self._cv = threading.Condition()
        self.pages: Dict[int, Optional[_ClusterPages]] = {}
        self.hot: Dict[int, Any] = {}  # cid -> device mirror (True on CPU)
        # bytes COUNTED IN per hot cid: demotion must subtract exactly what
        # promotion added, not the block's current (possibly grown) size
        self._hot_nbytes: Dict[int, int] = {}
        self.hot_bytes = 0
        self.spilled: Dict[int, str] = {}  # cid -> object key
        self.staging: set = set()
        self.spill_store = spill_store
        self.spill_prefix = spill_prefix

    # -- residency reads ------------------------------------------------------

    def residency(self, cid: int) -> str:
        with self._cv:
            if cid in self.hot:
                return "hot"
            if self.pages.get(cid) is not None:
                return "cold"
            if cid in self.spilled:
                return "spilled"
            return "absent"

    def counts(self) -> Dict[str, int]:
        with self._cv:
            hot = len(self.hot)
            spilled = sum(
                1 for c, p in self.pages.items() if p is None and c in self.spilled
            )
            cold = sum(1 for c, p in self.pages.items() if p is not None) - hot
            return {"hot": hot, "cold": max(0, cold), "spilled": spilled}

    def occupancy(self) -> float:
        with self._cv:
            if self.budget_bytes <= 0:
                return 1.0 if self.hot else 0.0
            return self.hot_bytes / self.budget_bytes

    # -- engine-side installs -------------------------------------------------

    def install(self, cid: int, block: _ClusterPages) -> None:
        """(Re)install a cluster's host block (fresh build or post-churn
        rebuild): any device mirror drops and the spill entry clears. The
        BLOB stays in the store — a background rebuild's snapshot may still
        be reading it; a re-freeze overwrites the same key and the
        generation-swap prefix sweep collects the rest."""
        with self._cv:
            self.pages[cid] = block
            self._demote_locked(cid)
            self.spilled.pop(cid, None)
            self._cv.notify_all()

    def drop(self, cid: int) -> None:
        with self._cv:
            self.pages.pop(cid, None)
            self._demote_locked(cid)
            self.spilled.pop(cid, None)

    # -- hot tier -------------------------------------------------------------

    def _device_mirror(self, block: _ClusterPages) -> Any:
        if jax.default_backend() == "cpu":
            return True  # zero-copy host==device; residency is bookkeeping
        mask = jnp.where(jnp.asarray(block.valid), 0.0, -jnp.inf).astype(jnp.float32)
        if block.quant:
            # the int8 mirror: codes + page-broadcast row scales + exact
            # norms — exactly the payload ``nbytes`` prices against the hot
            # budget (a 4-tuple; the fp32 mirror is a 3-tuple)
            arrs: Tuple[Any, ...] = (
                jnp.asarray(block.qvecs),
                jnp.asarray(knn_quant.row_scales(block.qscale, len(block.slots))),
                jnp.asarray(block.norms),
                mask,
            )
        else:
            arrs = (jnp.asarray(block.vecs), jnp.asarray(block.norms), mask)
        if self.device is not None:
            arrs = tuple(jax.device_put(a, self.device) for a in arrs)
        return arrs

    def promote(self, cid: int) -> bool:
        """Stage ``cid`` hot (called by the prefetcher, or inline). Returns
        False when the block is absent (still frozen) or already hot."""
        with self._cv:
            block = self.pages.get(cid)
            if block is None or cid in self.hot:
                return False
            nbytes = block.nbytes
            mutations = block.mutations
            if 0 < self.budget_bytes < nbytes:
                # a block bigger than the WHOLE budget can never fit: promoting
                # it would evict the entire hot set and still overflow — it
                # serves from the cold tier (hot_bytes <= budget stays a real
                # invariant because of this refusal)
                return False
            self.staging.add(cid)
        try:
            mirror = self._device_mirror(block)
        finally:
            # the staging slot is released on EVERY path — a failed device
            # put must not wedge the cluster out of both tiers
            # (tiered_index_model's leak_stage planted bug)
            with self._cv:
                self.staging.discard(cid)
        evicted: List[Any] = []
        with self._cv:
            if (
                self.pages.get(cid) is not block
                or block.mutations != mutations
            ):
                # churn invalidated the block mid-stage — either replaced
                # outright or mutated IN PLACE (append/invalidate): a mirror
                # built from the pre-churn view must never install
                return False
            self.hot[cid] = mirror
            self._hot_nbytes[cid] = nbytes
            self.hot_bytes += nbytes
            if self.budget_bytes > 0:
                evicted = self._evict_over_budget_locked(keep=cid)
            self._cv.notify_all()
        if evicted:
            telemetry.stage_add("index.demotions", float(len(evicted)))
        return True

    def _demote_locked(self, cid: int) -> None:
        if cid in self.hot:
            del self.hot[cid]  # noqa: PWA103 (caller holds self._cv)
            self.hot_bytes -= self._hot_nbytes.pop(cid, 0)  # noqa: PWA103 (caller holds self._cv)
            self.hot_bytes = max(0, self.hot_bytes)  # noqa: PWA103 (caller holds self._cv)

    def _evict_over_budget_locked(self, keep: int) -> List[int]:
        """Evict hot mirrors (never ``keep``) until within budget; caller
        holds the lock. Eviction order is insertion order — the EWMA-driven
        promotion stream re-promotes anything still actually probed."""
        evicted: List[int] = []
        while self.hot_bytes > self.budget_bytes and len(self.hot) > 1:
            victim = next((c for c in self.hot if c != keep), None)
            if victim is None:
                break
            self._demote_locked(victim)
            evicted.append(victim)
        return evicted

    # -- frozen spill tier ----------------------------------------------------

    def spill(self, cid: int) -> bool:
        """Freeze a cold, churn-free cluster into the object store and drop
        its host block. Engine thread only."""
        if self.spill_store is None:
            return False
        with self._cv:
            block = self.pages.get(cid)
            if block is None or cid in self.hot or cid in self.staging:
                return False
            if block.n != block.n_live:
                # non-compact blocks must NOT freeze: the blob stores live
                # rows compacted, so positions would shift across the
                # round-trip and desynchronize the store's slot locators
                # (the caller compacts first)
                return False
        key = f"{self.spill_prefix}/gen{self.generation}/cluster{cid}"
        self.spill_store.put(key, block.to_blob())
        with self._cv:
            if self.pages.get(cid) is not block:
                return False  # churned while serializing: blob is stale
            self.pages[cid] = None
            self.spilled[cid] = key
        return True

    def unspill(self, cid: int) -> Optional[_ClusterPages]:
        """Load a frozen cluster back to the cold tier (prefetcher or the
        synchronous stall path). Returns the block, or None when the cluster
        is not frozen (already loaded by a racing stage)."""
        with self._cv:
            block = self.pages.get(cid)
            if block is not None:
                return block
            key = self.spilled.get(cid)
            if key is None or cid in self.staging:
                return None
            self.staging.add(cid)
        blob = None
        try:
            if self.spill_store is not None:
                blob = self.spill_store.get(key)
        finally:
            with self._cv:
                self.staging.discard(cid)
        if blob is None:
            raise TieredIndexError(
                f"spill tier lost cluster {cid} (key {key!r}): the frozen "
                "object store no longer serves it"
            )
        loaded = _ClusterPages.from_blob(self.dim, blob, quant=self.quant == "int8")
        with self._cv:
            if self.pages.get(cid) is None and self.spilled.get(cid) == key:
                self.pages[cid] = loaded
                # the entry clears (the cluster is cold again) but the BLOB
                # stays — a rebuild snapshot may still name it; re-freezing
                # overwrites the same generation-scoped key, and the swap's
                # prefix sweep deletes the whole retired generation, so
                # growth is bounded at one blob per cluster per generation
                self.spilled.pop(cid, None)
                self._cv.notify_all()
                return loaded
            return self.pages.get(cid)

    def wait_loaded(self, cid: int, timeout: float) -> Optional[_ClusterPages]:
        """Block (bounded) until a staged cluster's block lands — the stall
        path when the prefetch window did not fully hide the load."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                block = self.pages.get(cid)
                if block is not None:
                    return block
                if cid not in self.staging and cid not in self.spilled:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(timeout=min(0.25, remaining))


# ---------------------------------------------------------------------------
# async prefetcher
# ---------------------------------------------------------------------------


class Prefetcher:
    """One background worker staging cluster pages ahead of the scorer:
    unspills frozen clusters and promotes probed ones hot. Lazy-spawned,
    daemon, joined on :meth:`close`; the request queue is bounded so a probe
    storm degrades to synchronous loads instead of unbounded memory."""

    _IDLE_POLL_S = 0.25

    def __init__(self) -> None:
        self._queue: "queue.Queue[tuple]" = queue.Queue(maxsize=4096)
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self._stop = threading.Event()

    def request(self, manager: TierManager, cids: List[int], *, promote: bool) -> None:
        self._ensure_thread()
        for cid in cids:
            try:
                self._queue.put_nowait((manager, cid, promote))
            except queue.Full:
                break  # scorer falls back to its synchronous path
        telemetry.stage_add("index.prefetch_requests", float(len(cids)))

    def _ensure_thread(self) -> None:
        with self._mu:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="pathway:ivf-prefetch", daemon=True
                )
                self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                manager, cid, promote = self._queue.get(timeout=self._IDLE_POLL_S)
            except queue.Empty:
                continue
            try:
                if manager.residency(cid) == "spilled":
                    manager.unspill(cid)
                    telemetry.stage_add("index.unspills")
                if promote and manager.promote(cid):
                    telemetry.stage_add("index.promotions")
                telemetry.stage_add("index.prefetch_staged")
            except TieredIndexError:
                # the scorer's synchronous path will surface the typed
                # failure to the caller with full context
                telemetry.stage_add("index.prefetch_errors")

    def close(self) -> None:
        with self._mu:
            thread = self._thread
            self._thread = None
        if thread is not None and thread.is_alive():
            self._stop.set()
            thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# device scoring kernel (hot tier, non-CPU backends)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("metric",))
def _score_block_kernel(
    vecs: jax.Array, norms: jax.Array, mask: jax.Array, queries: jax.Array, metric: str
) -> jax.Array:
    """Score one hot cluster block on device: (q, rows) exact scores with the
    SAME metric epilogue as the host path (bitwise parity is the tier-honesty
    contract). Block capacities are pow2 so the jit cache stays O(log)."""
    dot = jnp.dot(queries, vecs.T, preferred_element_type=jnp.float32)
    qn = jnp.sum(queries * queries, axis=1, keepdims=True)
    if metric == "l2sq":
        scores = 2.0 * dot - norms[None, :] - qn
    elif metric == "cos":
        scores = dot / jnp.maximum(
            jnp.sqrt(qn) * jnp.sqrt(norms)[None, :], 1e-30
        )
    else:  # ip
        scores = dot
    return scores + mask[None, :]


# ---------------------------------------------------------------------------
# background rebuild
# ---------------------------------------------------------------------------


class _RebuildResult:
    __slots__ = ("generation", "centroids", "pages", "where", "trained_sizes", "error")

    def __init__(self, generation: int):
        self.generation = generation
        self.centroids: Optional[np.ndarray] = None
        self.pages: Dict[int, _ClusterPages] = {}
        self.where: Dict[int, tuple] = {}
        self.trained_sizes: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


def _two_means(vecs: np.ndarray, iters: int = 6) -> np.ndarray:
    """Host 2-means over one cluster's members; returns a bool mask of the
    second group (the split path — same algorithm the untiered store uses)."""
    c0, c1 = vecs[0], vecs[len(vecs) // 2]
    g1 = np.zeros(len(vecs), dtype=bool)
    for _ in range(iters):
        d0 = np.sum((vecs - c0) ** 2, axis=1)
        d1 = np.sum((vecs - c1) ** 2, axis=1)
        g1 = d1 < d0
        if g1.all() or (~g1).all():
            break
        c0 = vecs[~g1].mean(axis=0)
        c1 = vecs[g1].mean(axis=0)
    return g1


_TRAIN_SAMPLE_PER_CLUSTER = 32


def _train_centroids(
    sample: np.ndarray, n_clusters: int, train_iters: int, seed: int = 0
) -> np.ndarray:
    """k-means over a bounded sample (faiss-style) through the shared device
    kernel; returns host (C, dim) f32 centroids."""
    rng = np.random.default_rng(seed)
    seeds = rng.choice(len(sample), size=n_clusters, replace=len(sample) < n_clusters)
    init = jnp.asarray(sample[seeds], dtype=jnp.float32)
    pad = (-len(sample)) % _KMEANS_CHUNK
    vecs = sample
    if pad:
        vecs = np.concatenate([sample, np.zeros((pad, sample.shape[1]), np.float32)])
    valid = np.arange(len(vecs)) < len(sample)
    cents = _kmeans_kernel(
        jnp.asarray(vecs), jnp.asarray(valid), init, train_iters
    )
    # writable host copy: per-cluster maintenance recenters rows in place
    return np.array(cents, dtype=np.float32)


def _assign_rows_np(rows: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Top-2 centroid assignment, chunked through the shared device kernel.
    Row counts pad to pow2 buckets (floor 256): maintenance assigns ragged
    per-cluster member sets every pass, and an unpadded shape per size would
    compile a fresh XLA program per cluster — the compile storm IS the pause
    this store exists to avoid."""
    if not len(rows):
        return np.zeros((0, 2), dtype=np.int32)
    cents = jnp.asarray(centroids)
    chunk = max(1024, (1 << 28) // max(len(centroids), rows.shape[1], 1))
    parts = []
    for start in range(0, len(rows), chunk):
        block = rows[start : start + chunk]
        n = len(block)
        bucket = next_pow2(max(256, n))
        if bucket != n:
            block = np.concatenate(
                [block, np.zeros((bucket - n, block.shape[1]), block.dtype)]
            )
        got = np.asarray(_assign2_kernel(jnp.asarray(block), cents))
        parts.append(got[:n])
    return np.concatenate(parts)


# ---------------------------------------------------------------------------
# the tiered store
# ---------------------------------------------------------------------------


class TieredIvfKnnStore:
    """Keyed IVF-Flat store with tiered page residency and churn-native
    maintenance. API-compatible with :class:`~pathway_tpu.ops.knn_ivf.
    IvfKnnStore` where the engine touches it (``add``/``add_many``/
    ``remove``/``search_batch``/``key_of``/``slot_of``/``export_rows``)."""

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        initial_capacity: int = 1024,  # accepted for API parity; blocks size themselves
        n_clusters: int = 64,
        n_probe: int = 8,
        train_iters: int = 8,
        device: Any = None,
        hbm_budget_bytes: "int | None" = None,
        spill_store: Any = None,
        prefetch: "bool | None" = None,
        quant: "str | None" = None,
    ):
        assert metric in ("l2sq", "cos", "ip")
        self.dim = dim
        self.metric = metric
        self.device = device
        # quantized tower mode ("off" | "int8"); None reads PATHWAY_IVF_QUANT.
        # Resolved ONCE at construction — a mid-life env flip must go through
        # a rebuild (descriptor install refuses mode mismatches loudly).
        self._quant = quant_mode(quant)
        self._qblocks = self._quant == "int8"
        # lazily-built int8 coarse-probe mirror of the centroids, padded to
        # pow2 with |c|^2 = +inf rows; invalidated at EVERY site that moves
        # self._cents (train/split/maintain/swap) because maintenance
        # recenters rows IN PLACE — identity checks would miss it
        self._qcents: "Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]" = None
        self.n_clusters = max(2, n_clusters)
        self.n_probe = min(n_probe, self.n_clusters)
        self._n_clusters_base = self.n_clusters
        self.train_iters = train_iters
        self.slot_of: Dict[Any, int] = {}
        self.key_of: Dict[int, Any] = {}
        self._next_slot = 0
        # staged adds keyed by slot (insertion-ordered): a removal of a
        # just-staged row is an O(1) pop, not an O(n) list scan — interleaved
        # add/remove churn waves would otherwise go quadratic
        self._staged: Dict[int, np.ndarray] = {}
        self._staged_removals: List[int] = []
        # pre-train holding pen: rows wait here until the first training pass
        self._untrained_slots: List[int] = []
        self._untrained_vecs: List[np.ndarray] = []
        # current generation
        self.generation = 0
        self._cents: Optional[np.ndarray] = None  # (C, dim) f32, host
        # slot -> (cid << 32) | pos, packed so the rescore epilogue can map a
        # whole shortlist with one C-level fromiter(map(get, ...)) pass
        # instead of a python loop over (cid, pos) tuples
        self._where: Dict[int, int] = {}
        self._trained_sizes = np.zeros(0, dtype=np.int64)
        self._drift = np.zeros(0, dtype=np.int64)
        self._ewma = np.zeros(0, dtype=np.float64)
        self._churn_since_train = 0
        self._trained_total = 0
        self._batches = 0  # search batches served (spill settling guard)
        if hbm_budget_bytes is None:
            hbm_budget_bytes = hbm_budget_bytes_env()
        self._budget_bytes = int(hbm_budget_bytes)
        if spill_store is None:
            spill_dir = os.environ.get("PATHWAY_IVF_SPILL_DIR")
            if spill_dir:
                spill_store = DirSpillStore(spill_dir)
        self.tiers = TierManager(
            dim, 0, budget_bytes=self._budget_bytes, device=device,
            spill_store=spill_store, quant=self._quant,
        )
        self._prefetch_on = _prefetch_enabled() if prefetch is None else bool(prefetch)
        self._prefetcher = Prefetcher()
        # hot-block device scoring (non-CPU backends) rides a first-use
        # bitwise parity probe against the host path — any deviation (e.g.
        # accumulation-order differences) permanently downgrades scoring to
        # host BLAS, so residency can never change results (the PR-8 fusion
        # discipline)
        self._device_checked = False
        self._device_ok = True
        self._qprobe_checked = False
        self._rescore_hist = None  # cached handle; histogram() locks a registry
        # background rebuild state (shared with the rebuild worker)
        self._mu = threading.Lock()
        self._pending: Optional[_RebuildResult] = None
        self._rebuild_thread: Optional[threading.Thread] = None
        self._rebuild_dirty: Optional[set] = None  # slots churned post-snapshot
        # observability (engine thread; tests and the bench read it)
        self.stats: Dict[str, float] = {
            "rebuilds": 0, "swaps": 0, "swaps_torn": 0, "splits": 0,
            "merges": 0, "compactions": 0, "spills": 0, "max_pause_s": 0.0,
            "prefetch_stall_s": 0.0, "probe_hot": 0, "probe_cold": 0,
            "probe_spilled": 0, "quant_recalibrations": 0,
            "quant_chaos_aborts": 0,
        }

    # -- ingest ---------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.slot_of)

    def add(self, key: Any, vector: Any) -> None:
        vector = np.asarray(vector, dtype=np.float32).reshape(-1)
        assert vector.shape[0] == self.dim, (
            f"dim mismatch: {vector.shape[0]} != {self.dim}"
        )
        if key in self.slot_of:
            self.remove(key)
        slot = self._next_slot
        self._next_slot += 1
        self.slot_of[key] = slot
        self.key_of[slot] = key
        self._staged[slot] = vector

    def add_many(self, keys: List[Any], vectors: Any) -> None:
        vectors = np.asarray(vectors, dtype=np.float32).reshape(len(keys), self.dim)
        last = {k: i for i, k in enumerate(keys)}  # intra-batch dedup: last wins
        if len(last) != len(keys):
            keep = sorted(last.values())
            keys = [keys[i] for i in keep]
            vectors = vectors[keep]
        for k in [k for k in keys if k in self.slot_of]:
            self.remove(k)
        first = self._next_slot
        slots = list(range(first, first + len(keys)))
        self._next_slot += len(keys)
        self.slot_of.update(zip(keys, slots))
        self.key_of.update(zip(slots, keys))
        self._staged.update(zip(slots, vectors))

    def remove(self, key: Any) -> None:
        slot = self.slot_of.pop(key, None)
        if slot is None:
            return
        self.key_of.pop(slot, None)
        if self._staged.pop(slot, None) is not None:
            return
        self._staged_removals.append(slot)

    # -- churn application (the flush path: NO global rebuild) ----------------

    def _flush(self) -> None:
        if self._staged:
            slots = np.fromiter(self._staged.keys(), dtype=np.int64)
            vecs = np.stack(list(self._staged.values())).astype(np.float32)
            self._staged = {}
            if self._cents is None:
                self._untrained_slots.extend(slots.tolist())
                self._untrained_vecs.extend(vecs)
            else:
                self._place_rows(slots, vecs)
        if self._staged_removals:
            removals = self._staged_removals
            self._staged_removals = []
            for slot in removals:
                self._remove_slot(slot)

    def _place_rows(self, slots: np.ndarray, vecs: np.ndarray) -> None:
        """Assign a churn batch to its clusters and append per-cluster — the
        incremental path: only the touched clusters' blocks re-stage."""
        top2 = _assign_rows_np(vecs, self._cents)
        norms = np.sum(vecs * vecs, axis=1)
        order = np.argsort(top2[:, 0], kind="stable")
        cids = top2[order, 0]
        uniq, first_idx = np.unique(cids, return_index=True)
        bounds = np.append(first_idx, len(cids))
        dirty = self._rebuild_dirty
        for g, cid in enumerate(uniq):
            sel = order[bounds[g] : bounds[g + 1]]
            cid = int(cid)
            block = self._block(cid, create=True)
            first = block.append(slots[sel], vecs[sel], norms[sel])
            base = cid << 32
            for j, row in enumerate(sel):
                self._where[int(slots[row])] = base | (first + j)
            self.tiers.install(cid, block)
            if cid < len(self._drift):
                self._drift[cid] += len(sel)
        self._churn_since_train += len(slots)
        if dirty is not None:
            dirty.update(int(s) for s in slots)

    def _remove_slot(self, slot: int) -> None:
        loc = self._where.pop(slot, None)
        if loc is None:
            # still in the pre-train pen
            if slot in self._untrained_slots:
                i = self._untrained_slots.index(slot)
                del self._untrained_slots[i]
                del self._untrained_vecs[i]
            return
        cid, pos = loc >> 32, loc & 0xFFFFFFFF
        block = self._block(cid, create=False)
        if block is not None:
            block.invalidate(pos)
            self.tiers.install(cid, block)  # stale mirrors/blobs drop
        if cid < len(self._drift):
            self._drift[cid] += 1
        self._churn_since_train += 1
        if self._rebuild_dirty is not None:
            self._rebuild_dirty.add(slot)

    def _block(self, cid: int, *, create: bool) -> Optional[_ClusterPages]:
        """The cluster's host block, unspilling synchronously when frozen
        (churn unfreezes — the spill tier only holds idle clusters)."""
        with self.tiers._cv:
            block = self.tiers.pages.get(cid)
            frozen = block is None and cid in self.tiers.spilled
        if block is None and frozen:
            block = self.tiers.unspill(cid)
            if block is None:
                # the prefetcher is mid-stage on this cluster: WAIT for its
                # block rather than installing an empty one over it (which
                # would orphan every row the stage is about to land)
                block = self.tiers.wait_loaded(cid, timeout=30.0)
        if block is None and create:
            with self.tiers._cv:
                block = self.tiers.pages.get(cid)
                if block is None:
                    block = _ClusterPages(self.dim, quant=self._qblocks)
                    self.tiers.pages[cid] = block
                    self.tiers._cv.notify_all()
        return block

    # -- training / maintenance ----------------------------------------------

    def _initial_train(self) -> None:
        if not self._untrained_slots:
            return
        slots = np.asarray(self._untrained_slots, dtype=np.int64)
        vecs = np.stack(self._untrained_vecs).astype(np.float32)
        self._untrained_slots, self._untrained_vecs = [], []
        self.n_clusters = self._n_clusters_base
        rng = np.random.default_rng(0)
        cap = self.n_clusters * _TRAIN_SAMPLE_PER_CLUSTER
        sample = vecs if len(vecs) <= cap else vecs[rng.choice(len(vecs), cap, replace=False)]
        self._cents = _train_centroids(sample, self.n_clusters, self.train_iters)
        self._qcents = None
        self._grow_cluster_arrays(self.n_clusters)
        self._place_rows(slots, vecs)
        # splits bound the bucket width the probes pay for
        self._split_oversized_clusters()
        self._trained_total = len(slots)
        self._trained_sizes = np.array(
            [self._live_count(c) for c in range(self.n_clusters)], dtype=np.int64
        )
        self._drift = np.zeros(self.n_clusters, dtype=np.int64)
        self._churn_since_train = 0

    def _grow_cluster_arrays(self, n: int) -> None:
        if len(self._drift) < n:
            extra = n - len(self._drift)
            self._drift = np.concatenate([self._drift, np.zeros(extra, np.int64)])
            self._trained_sizes = np.concatenate(
                [self._trained_sizes, np.zeros(extra, np.int64)]
            )
            self._ewma = np.concatenate([self._ewma, np.zeros(extra, np.float64)])

    def _live_count(self, cid: int) -> int:
        with self.tiers._cv:
            block = self.tiers.pages.get(cid)
        return block.n_live if block is not None else 0

    @staticmethod
    def _cap_for(n_live: int, n_clusters: int) -> int:
        mean = max(1, n_live // max(n_clusters, 1))
        cap = 8
        while cap < (3 * mean + 1) // 2:
            cap *= 2
        return cap

    def _split_oversized_clusters(self) -> None:
        cap = self._cap_for(len(self.slot_of), self.n_clusters)
        limit = 2 * self._n_clusters_base
        for cid in range(self.n_clusters):
            if self.n_clusters >= limit:
                break
            block = self._block(cid, create=False)
            if block is None or block.n_live <= cap:
                continue
            self._split_cluster(cid)

    def _split_cluster(self, cid: int) -> None:
        """2-means split: half the members move to a NEW cluster — bounded
        per-cluster work, the locators of exactly the moved rows rewrite."""
        block = self._block(cid, create=False)
        if block is None or block.n_live < 2 * PAGE // 8:
            return
        slots, vecs, norms = block.live_rows()
        g1 = _two_means(vecs)
        if not g1.any() or g1.all():
            return
        new_cid = self.n_clusters
        self.n_clusters += 1
        self._grow_cluster_arrays(self.n_clusters)
        keep_block = _ClusterPages(self.dim, cap=int((~g1).sum()), quant=self._qblocks)
        keep_block.append(slots[~g1], vecs[~g1], norms[~g1])
        new_block = _ClusterPages(self.dim, cap=int(g1.sum()), quant=self._qblocks)
        new_block.append(slots[g1], vecs[g1], norms[g1])
        for j, s in enumerate(slots[~g1]):
            self._where[int(s)] = (cid << 32) | j
        for j, s in enumerate(slots[g1]):
            self._where[int(s)] = (new_cid << 32) | j
        cents = np.asarray(self._cents)
        new_cents = np.concatenate([cents, vecs[g1].mean(axis=0)[None, :]])
        new_cents[cid] = vecs[~g1].mean(axis=0)
        self._cents = new_cents
        self._qcents = None
        self.tiers.install(cid, keep_block)
        self.tiers.install(new_cid, new_block)
        self._trained_sizes[cid] = keep_block.n_live
        self._trained_sizes[new_cid] = new_block.n_live
        self._drift[cid] = 0
        self._drift[new_cid] = 0
        self.stats["splits"] += 1
        telemetry.stage_add("index.splits")

    def _maintain_cluster(self, cid: int) -> None:
        """Per-cluster drift response: compact, recenter, re-assign strays,
        split or merge — never a global pass."""
        block = self._block(cid, create=False)
        if block is None:
            return
        # every branch below may move self._cents rows IN PLACE (recenter,
        # dead-centroid, merge) — the int8 probe mirror cannot tell, so it
        # drops up front
        self._qcents = None
        if block.n_live < block.n // 2 and block.n >= PAGE:
            self._compact_cluster(cid, block)
            block = self._block(cid, create=False)
            if block is None:
                return
        slots, vecs, norms = block.live_rows()
        n_live = len(slots)
        if n_live == 0:
            self._cents[cid] = _DEAD_CENTROID  # never probed until a row lands again
            self._drift[cid] = 0
            self._trained_sizes[cid] = 0
            return
        self._cents[cid] = vecs.mean(axis=0)
        # re-assign: members now nearer another centroid move there
        top2 = _assign_rows_np(vecs, self._cents)
        stray = top2[:, 0] != cid
        small = n_live < max(4, self._cap_for(len(self.slot_of), self.n_clusters) // 16)
        if small and self.n_clusters > 2:
            # merge: drain the cluster entirely into each row's next-best home
            dest = np.where(top2[:, 0] == cid, top2[:, 1], top2[:, 0])
            self._move_rows(cid, slots, vecs, norms, dest)
            self._cents[cid] = _DEAD_CENTROID
            self.stats["merges"] += 1
            telemetry.stage_add("index.merges")
        elif stray.any() and stray.sum() < n_live:
            self._move_rows(
                cid, slots[stray], vecs[stray], norms[stray], top2[stray, 0]
            )
        block = self._block(cid, create=False)
        if block is not None and block.n_live > self._cap_for(
            len(self.slot_of), self.n_clusters
        ):
            self._split_cluster(cid)
        self._drift[cid] = 0
        self._trained_sizes[cid] = self._live_count(cid)
        if self._qblocks:
            block = self._block(cid, create=False)
            if block is not None:
                self._recalibrate_quant(cid, block)

    def _recalibrate_quant(self, cid: int, block: _ClusterPages) -> None:
        """Per-page scale recalibration on the maintenance path (churn hook):
        removals can leave a page's scale pinned by rows that are now dead,
        wasting code resolution on vectors the mask hides — recompute each
        scale over the LIVE rows only and re-derive the codes.

        The replacement codes + sidecars are computed entirely OFF to the
        side and installed by plain reference swaps; the ``quant`` chaos op
        fires BEFORE the install, so a kill mid-recalibration always leaves
        the old scales serving intact (the ladder-recovery contract the
        chaos test pins). Never stop-the-world: one cluster per call, riding
        the same bounded maintenance pass as compaction."""
        if not block.quant or block.n == 0:
            return
        from pathway_tpu.internals.chaos import get_chaos
        from pathway_tpu.internals.config import get_pathway_config

        cap = len(block.slots)
        n_pages = max(1, cap // PAGE)
        new_qvecs = np.zeros((cap, self.dim), dtype=np.int8)
        new_qscale = np.ones(n_pages, dtype=np.float32)
        new_qzero = np.zeros(n_pages, dtype=np.float32)
        for p in range(n_pages):
            lo, hi = p * PAGE, min((p + 1) * PAGE, cap)
            live = block.valid[lo:hi]
            rows = block.vecs[lo:hi]
            s = knn_quant.page_scale(rows[live] if live.any() else rows)
            new_qscale[p] = np.float32(s)
            # dead rows quantize at the live scale too (they may clip): the
            # validity mask hides them, and determinism beats their fidelity
            new_qvecs[lo:hi] = knn_quant.quantize_rows(rows, s)
        chaos = get_chaos()
        if chaos is not None and chaos.index_fault(
            "quant", get_pathway_config().process_id
        ):
            # injected mid-recalibration kill: the freshly computed sidecars
            # are DISCARDED before anything re-points — old scales serve on
            self.stats["quant_chaos_aborts"] += 1
            telemetry.stage_add("index.quant.chaos_aborts")
            _record_event("chaos_quant_kill", cluster=cid, generation=self.generation)
            return
        block.qvecs, block.qscale, block.qzero = new_qvecs, new_qscale, new_qzero
        block._drop_quant_caches()
        block.mutations += 1  # a mirror staged off the old codes must not install
        self.tiers.install(cid, block)  # stale hot mirrors of the old codes drop
        self.stats["quant_recalibrations"] += 1
        telemetry.stage_add("index.quant.recalibrations")
        _record_event("quant_swap", cluster=cid, generation=self.generation)

    def _move_rows(
        self,
        from_cid: int,
        slots: np.ndarray,
        vecs: np.ndarray,
        norms: np.ndarray,
        dest: np.ndarray,
    ) -> None:
        src = self._block(from_cid, create=False)
        for s in slots:
            loc = self._where.get(int(s))
            if loc is not None and src is not None and (loc >> 32) == from_cid:
                src.invalidate(loc & 0xFFFFFFFF)
        order = np.argsort(dest, kind="stable")
        uniq, first_idx = np.unique(dest[order], return_index=True)
        bounds = np.append(first_idx, len(order))
        for g, cid in enumerate(uniq):
            cid = int(cid)
            if cid == from_cid:
                continue
            sel = order[bounds[g] : bounds[g + 1]]
            target = self._block(cid, create=True)
            first = target.append(slots[sel], vecs[sel], norms[sel])
            base = cid << 32
            for j, row in enumerate(sel):
                self._where[int(slots[row])] = base | (first + j)
            self.tiers.install(cid, target)
        if src is not None:
            self.tiers.install(from_cid, src)

    def _compact_cluster(self, cid: int, block: _ClusterPages) -> None:
        slots, vecs, norms = block.live_rows()
        fresh = _ClusterPages(self.dim, cap=max(PAGE, len(slots)), quant=self._qblocks)
        if len(slots):
            fresh.append(slots, vecs, norms)
        base = cid << 32
        for j, s in enumerate(slots):
            self._where[int(s)] = base | j
        self.tiers.install(cid, fresh)
        self.stats["compactions"] += 1
        telemetry.stage_add("index.compactions")

    def _maintain(self) -> None:
        """The commit-boundary maintenance pass: bounded per-cluster work for
        drifted clusters; schedule/commit the background rebuild."""
        if self._cents is None:
            return
        if self._rebuild_inflight():
            # the pending generation supersedes any per-cluster fix; churning
            # blocks under the rebuild snapshot would be wasted work
            return
        t0 = time.perf_counter()
        did = 0
        threshold = _cluster_drift_threshold()
        drifted = np.nonzero(
            self._drift > np.maximum(8, threshold * np.maximum(self._trained_sizes, 1))
        )[0]
        for cid in drifted[:64]:  # bound one pass; the rest drift into the next
            self._maintain_cluster(int(cid))
            did += 1
        if did:
            telemetry.stage_add("index.maintain_clusters", float(did))
        if (
            self._churn_since_train
            >= _rebuild_drift_threshold() * max(self._trained_total, 1)
            and not self._rebuild_inflight()
        ):
            self._schedule_rebuild()
        self._maybe_spill()
        pause = time.perf_counter() - t0
        if did or pause > 1e-4:
            telemetry.stage_add("index.maintain_s", pause)
            self.stats["max_pause_s"] = max(self.stats["max_pause_s"], pause)

    def _maybe_spill(self) -> None:
        if self.tiers.spill_store is None or self._cents is None:
            return
        if self._batches < 4:
            return  # EWMA has no history yet: freezing now thrashes the probes
        eps = _spill_ewma_threshold()
        frozen = 0
        for cid in range(min(self.n_clusters, len(self._ewma))):
            if frozen >= 16:
                break
            if self._ewma[cid] >= eps or self._drift[cid] > 0:
                continue
            if self.tiers.residency(cid) != "cold":
                continue
            block = self._block(int(cid), create=False)
            if block is not None and block.n != block.n_live:
                # compact first: positions must survive the spill round-trip
                self._compact_cluster(int(cid), block)
            if self.tiers.spill(int(cid)):
                frozen += 1
        if frozen:
            self.stats["spills"] += frozen
            telemetry.stage_add("index.spills", float(frozen))

    # -- background rebuild ----------------------------------------------------

    def _rebuild_inflight(self) -> bool:
        with self._mu:
            return self._rebuild_thread is not None or self._pending is not None

    def _schedule_rebuild(self) -> None:
        """Snapshot the corpus (write-once rows + copied validity masks) and
        train the next generation off-thread; live churn keeps landing in the
        current generation AND in the dirty-set the swap reconciles."""
        from pathway_tpu.internals.chaos import get_chaos
        from pathway_tpu.internals.config import get_pathway_config

        # (vecs, norms, slots, valid, n) per resident cluster; frozen clusters
        # enter as ("spill", key) and the WORKER loads them off-thread — the
        # schedule pause must never be proportional to the spill tier (blobs
        # are retained until the swap's prefix sweep, so the reads are safe)
        snapshot: List[tuple] = []
        with self.tiers._cv:
            pages = dict(self.tiers.pages)
            spilled = dict(self.tiers.spilled)
        for cid in range(self.n_clusters):
            block = pages.get(cid)
            if block is None:
                key = spilled.get(cid)
                if key is not None:
                    snapshot.append(("spill", key))
                continue
            if block.n == 0:
                continue
            snapshot.append(
                (block.vecs, block.norms, block.slots, block.valid[: block.n].copy(), block.n)
            )
        if not snapshot:
            return
        chaos = get_chaos()
        rank = get_pathway_config().process_id
        if chaos is not None:
            chaos.begin_rebuild_attempt()
        generation = self.generation + 1
        self.stats["rebuilds"] += 1
        telemetry.stage_add("index.rebuilds")
        _record_event(
            "index_rebuild", generation=generation, clusters=len(snapshot),
            rows=len(self.slot_of),
        )
        # _rebuild_dirty is engine-thread-only (churn bookkeeping the swap
        # reconciles); only the thread handle itself is shared with the worker
        self._rebuild_dirty = set()
        thread = threading.Thread(
            target=self._rebuild_worker,
            args=(generation, snapshot, chaos, rank),
            name="pathway:ivf-rebuild",
            daemon=True,
        )
        with self._mu:
            self._rebuild_thread = thread
        thread.start()

    def _rebuild_worker(
        self, generation: int, snapshot: List[tuple], chaos: Any, rank: int
    ) -> None:
        result = _RebuildResult(generation)
        try:
            if chaos is not None:
                chaos.maybe_rebuild_kill(rank, generation=generation)
            spill_store = self.tiers.spill_store
            resolved: List[tuple] = []
            for entry in snapshot:
                if not isinstance(entry[0], str):
                    resolved.append(entry)  # resident (vecs, norms, slots, valid, n)
                    continue
                blob = spill_store.get(entry[1]) if spill_store is not None else None
                if blob is None:
                    raise TieredIndexError(
                        f"rebuild snapshot lost frozen cluster blob {entry[1]!r}"
                    )
                block = _ClusterPages.from_blob(self.dim, blob, quant=self._qblocks)
                resolved.append(
                    (block.vecs, block.norms, block.slots,
                     block.valid[: block.n].copy(), block.n)
                )
            snapshot = resolved
            rng = np.random.default_rng(generation)
            n_clusters = self._n_clusters_base
            cap = n_clusters * _TRAIN_SAMPLE_PER_CLUSTER
            total = sum(int(v.sum()) for _, _, _, v, _ in snapshot)
            # proportional per-cluster sample, streamed block by block
            parts = []
            for vecs, _norms, _slots, valid, n in snapshot:
                live = vecs[:n][valid]
                take = min(len(live), max(1, int(round(cap * len(live) / max(total, 1)))))
                if take >= len(live):
                    parts.append(live)
                else:
                    parts.append(live[rng.choice(len(live), take, replace=False)])
            sample = np.concatenate(parts) if parts else np.zeros((0, self.dim), np.float32)
            cents = _train_centroids(sample, n_clusters, self.train_iters, seed=generation)
            # stream-assign every live row, collecting the new membership
            members: Dict[int, List[tuple]] = {}
            for vecs, norms, slots, valid, n in snapshot:
                live = valid
                lv = vecs[:n][live]
                if not len(lv):
                    continue
                top2 = _assign_rows_np(lv, cents)
                ls, ln = slots[:n][live], norms[:n][live]
                for cid in np.unique(top2[:, 0]):
                    sel = top2[:, 0] == cid
                    members.setdefault(int(cid), []).append((ls[sel], lv[sel], ln[sel]))
            # materialize blocks (+ split badly oversized clusters)
            pages: Dict[int, _ClusterPages] = {}
            for cid, chunks in members.items():
                slots_c = np.concatenate([c[0] for c in chunks])
                vecs_c = np.concatenate([c[1] for c in chunks])
                norms_c = np.concatenate([c[2] for c in chunks])
                block = _ClusterPages(
                    self.dim, cap=max(PAGE, len(slots_c)), quant=self._qblocks
                )
                block.append(slots_c, vecs_c, norms_c)
                pages[cid] = block
            cents, pages = _rebuild_split_pass(
                cents, pages, self.dim, self._n_clusters_base, quant=self._qblocks
            )
            where: Dict[int, tuple] = {}
            trained = np.zeros(len(cents), dtype=np.int64)
            for cid, block in pages.items():
                trained[cid] = block.n_live
                for j in range(block.n):
                    where[int(block.slots[j])] = (cid, j)
            result.centroids = cents
            result.pages = pages
            result.where = where
            result.trained_sizes = trained
        except BaseException as exc:  # noqa: PWA202 (shipped typed to the engine thread via _pending.error — the swap path re-raises it as TieredIndexError)
            result.error = exc
        with self._mu:
            self._pending = result
            self._rebuild_thread = None

    def _maybe_swap(self) -> None:
        """The commit-boundary generation swap: atomic from any reader's view
        (everything re-points under one engine-thread pass; queries only run
        between commits). The OLD generation serves until this commits."""
        from pathway_tpu.internals.chaos import get_chaos
        from pathway_tpu.internals.config import get_pathway_config

        with self._mu:
            pending = self._pending
            if pending is None:
                return
            self._pending = None
        dirty = self._rebuild_dirty or set()
        self._rebuild_dirty = None
        if pending.error is not None:
            raise TieredIndexError(
                f"background index rebuild for generation {pending.generation} "
                f"failed: {pending.error!r}"
            ) from pending.error
        chaos = get_chaos()
        if chaos is not None and chaos.index_fault(
            "tier_swap_torn", get_pathway_config().process_id
        ):
            # injected torn swap: the pending generation is DISCARDED before
            # anything re-points — the old generation keeps serving, drift
            # still exceeds the threshold, and the next maintenance pass
            # schedules a fresh rebuild (the retry the chaos test asserts)
            self.stats["swaps_torn"] += 1
            telemetry.stage_add("index.swaps_torn")
            _record_event("index_swap", generation=pending.generation, torn=True)
            return
        t0 = time.perf_counter()
        new_tiers = TierManager(
            self.dim, pending.generation, budget_bytes=self._budget_bytes,
            device=self.device, spill_store=self.tiers.spill_store,
            quant=self._quant,
        )
        for cid, block in pending.pages.items():
            new_tiers.pages[cid] = block
        cents = pending.centroids
        where = pending.where
        trained = pending.trained_sizes
        # reconcile churn that landed after the snapshot
        dirty_adds: List[int] = []
        for slot in dirty:
            if slot not in self.key_of:
                # removed post-snapshot: flip it dead in the new generation
                loc = where.get(slot)
                if loc is not None:
                    block = new_tiers.pages.get(loc[0])
                    if block is not None:
                        block.invalidate(loc[1])
                continue
            if slot not in where:
                dirty_adds.append(slot)
        if dirty_adds:
            vecs = np.stack([self._vector_of(s) for s in dirty_adds]).astype(np.float32)
            top2 = _assign_rows_np(vecs, cents)
            norms = np.sum(vecs * vecs, axis=1)
            for i, slot in enumerate(dirty_adds):
                cid = int(top2[i, 0])
                block = new_tiers.pages.get(cid)
                if block is None:
                    block = _ClusterPages(self.dim, quant=self._qblocks)
                    new_tiers.pages[cid] = block
                pos = block.append(
                    np.asarray([slot]), vecs[i : i + 1], norms[i : i + 1]
                )
                where[slot] = (cid << 32) | pos
        # the swap: one engine-thread re-point (commit-boundary atomicity)
        old_tiers = self.tiers
        self._cents = cents
        self._qcents = None
        self._where = where
        self.n_clusters = len(cents)
        self.tiers = new_tiers
        self.generation = pending.generation
        self._trained_sizes = trained
        self._drift = np.zeros(len(cents), dtype=np.int64)
        self._ewma = np.zeros(len(cents), dtype=np.float64)
        self._trained_total = len(self.slot_of)
        self._churn_since_train = 0
        # re-arm the spill settling guard: the fresh generation's EWMA is all
        # zeros, and freezing before it has history would spill the hottest
        # working set right at the swap
        self._batches = 0
        # the old generation is retired: sweep EVERY blob under its prefix
        # (incl. ones whose entries were popped by unspill) — the frozen tier
        # must never accumulate one full copy per rebuild
        if old_tiers.spill_store is not None:
            with old_tiers._cv:
                old_tiers.spilled.clear()
            prefix = f"{old_tiers.spill_prefix}/gen{old_tiers.generation}"
            for key in old_tiers.spill_store.list(prefix):
                old_tiers.spill_store.delete(key)
        pause = time.perf_counter() - t0
        self.stats["swaps"] += 1
        self.stats["max_pause_s"] = max(self.stats["max_pause_s"], pause)
        telemetry.stage_add_many({"index.swaps": 1.0, "index.swap_s": pause})
        _record_event(
            "index_swap", generation=self.generation, pause_s=round(pause, 4),
            clusters=self.n_clusters,
        )

    def _vector_of(self, slot: int) -> np.ndarray:
        loc = self._where.get(slot)
        if loc is None:
            raise TieredIndexError(f"slot {slot} has no located vector")
        cid = loc >> 32
        block = self._block(cid, create=False)
        if block is None:
            raise TieredIndexError(f"cluster {cid} pages unavailable for slot {slot}")
        return block.vecs[loc & 0xFFFFFFFF]

    # -- search ---------------------------------------------------------------

    def _quant_cents(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The int8 coarse-probe mirror: per-centroid symmetric codes (a
        centroid is a one-row page), exact fp32 ``|c|^2``, padded to a pow2
        centroid count with ``cn = +inf`` rows so affinity on pads is -inf
        and the device kernel's jit cache stays O(log) over (C, q) buckets."""
        if self._qcents is None:
            cents = np.asarray(self._cents, dtype=np.float32)
            c_now = len(cents)
            c_pad = next_pow2(max(8, c_now))
            codes = np.zeros((c_pad, self.dim), dtype=np.int8)
            scales = np.ones(c_pad, dtype=np.float32)
            cn = np.full(c_pad, np.inf, dtype=np.float32)
            m = np.max(np.abs(cents), axis=1)
            scales[:c_now] = np.where(m > 0.0, m / 127.0, 1.0)
            codes[:c_now] = np.clip(
                np.rint(cents / scales[:c_now, None]), -127, 127
            ).astype(np.int8)
            cn[:c_now] = np.sum(cents * cents, axis=1)
            self._qcents = (codes, scales, cn)
        return self._qcents

    def _effective_n_probe(self) -> int:
        """Brownout-aware probe count (same contract as the untiered store)."""
        from pathway_tpu.engine.brownout import get_brownout

        return max(1, self.n_probe >> get_brownout().nprobe_shift())

    def _prepare_search(self) -> bool:
        self._flush()
        if self._cents is None:
            self._initial_train()
        self._maybe_swap()
        self._maintain()
        # a swap scheduled by THIS maintain pass is taken at the NEXT commit
        # boundary — queries in between keep the old generation (fence-riding)
        return self._cents is not None

    def _touch(self, probed: np.ndarray, counts: np.ndarray, allow_promote: bool) -> None:
        alpha = _ewma_alpha()
        if len(self._ewma) < self.n_clusters:
            self._grow_cluster_arrays(self.n_clusters)
        self._ewma *= 1.0 - alpha
        share = counts / max(counts.sum(), 1)
        self._ewma[probed] += alpha * share * len(probed)
        if not allow_promote:
            return
        to_promote = [
            int(c) for c in probed if self.tiers.residency(int(c)) in ("cold", "spilled")
        ]
        if not to_promote:
            return
        if self._prefetch_on:
            self._prefetcher.request(self.tiers, to_promote, promote=True)
        else:
            for cid in to_promote:
                if self.tiers.residency(cid) == "spilled":
                    self.tiers.unspill(cid)
                self.tiers.promote(cid)

    def _scoring_block(self, cid: int, res_at_probe: str) -> Optional[_ClusterPages]:
        """The block for scoring. A cluster that was FROZEN at probe time
        observes its surfaced stall — ~0 when the prefetch overlap window hid
        the load entirely (exactly what the stall histogram should say), the
        real wait when it did not."""
        if res_at_probe == "spilled":
            t0 = time.perf_counter()
            block = self.tiers.wait_loaded(cid, timeout=0.05)
            if block is None:
                block = self.tiers.unspill(cid)
            if block is None:
                # a slow stage (large cluster / slow object store) is still in
                # flight: wait it out — silently skipping the cluster would
                # change results, the one thing residency must never do
                block = self.tiers.wait_loaded(cid, timeout=30.0)
                if block is None and self.tiers.residency(cid) != "absent":
                    raise TieredIndexError(
                        f"cluster {cid} pages never arrived from the spill "
                        "tier (stage wedged or object store unreachable)"
                    )
            stall = time.perf_counter() - t0
            self.stats["prefetch_stall_s"] += stall
            from pathway_tpu.engine.profile import histogram

            histogram("pathway_ivf_prefetch_stall_seconds").observe(stall)
            telemetry.stage_add("index.prefetch_stall_s", stall)
            return block
        res = self.tiers.residency(cid)
        if res in ("hot", "cold"):
            with self.tiers._cv:
                return self.tiers.pages.get(cid)
        if res == "absent":
            return None  # empty cluster: no pages anywhere, nothing to score
        block = self.tiers.wait_loaded(cid, timeout=0.05)
        return block if block is not None else self.tiers.unspill(cid)

    def search_batch(self, queries: Any, k: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        ready = self._prepare_search()
        q = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
        nq = q.shape[0]
        k_eff = max(1, k)
        if not ready:
            return (
                np.full((nq, k_eff), -np.inf, dtype=np.float32),
                np.full((nq, k_eff), -1, dtype=np.int64),
                np.zeros((nq, k_eff), dtype=bool),
            )
        from pathway_tpu.engine.brownout import get_brownout

        self._batches += 1
        shift = get_brownout().nprobe_shift()
        n_probe = max(1, min(self.n_probe >> shift, self.n_clusters))
        cents = self._cents
        quant = self._qblocks
        device_hot = jax.default_backend() != "cpu"
        q_codes = q_scales = qf_codes = None
        if quant:
            # the quantized tower: int8 coarse probe + int8 page scoring
            # build a shortlist; the exact fp32 rescore epilogue below is
            # the ONLY thing that computes returned scores
            q_codes, q_scales = knn_quant.quantize_queries(q)
            qf_codes = q_codes.astype(np.float32)
            qc_codes, qc_scales, qc_n = self._quant_cents()
            aff = None
            if device_hot and self._device_ok:
                q_pad = next_pow2(max(8, nq))
                pq = np.zeros((q_pad, self.dim), dtype=np.int8)
                pq[:nq] = q_codes
                ps = np.ones(q_pad, dtype=np.float32)
                ps[:nq] = q_scales
                aff = np.asarray(
                    knn_quant.quant_probe_kernel(
                        jnp.asarray(qc_codes), jnp.asarray(qc_scales),
                        jnp.asarray(qc_n), jnp.asarray(pq), jnp.asarray(ps),
                    )
                )[:nq, : self.n_clusters]
                if not self._qprobe_checked:
                    # first-use parity vs the host twin: the int8 dot is
                    # exact integers in f32, so any deviation is a backend
                    # arithmetic lie — downgrade everything to host
                    self._qprobe_checked = True
                    host_aff = knn_quant.coarse_affinity(
                        q_codes, q_scales, qc_codes, qc_scales, qc_n
                    )[:, : self.n_clusters]
                    if not np.array_equal(aff, host_aff):
                        self._device_ok = False
                        telemetry.stage_add("index.device_parity_rejects")
                        aff = host_aff
            if aff is None:
                aff = knn_quant.coarse_affinity(
                    q_codes, q_scales, qc_codes, qc_scales, qc_n
                )[:, : self.n_clusters]
        else:
            cn = np.sum(cents * cents, axis=1)
            aff = 2.0 * q @ cents.T - cn[None, :]
        if n_probe < self.n_clusters:
            probe = np.argpartition(aff, -n_probe, axis=1)[:, -n_probe:]
        else:
            probe = np.broadcast_to(
                np.arange(self.n_clusters), (nq, self.n_clusters)
            ).copy()
        probed, counts = np.unique(probe, return_counts=True)
        # residency census AT PROBE TIME — the hit rate reflects where the
        # coarse quantizer found each cluster, before any staging moves it
        at_probe = {int(c): self.tiers.residency(int(c)) for c in probed}
        n_hot = sum(1 for r in at_probe.values() if r == "hot")
        n_cold = sum(1 for r in at_probe.values() if r == "cold")
        n_spilled = sum(1 for r in at_probe.values() if r == "spilled")
        self.stats["probe_hot"] += n_hot
        self.stats["probe_cold"] += n_cold
        self.stats["probe_spilled"] += n_spilled
        telemetry.stage_add_many({
            "index.probes": float(len(probed)),
            "index.probe_hot": float(n_hot),
            "index.probe_cold": float(n_cold),
            "index.probe_spilled": float(n_spilled),
        })
        # a browned-out probe set must never thrash the tiers (rung 2 is
        # half the clusters — promoting for it evicts the real working set)
        self._touch(probed, counts, allow_promote=shift == 0)
        # async prefetch: name every probed frozen cluster BEFORE scoring, so
        # the load overlaps the hot/cold scoring work below
        frozen = [cid for cid, r in at_probe.items() if r == "spilled"]
        if frozen and self._prefetch_on:
            self._prefetcher.request(self.tiers, frozen, promote=False)
        qn = np.sum(q * q, axis=1)
        # cluster-major scoring, resident clusters first (the overlap window)
        order_ids = sorted(
            at_probe, key=lambda c: 0 if at_probe[c] in ("hot", "cold") else 1
        )
        blocks: Dict[int, _ClusterPages] = {}
        widths: Dict[int, int] = {}
        for cid in order_ids:
            block = self._scoring_block(cid, at_probe[cid])
            if block is not None and block.n > 0:
                blocks[cid] = block
                widths[cid] = block.n
        # per-query candidate layout (same shape discipline as _search_numpy)
        pc = np.array(
            [[widths.get(int(c), 0) for c in row] for row in probe], dtype=np.int64
        )
        col0 = np.zeros_like(pc)
        np.cumsum(pc[:, :-1], axis=1, out=col0[:, 1:])
        W = int(pc.sum(axis=1).max()) if nq else 0
        if W == 0:
            return (
                np.full((nq, k_eff), -np.inf, dtype=np.float32),
                np.full((nq, k_eff), -1, dtype=np.int64),
                np.zeros((nq, k_eff), dtype=bool),
            )
        buf_s = np.full((nq, W), -np.inf, dtype=np.float32)
        buf_i = np.full((nq, W), -1, dtype=np.int64)
        flatc = probe.ravel()
        flatq = np.repeat(np.arange(nq), probe.shape[1])
        flats = col0.ravel()
        order = np.argsort(flatc, kind="stable")
        fc, fq, fs = flatc[order], flatq[order], flats[order]
        uniq, first = np.unique(fc, return_index=True)
        bounds = np.append(first, len(fc))
        for g in range(len(uniq)):
            cid = int(uniq[g])
            block = blocks.get(cid)
            if block is None:
                continue
            sel = slice(bounds[g], bounds[g + 1])
            qs, ds = fq[sel], fs[sel]
            n = block.n
            # a cluster probed by EVERY query (always true for solo
            # queries) needs no per-block gather: within a run qs ascends,
            # so len(qs) == nq means qs == arange(nq) and the fancy-index
            # copies are identity selections
            if len(qs) == nq:
                g_q, g_qn = q, qn
                g_qf, g_qsc = qf_codes, q_scales
            else:
                g_q, g_qn = q[qs], qn[qs]
                g_qf = qf_codes[qs] if quant else None
                g_qsc = q_scales[qs] if quant else None
            mirror = None
            if device_hot and self._device_ok:
                with self.tiers._cv:
                    mirror = self.tiers.hot.get(cid)

            def host_scores() -> np.ndarray:
                s = knn_quant.host_metric_scores(
                    g_q, block.vecs[:n], block.norms[:n], g_qn, self.metric
                )
                s += block.maskadd(n)[None, :]
                return s

            def host_scores_quant() -> np.ndarray:
                # approximate int8 affinities (shortlist only): exact
                # integer dot via the cached f32 cast (BLAS), dequantized
                # by the page scales, with the fused mask-norms epilogue.
                # The l2sq body is inlined from knn_quant.approx_scores in
                # bitwise lockstep — two python frames per block were a
                # measurable share of solo-query latency
                if (
                    self.metric == "l2sq"
                    and self.dim <= knn_quant._INT8_EXACT_DIM_LIMIT
                ):
                    dot = g_qf @ block.qvecs_f32()[:n].T
                    dot *= (2.0 * g_qsc)[:, None] * block.qsrow(n)[None, :]
                    dot += block.negn(n)[None, :]
                    return dot
                if self.metric == "l2sq":
                    return knn_quant.approx_scores(
                        g_qf, g_qsc, g_qn,
                        block.qvecs_f32()[:n], block.qsrow(n),
                        block.norms[:n], self.metric,
                        negnorm=block.negn(n),
                    )
                return knn_quant.approx_scores(
                    g_qf, g_qsc, g_qn,
                    block.qvecs_f32()[:n], block.qsrow(n), block.norms[:n],
                    self.metric, maskadd=block.maskadd(n),
                )

            host_fn = host_scores_quant if quant else host_scores
            if mirror is not None and mirror is not True:
                if quant:
                    g_n = len(qs)
                    g_pad = next_pow2(max(8, g_n))
                    gq = np.zeros((g_pad, self.dim), dtype=np.int8)
                    gq[:g_n] = q_codes[qs]
                    gs = np.ones(g_pad, dtype=np.float32)
                    gs[:g_n] = q_scales[qs]
                    gn = np.zeros(g_pad, dtype=np.float32)
                    gn[:g_n] = qn[qs]
                    sub = np.asarray(
                        knn_quant.quant_score_block_kernel(
                            mirror[0], mirror[1], mirror[2], mirror[3],
                            jnp.asarray(gq), jnp.asarray(gs), jnp.asarray(gn),
                            self.metric,
                        )
                    )[:g_n, :n]
                else:
                    sub = np.asarray(
                        _score_block_kernel(
                            mirror[0], mirror[1], mirror[2],
                            jnp.asarray(q[qs]), self.metric,
                        )
                    )[:, :n]
                if not self._device_checked:
                    # first-use parity probe: the device path must agree with
                    # the host path byte-for-byte or it never scores again
                    # (under int8 the dots are exact integers in f32, so
                    # parity is arithmetic — the probe just proves it)
                    self._device_checked = True
                    if not np.array_equal(sub, host_fn()):
                        self._device_ok = False
                        telemetry.stage_add("index.device_parity_rejects")
                        sub = host_fn()
            else:
                sub = host_fn()
            cols = ds[:, None] + np.arange(n)[None, :]
            buf_s[qs[:, None], cols] = sub
            buf_i[qs[:, None], cols] = np.where(block.valid[:n], block.slots[:n], -1)
        if quant:
            scores, idx = self._exact_rescore(
                q, qn, buf_s, buf_i, blocks, k_eff, W, probe, col0
            )
        else:
            scores, idx = topk_rows(buf_s, buf_i, k_eff)
        valid = np.isfinite(scores)
        # per-batch tier observability (hit rate, occupancy)
        from pathway_tpu.engine.profile import histogram

        total = n_hot + n_cold + n_spilled
        if total > 0:
            histogram("pathway_ivf_tier_hit_ratio").observe(
                (n_hot + n_cold) / total
            )
        histogram("pathway_ivf_tier_occupancy_ratio").observe(self.tiers.occupancy())
        return scores, idx, valid

    def _exact_rescore(
        self,
        q: np.ndarray,
        qn: np.ndarray,
        buf_s: np.ndarray,
        buf_i: np.ndarray,
        blocks: Dict[int, _ClusterPages],
        k_eff: int,
        width: int,
        probe: "np.ndarray | None" = None,
        col0: "np.ndarray | None" = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The exact fp32 rescore epilogue: take the int8 shortlist
        ``max(k, PATHWAY_IVF_RESCORE_K)`` deep (clamped to the candidate
        width), gather the fp32 source rows of every shortlisted slot, and
        recompute their scores through :func:`knn_quant.rescore_pairs` — the
        pinned epilogue. The top-k the caller sees ranks by EXACT scores
        only; approximate scores never leave the store."""
        nq = q.shape[0]
        depth = min(width, max(k_eff, rescore_k()))
        # shortlist SELECTION only — no sort: the exact scores below are the
        # ranking, so a bare argpartition beats the full topk_rows contract
        # (1-D plain fancy indexing for the solo case: take_along_axis
        # builds index grids whose overhead is visible at these sizes)
        if nq == 1:
            part = np.argpartition(buf_s[0], -depth)[-depth:][None, :]
            ap_i = buf_i[0][part[0]][None, :]
        else:
            part = np.argpartition(buf_s, -depth, axis=1)[:, -depth:]
            ap_i = np.take_along_axis(buf_i, part, axis=1)
        flat = ap_i.ravel()
        if nq == 1 and probe is not None:
            # solo fast path: a shortlist COLUMN maps to its owning
            # (cluster, row) through the buffer layout itself — col0 holds
            # each probed cluster's start column, so one searchsorted
            # replaces any per-slot lookup (side="right" lands past every
            # zero-width cluster sharing a start). Dead rows carry id -1 in
            # buf_i; their cluster is forced to -1 so the gather skips them.
            j = np.searchsorted(col0[0], part[0], side="right") - 1
            cids = probe[0][j]
            poss = part[0] - col0[0][j]
            dead = flat < 0
            if dead.any():
                cids = np.where(dead, np.int64(-1), cids)
        else:
            # batch path: slot -> (cid, pos) in one C-level pass — _where
            # packs both into one int ((cid << 32) | pos, -1 for a miss or
            # a padding slot), so fromiter(map(get, ...)) replaces a python
            # loop that was a measurable share of query latency; the
            # arithmetic >> keeps -1 (miss) negative, and a miss's pos bits
            # are never consumed (its run is skipped with sok cleared)
            packed = np.fromiter(
                map(self._where.get, flat.tolist(), _repeat(-1)),
                dtype=np.int64, count=flat.size,
            )
            cids = packed >> 32
            poss = packed & 0xFFFFFFFF
        # group by owning cluster via one argsort, gather each run with a
        # contiguous slice copy, and rescore IN SORTED ORDER — rescore_pairs
        # is row-independent (pairwise einsum), so a final scatter restores
        # shortlist order bit-for-bit while the per-cluster work drops from
        # a boolean mask + fancy scatter to a slice assignment
        order = np.argsort(cids, kind="stable")
        sc, sp = cids[order], poss[order]
        sok = sc >= 0
        # np.empty, not zeros: rows of skipped runs stay garbage but their
        # scores are forced to -inf below before anything ranks on them
        svecs = np.empty((flat.size, self.dim), dtype=np.float32)
        snorms = np.empty(flat.size, dtype=np.float32)
        neq = np.empty(sc.size, dtype=bool)
        neq[0] = True
        np.not_equal(sc[1:], sc[:-1], out=neq[1:])
        starts = np.flatnonzero(neq)
        ends = np.append(starts[1:], sc.size)
        for a, b in zip(starts.tolist(), ends.tolist()):
            cid = int(sc[a])
            if cid < 0:
                continue
            blk = blocks.get(cid)
            if blk is None:
                blk = self._block(cid, create=False)
            if blk is None:
                sok[a:b] = False
                continue
            rows = sp[a:b]
            np.take(blk.vecs, rows, axis=0, out=svecs[a:b])
            np.take(blk.norms, rows, out=snorms[a:b])
        if nq == 1:
            # solo query: every pair shares the one query row — np.repeat
            # builds the contiguous copy ~2x faster than a fancy index of
            # an all-zeros qis (and contiguity matters: einsum over a
            # stride-0 broadcast view measured SLOWER than the copy)
            qg = np.repeat(q, flat.size, axis=0)
            qng = np.repeat(qn, flat.size)
        else:
            qis = np.repeat(np.arange(nq), depth)[order]
            qg, qng = q[qis], qn[qis]
        sexact = knn_quant.rescore_pairs(qg, svecs, snorms, qng, self.metric)
        n_ok = int(sok.sum())
        if n_ok < flat.size:
            sexact = np.where(sok, sexact, np.float32(-np.inf))
        exact = np.empty(flat.size, dtype=np.float32)
        exact[order] = sexact
        exact = exact.reshape(nq, depth)
        hist = self._rescore_hist
        if hist is None:
            from pathway_tpu.engine.profile import histogram

            hist = self._rescore_hist = histogram("pathway_ivf_quant_rescore_depth")
        hist.observe(float(depth))
        telemetry.stage_add_many({
            "index.quant.batches": 1.0,
            "index.quant.rescored_pairs": float(n_ok),
        })
        if depth < k_eff:
            # starved shortlist (width < k): topk_rows pads to the contract
            return topk_rows(exact, ap_i, k_eff)
        # the common tail: depth >= k, arrays are (nq, depth) with depth
        # small — a stable full argsort beats topk_rows' partition+sort
        # ceremony at this size, and stability keeps the ranking a pure
        # function of (exact scores, shortlist order), so residency moves
        # (which leave both bitwise-identical) cannot reorder ties
        if nq == 1:
            e = exact[0]
            top = np.argsort(-e, kind="stable")[:k_eff]
            out_s = e[top][None, :]
            out_i = ap_i[0][top].astype(np.int64, copy=False)[None, :]
        else:
            top = np.argsort(-exact, axis=1, kind="stable")[:, :k_eff]
            out_s = np.take_along_axis(exact, top, axis=1)
            out_i = np.take_along_axis(ap_i, top, axis=1).astype(
                np.int64, copy=False
            )
        out_i[~np.isfinite(out_s)] = -1
        return out_s, out_i

    # -- export / lifecycle ----------------------------------------------------

    def export_rows(self) -> Tuple[List[Any], np.ndarray]:
        """Every live (key, vector) pair — the rebuildable-descriptor
        contract shared with the dense stores."""
        self._flush()
        keys: List[Any] = []
        parts: List[np.ndarray] = []
        if self._untrained_slots:
            keys.extend(self.key_of[s] for s in self._untrained_slots)
            parts.extend(v[None, :] for v in self._untrained_vecs)
        seen_cids = set(loc >> 32 for loc in self._where.values())
        for cid in sorted(seen_cids):
            block = self._block(cid, create=False)
            if block is None:
                continue
            slots, vecs, _norms = block.live_rows()
            for j, s in enumerate(slots):
                key = self.key_of.get(int(s))
                if key is not None:
                    keys.append(key)
                    parts.append(vecs[j : j + 1])
        if not parts:
            return keys, np.zeros((0, self.dim), dtype=np.float32)
        return keys, np.concatenate(parts)

    def iter_export_fragments(
        self, max_rows: int
    ) -> "Iterator[Tuple[List[Any], np.ndarray]]":
        """Bounded-memory export: yield ``(keys, vectors)`` chunks of at most
        ``max_rows`` rows, walking untrained staging and then the cluster
        pages WITHOUT concatenating the corpus — peak memory is one fragment
        plus one resident page, however large the index (the replica-feed
        bootstrap contract; spill-tier pages fault in one at a time through
        ``_block`` exactly like a cold probe would)."""
        self._flush()
        max_rows = max(1, int(max_rows))
        keys: List[Any] = []
        parts: List[np.ndarray] = []
        n_buf = 0

        def drain() -> Tuple[List[Any], np.ndarray]:
            nonlocal keys, parts, n_buf
            out = (
                keys,
                np.concatenate(parts)
                if parts
                else np.zeros((0, self.dim), dtype=np.float32),
            )
            keys, parts, n_buf = [], [], 0
            return out

        if self._untrained_slots:
            for s, v in zip(self._untrained_slots, self._untrained_vecs):
                keys.append(self.key_of[s])
                parts.append(np.asarray(v, dtype=np.float32)[None, :])
                n_buf += 1
                if n_buf >= max_rows:
                    yield drain()
        seen_cids = set(loc >> 32 for loc in self._where.values())
        for cid in sorted(seen_cids):
            block = self._block(cid, create=False)
            if block is None:
                continue
            slots, vecs, _norms = block.live_rows()
            for j, s in enumerate(slots):
                key = self.key_of.get(int(s))
                if key is None:
                    continue
                keys.append(key)
                parts.append(vecs[j : j + 1])
                n_buf += 1
                if n_buf >= max_rows:
                    yield drain()
        if n_buf:
            yield drain()

    @property
    def quant(self) -> str:
        """The resolved quantization mode ("off" | "int8")."""
        return self._quant

    def quant_state(self) -> Dict[str, Any]:
        """Quantization descriptor payload for replication/checkpoint: the
        mode plus every resident cluster's per-page scale/zero-point
        sidecars (copies — the descriptor must not alias live arrays). A
        replica installs this alongside ``export_rows`` so restore is exact:
        same mode, same sidecars, bit-identical codes after re-append."""
        if self._quant == "off":
            return {"mode": "off"}
        self._flush()
        clusters: Dict[int, Dict[str, Any]] = {}
        with self.tiers._cv:
            pages = dict(self.tiers.pages)
        for cid, block in pages.items():
            if block is None or block.n == 0 or not block.quant:
                continue
            clusters[int(cid)] = {
                "rows": int(block.n),
                "qscale": block.qscale.copy(),
                "qzero": block.qzero.copy(),
            }
        return {"mode": self._quant, "dtype": "int8", "clusters": clusters}

    def quant_recall_audit(self, queries: Any, k: int = 10) -> float:
        """The quantized-vs-exact honesty key: recall@k of the quantized
        tower against a full exact fp32 scan of the live corpus (audit path,
        never serving). Observed on the ``pathway_ivf_quant_recall_ratio``
        histogram so /metrics carries it."""
        q = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
        _scores, idx, valid = self.search_batch(q, k)
        keys, vecs = self.export_rows()
        if not keys:
            return 1.0
        norms = np.sum(vecs * vecs, axis=1)
        qn = np.sum(q * q, axis=1)
        exact = knn_quant.host_metric_scores(q, vecs, norms, qn, self.metric)
        kk = min(k, len(keys))
        hits = 0
        for i in range(q.shape[0]):
            top = np.argpartition(exact[i], -kk)[-kk:]
            truth = {keys[j] for j in top}
            got = {
                self.key_of.get(int(s))
                for s, v in zip(idx[i], valid[i]) if v and s >= 0
            }
            hits += len(truth & got)
        ratio = hits / max(q.shape[0] * kk, 1)
        from pathway_tpu.engine.profile import histogram

        histogram("pathway_ivf_quant_recall_ratio").observe(ratio)
        telemetry.stage_add("index.quant.recall_audits")
        return ratio

    def attach_spill(self, store: Any, prefix: str = "ivf-spill") -> None:
        """Enable the frozen tier behind any persistence ``ObjectStore``."""
        with self.tiers._cv:
            self.tiers.spill_store = store
            self.tiers.spill_prefix = prefix

    def tier_stats(self) -> Dict[str, Any]:
        counts = self.tiers.counts()
        out = dict(self.stats)
        out.update(counts)
        out["generation"] = self.generation
        out["n_clusters"] = self.n_clusters
        out["quant"] = self._quant
        out["hot_bytes"] = self.tiers.hot_bytes
        out["budget_bytes"] = self._budget_bytes
        out["occupancy"] = self.tiers.occupancy()
        out["rebuild_inflight"] = self._rebuild_inflight()
        return out

    def close(self) -> None:
        """Join the worker threads (tests and long-lived servers); the store
        remains usable — workers re-spawn lazily."""
        self._prefetcher.close()
        with self._mu:
            thread = self._rebuild_thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=30.0)


def hbm_budget_bytes_env() -> int:
    """Alias kept separate so the ctor default reads the env exactly once."""
    return hbm_budget_bytes()


def _rebuild_split_pass(
    cents: np.ndarray,
    pages: Dict[int, _ClusterPages],
    dim: int,
    base_clusters: int,
    *,
    quant: bool = False,
) -> Tuple[np.ndarray, Dict[int, _ClusterPages]]:
    """Split oversized clusters of a freshly-built generation (bounds the
    per-probe page budget like the untiered store's train-time splits)."""
    total = sum(b.n_live for b in pages.values())
    cap = TieredIvfKnnStore._cap_for(total, max(len(cents), 1))
    limit = 2 * base_clusters
    cents_list = [cents]
    for _ in range(6):
        n_now = sum(c.shape[0] for c in cents_list)
        over = [
            cid for cid, b in pages.items() if b.n_live > cap
        ]
        if not over or n_now + len(over) > limit:
            break
        for cid in over:
            block = pages[cid]
            slots, vecs, norms = block.live_rows()
            g1 = _two_means(vecs)
            if not g1.any() or g1.all():
                continue
            new_cid = sum(c.shape[0] for c in cents_list)
            keep = _ClusterPages(dim, cap=max(PAGE, int((~g1).sum())), quant=quant)
            keep.append(slots[~g1], vecs[~g1], norms[~g1])
            moved = _ClusterPages(dim, cap=max(PAGE, int(g1.sum())), quant=quant)
            moved.append(slots[g1], vecs[g1], norms[g1])
            pages[cid] = keep
            pages[new_cid] = moved
            all_c = np.concatenate(cents_list)
            all_c[cid] = vecs[~g1].mean(axis=0)
            cents_list = [all_c, vecs[g1].mean(axis=0)[None, :]]
    return np.concatenate(cents_list).astype(np.float32), pages


def _record_event(kind: str, **details: Any) -> None:
    try:
        from pathway_tpu.engine.profile import get_flight_recorder

        get_flight_recorder().record_event(kind, **details)
    except Exception:  # noqa: PWA202 (observability must never kill the serving path; no typed contract rides through here)
        pass
