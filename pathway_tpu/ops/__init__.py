"""Dense TPU kernels: KNN search, segment reductions, hashing helpers."""
