"""Brute-force & LSH KNN over an HBM-resident vector store.

TPU-native replacement for the reference's engine KNN: ``src/external_integration/
brute_force_knn_integration.rs:113`` (ndarray matmul + partial sort via ``src/mat_mul.rs:5``)
and ``stdlib/ml/classifiers/_knn_lsh.py`` (random-projection LSH). Design:

- the vector store is ONE dense ``(capacity, dim)`` jax array in HBM with a validity mask;
  capacity doubles amortized so jit re-traces are rare (static shapes for XLA);
- search = one jit'd kernel: ``queries @ data.T`` on the MXU (bf16 accumulate-f32 by default)
  fused with masking + ``lax.top_k`` — XLA fuses the elementwise mask into the matmul epilogue;
- adds/removes stage host-side and flush as one scatter (``data.at[slots].set(batch)``) per
  commit, so ingest cost is one device round-trip per batch, not per row.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pathway_tpu.internals.shapes import next_pow2 as _next_pow2_shared


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _search_kernel(
    data: jax.Array, valid: jax.Array, norms: jax.Array, queries: jax.Array, k: int, metric: str
) -> Tuple[jax.Array, jax.Array]:
    """Top-k over the full store: (q, cap) score matrix on the MXU, masked, top_k."""
    scores = jnp.dot(
        queries, data.T, preferred_element_type=jnp.float32
    )  # (q, cap) — MXU path (bf16 operands accumulate in f32)
    # query norms in f32 regardless of storage dtype: a bf16 self-product loses
    # ~3 decimal digits, which skews l2 distances near ties
    qf = queries.astype(jnp.float32)
    if metric == "l2sq":
        qn = jnp.sum(qf * qf, axis=1, keepdims=True)
        scores = -(qn + norms[None, :] - 2.0 * scores)  # -(||q-d||^2), higher is better
    elif metric == "cos":
        qn = jnp.linalg.norm(qf, axis=1, keepdims=True)
        scores = scores / jnp.maximum(qn * jnp.sqrt(norms)[None, :], 1e-30)
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    top_scores, top_idx = lax.top_k(scores, k)
    return top_scores, top_idx


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (>= 1): the shape-bucketing unit — every
    jit'd search/scatter kernel sees pow2-padded batch shapes so its cache is
    keyed by O(log) distinct buckets instead of one entry per raw size.
    Delegates to the ONE shared rule in ``internals/shapes.py`` (also used by
    the encoder and segment reductions)."""
    return _next_pow2_shared(n, floor=1)


def pad_queries_pow2(q_dev: jax.Array, dim: int) -> Tuple[jax.Array, int]:
    """Pad a device query batch with zero rows to the next pow2 count (floor
    8) — the ONE bucketing policy shared by the dense and IVF search paths.
    Returns (padded batch, original row count) for slicing results back."""
    nq = q_dev.shape[0]
    q_pad = next_pow2(max(8, nq))
    if q_pad != nq:
        q_dev = jnp.concatenate([q_dev, jnp.zeros((q_pad - nq, dim), q_dev.dtype)])
    return q_dev, nq


def kernel_cache_sizes() -> Dict[str, int]:
    """Entries in each search kernel's jit cache — the recompile counter the
    bench artifact reports and the jit-cache regression tests bound."""
    from pathway_tpu.ops import knn_ivf

    def sz(fn: Any) -> int:
        try:
            return int(fn._cache_size())
        except Exception:
            return -1

    from pathway_tpu.ops import knn_quant, knn_tiers

    return {
        "dense_search": sz(_search_kernel),
        "ivf_query": sz(knn_ivf._ivf_query_fused),
        "ivf_pack": sz(knn_ivf._pack_pages_kernel),
        # tiered store: assignment batches and hot blocks pad to pow2, so
        # both caches must stay O(log) over ragged cluster sizes (an unpadded
        # shape per cluster was an 18x ingest regression)
        "tiered_assign": sz(knn_ivf._assign2_kernel),
        "tiered_score": sz(knn_tiers._score_block_kernel),
        # quantized tower: int8 coarse probe and block scorer (pow2-padded
        # centroid counts / block capacities / query buckets, same O(log)
        # cache discipline)
        "quant_probe": sz(knn_quant.quant_probe_kernel),
        "quant_score": sz(knn_quant.quant_score_block_kernel),
    }


def topk_rows(
    scores: np.ndarray, ids: np.ndarray, k: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row host top-k over (n, m) candidate arrays: (n, k) scores sorted
    descending + their ids, padded with -inf / -1 when m < k; ids of non-finite
    scores are -1. The ONE merge contract shared by the CPU IVF path and the
    sharded top-k merge."""
    n, m = scores.shape
    kk = min(k, m)
    if kk > 0:
        part = np.argpartition(scores, -kk, axis=1)[:, -kk:]
        psc = np.take_along_axis(scores, part, axis=1)
        order = np.argsort(-psc, axis=1)
        top = np.take_along_axis(part, order, axis=1)
        out_s = np.take_along_axis(scores, top, axis=1).astype(np.float32)
        out_i = np.take_along_axis(ids, top, axis=1).astype(np.int64)
    else:
        out_s = np.zeros((n, 0), dtype=np.float32)
        out_i = np.zeros((n, 0), dtype=np.int64)
    if kk < k:
        out_s = np.pad(out_s, ((0, 0), (0, k - kk)), constant_values=-np.inf)
        out_i = np.pad(out_i, ((0, 0), (0, k - kk)), constant_values=-1)
    out_i[~np.isfinite(out_s)] = -1
    return out_s, out_i


def pad_pow2(slots: np.ndarray, vecs: "np.ndarray | None" = None, extras: "np.ndarray | None" = None):
    """Pad a scatter batch to a power-of-two bucket so the update kernel compiles
    once per (bucket, capacity) pair; padding repeats row 0 (duplicate scatter
    indices with identical values are no-ops)."""
    n = len(slots)
    if n == 0:
        return slots, vecs, extras
    bucket = _next_pow2_shared(n, floor=8)
    if bucket != n:
        pad = bucket - n
        slots = np.concatenate([slots, np.full(pad, slots[0], slots.dtype)])
        if vecs is not None:
            vecs = np.concatenate([vecs, np.repeat(vecs[:1], pad, axis=0)])
        if extras is not None:
            extras = np.concatenate([extras, np.repeat(extras[:1], pad, axis=0)])
    return slots, vecs, extras


def pow2_target(capacity: int, target: "int | None") -> int:
    """Next capacity: at least double, jumping straight past ``target`` (every
    distinct capacity costs an XLA compile of the resize/scatter shapes)."""
    new_capacity = capacity * 2
    if target is not None:
        while new_capacity < target:
            new_capacity *= 2
    return new_capacity


class SlotIngestMixin:
    """Host-staged keyed slot assignment shared by the dense and sharded stores.

    Requires the host class to provide ``dim``, ``slot_of``, ``key_of``, ``_free``,
    ``_staged_slots``, ``_staged_vecs``, ``_staged_invalid`` and ``_grow()``.
    """

    def add(self, key: Any, vector: np.ndarray) -> None:
        vector = np.asarray(vector, dtype=np.float32).reshape(-1)
        assert vector.shape[0] == self.dim, f"dim mismatch: {vector.shape[0]} != {self.dim}"
        if key in self.slot_of:
            self.remove(key)
        if not self._free:
            self._grow()
        slot = self._free.pop()
        self.slot_of[key] = slot
        self.key_of[slot] = key
        self._staged_slots.append(slot)
        self._staged_vecs.append(vector)

    def add_many(self, keys: List[Any], vectors: np.ndarray) -> None:
        """Bulk insert: one staging append for the whole batch (no per-row Python work
        beyond the key dict updates)."""
        vectors = np.asarray(vectors, dtype=np.float32).reshape(len(keys), self.dim)
        last = {k: i for i, k in enumerate(keys)}  # intra-batch dedup: last write wins
        if len(last) != len(keys):
            keep = sorted(last.values())
            keys = [keys[i] for i in keep]
            vectors = vectors[keep]
        for k in [k for k in keys if k in self.slot_of]:
            self.remove(k)
        if len(self._free) < len(keys):
            self._grow(target=self.capacity + len(keys) - len(self._free))
        slots = [self._free.pop() for _ in range(len(keys))]
        self.slot_of.update(zip(keys, slots))
        self.key_of.update(zip(slots, keys))
        self._staged_slots.extend(slots)
        self._staged_vecs.extend(vectors)

    def remove(self, key: Any) -> None:
        slot = self.slot_of.pop(key, None)
        if slot is None:
            return
        self.key_of.pop(slot, None)
        self._free.append(slot)
        self._staged_invalid.append(slot)
        # drop a staged add for the same slot if still pending
        if slot in self._staged_slots:
            i = self._staged_slots.index(slot)
            del self._staged_slots[i]
            del self._staged_vecs[i]


class DenseKNNStore(SlotIngestMixin):
    """Keyed dense vector store with amortized-capacity device residency."""

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        dtype: Any = jnp.float32,
        initial_capacity: int = 1024,
        device: Any = None,
    ):
        assert metric in ("l2sq", "cos", "ip")
        self.dim = dim
        self.metric = metric
        self.dtype = dtype
        self.capacity = initial_capacity
        self.device = device
        # explicit placement pins the store to one chip of a mesh (the sharded
        # wrappers place one sub-store per device); computations on committed
        # arrays stay on that device, so only the three roots need the put
        def _place(x):
            return jax.device_put(x, device) if device is not None else x

        self._data = _place(jnp.zeros((self.capacity, dim), dtype=dtype))
        self._valid = _place(jnp.zeros((self.capacity,), dtype=bool))
        self._norms = _place(jnp.zeros((self.capacity,), dtype=jnp.float32))
        self.slot_of: Dict[Any, int] = {}
        self.key_of: Dict[int, Any] = {}
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        # staged updates applied lazily before the next search
        self._staged_vecs: List[np.ndarray] = []
        self._staged_slots: List[int] = []
        self._staged_invalid: List[int] = []

    def __len__(self) -> int:
        return len(self.slot_of)

    def _grow(self, target: int | None = None) -> None:
        new_capacity = pow2_target(self.capacity, target)
        self._flush()
        extra = new_capacity - self.capacity
        self._data = jnp.concatenate(
            [self._data, jnp.zeros((extra, self.dim), dtype=self.dtype)]
        )
        self._valid = jnp.concatenate([self._valid, jnp.zeros((extra,), dtype=bool)])
        self._norms = jnp.concatenate(
            [self._norms, jnp.zeros((extra,), dtype=jnp.float32)]
        )
        self._free.extend(range(new_capacity - 1, self.capacity - 1, -1))
        old_capacity, self.capacity = self.capacity, new_capacity
        self._after_grow(old_capacity, extra)

    def _after_grow(self, old_capacity: int, extra: int) -> None:
        """Subclass hook: capacity geometry just changed."""

    def _flush(self) -> None:
        # staged batches pad to power-of-two buckets so the scatter kernels compile
        # once per (bucket, capacity) pair instead of once per batch size (padding
        # rows re-write slot[0] with its own values — a no-op)
        if self._staged_slots:
            slots_np = np.array(self._staged_slots, dtype=np.int32)
            vecs_np = np.stack(self._staged_vecs).astype(np.float32)
            slots_np, vecs_np, _ = pad_pow2(slots_np, vecs_np)
            slots = jnp.asarray(slots_np)
            vecs = jnp.asarray(vecs_np)
            self._data = self._data.at[slots].set(vecs.astype(self.dtype))
            self._norms = self._norms.at[slots].set(jnp.sum(vecs * vecs, axis=1))
            self._valid = self._valid.at[slots].set(True)
            self._staged_slots, self._staged_vecs = [], []
            self._after_flush_adds(slots_np, vecs)
        if self._staged_invalid:
            inv = sorted(set(self._staged_invalid))
            flags_np = np.array([s in self.key_of for s in inv], dtype=bool)
            slots_np = np.array(inv, dtype=np.int32)
            slots_np, _, flags_np = pad_pow2(slots_np, extras=flags_np)
            self._valid = self._valid.at[jnp.asarray(slots_np)].set(jnp.asarray(flags_np))
            self._staged_invalid = []
            self._after_flush_removals()

    def _after_flush_adds(self, padded_slots: np.ndarray, vecs: jax.Array) -> None:
        """Subclass hook: a staged add batch just scattered into the device
        arrays (IVF assigns the new rows to centroids here)."""

    def _after_flush_removals(self) -> None:
        """Subclass hook: staged invalidations just applied."""

    def export_rows(self) -> Tuple[List[Any], np.ndarray]:
        """Every live (key, vector) pair as host arrays — the *rebuildable
        descriptor* contract: an index over this store can be reconstructed
        on another process from this export alone (membership handoff,
        background rebuilds). One device gather for the whole corpus."""
        self._flush()
        keys = list(self.slot_of.keys())
        if not keys:
            return keys, np.zeros((0, self.dim), dtype=np.float32)
        slots = np.fromiter(self.slot_of.values(), dtype=np.int64)
        vecs = np.asarray(self._data[jnp.asarray(slots)].astype(jnp.float32))
        return keys, vecs

    def search_batch(self, queries: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (scores (q,k), slots (q,k), valid_mask (q,k)); slots map via key_of."""
        self._flush()
        if isinstance(queries, jax.Array):
            # device-resident queries (e.g. straight from the embedder) chain into
            # the search without a host round-trip; skip no-op casts/reshapes so
            # the serving path dispatches exactly one device computation
            if queries.dtype != jnp.float32:
                queries = queries.astype(jnp.float32)
            if queries.ndim != 2 or queries.shape[-1] != self.dim:
                queries = queries.reshape(-1, self.dim)
        else:
            queries = np.asarray(queries, dtype=np.float32).reshape(-1, self.dim)
        k_eff = max(1, min(k, self.capacity))
        q_dev = queries if isinstance(queries, jax.Array) else jnp.asarray(queries)
        # pow2 shape bucketing: serving traffic arrives at ragged batch sizes
        # and per-request k; padding both to the next power of two bounds the
        # kernel's jit cache at O(log) entries instead of one compile per size
        q_dev, nq = pad_queries_pow2(q_dev, self.dim)
        k_pad = min(next_pow2(k_eff), self.capacity)
        if self._data.dtype == jnp.bfloat16:
            # bf16-resident corpus (HBM capacity: 10M x 384 fits one v5e chip):
            # the MXU consumes bf16 natively with f32 accumulation — cast the
            # QUERIES down instead of materializing an f32 copy of the corpus
            q_dev = q_dev.astype(jnp.bfloat16)
            data = self._data
        else:
            data = (
                self._data
                if self._data.dtype == jnp.float32
                else self._data.astype(jnp.float32)
            )
        top_scores, top_idx = _search_kernel(
            data,
            self._valid,
            self._norms,
            q_dev,
            k_pad,
            self.metric,
        )
        # one batched host fetch (a tunneled device pays per-RPC latency, not size)
        scores, idx = jax.device_get((top_scores[:nq, :k_eff], top_idx[:nq, :k_eff]))
        valid = np.isfinite(scores)
        return scores, idx, valid


class BruteForceKnnIndex:
    """ExternalIndex-protocol adapter over DenseKNNStore (engine-facing).

    Parity: reference ``BruteForceKNNIndex`` (``brute_force_knn_integration.rs:22``) with its
    auxiliary filter data support (jmespath replaced by a python callable / jsonpath-lite).
    """

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        initial_capacity: int = 1024,
        mesh: Any = None,
        _store: Any = None,
    ):
        if _store is not None:
            # subclass-provided store (IvfKnnIndex): every other attribute
            # initializes here so subclasses never copy this tail
            self.store: Any = _store
        elif mesh is not None:
            from pathway_tpu.parallel.knn_sharded import ShardedKNNStore

            self.store = ShardedKNNStore(
                mesh, dim, metric=metric, initial_capacity=initial_capacity
            )
        else:
            self.store = DenseKNNStore(
                dim, metric=metric, initial_capacity=initial_capacity
            )
        self.filter_data: Dict[Any, Any] = {}

    def add(self, key: Any, vector: Any, filter_data: Any = None) -> None:
        self.store.add(key, _as_vector(vector))
        if filter_data is not None:
            self.filter_data[key] = filter_data

    def add_many(
        self, keys: List[Any], vectors: List[Any], filter_data: List[Any] | None = None
    ) -> None:
        """Bulk ingest: ONE staging append + one capacity jump for the whole batch
        (per-row adds through a growing device array would pay an XLA compile per
        capacity step)."""
        self.store.add_many(keys, np.stack([np.asarray(_as_vector(v)) for v in vectors]))
        if filter_data is not None:
            for k, f in zip(keys, filter_data):
                if f is not None:
                    self.filter_data[k] = f

    def remove(self, key: Any) -> None:
        self.store.remove(key)
        self.filter_data.pop(key, None)

    # -- rebuildable-descriptor contract (membership handoff) ----------------

    def rebuild_descriptor(self) -> "Dict[str, Any] | None":
        """The index content as a host-side descriptor another process can
        rebuild the SAME index from (keys + vectors + filter data) — the
        membership preflight's alternative to the blanket device-resident
        refusal. ``None`` when the backing store cannot export (a typed
        refusal is kept for those)."""
        export = getattr(self.store, "export_rows", None)
        if export is None:
            return None
        keys, vecs = export()
        desc: Dict[str, Any] = {
            "keys": keys,
            "vectors": vecs,
            "filter_data": dict(self.filter_data),
        }
        quant_state = getattr(self.store, "quant_state", None)
        if quant_state is not None:
            # quantized state joins the membership/checkpoint protocols:
            # mode + dtype + per-page sidecars ride the descriptor so the
            # receiving side can verify it serves the SAME tower geometry
            desc["quant"] = quant_state()
        return desc

    def iter_rebuild_fragments(
        self, rows_per_fragment: int
    ) -> "Tuple[Dict[str, Any], Any]":
        """Streaming form of :meth:`rebuild_descriptor` for the replica-feed
        bootstrap: a small header (filter data + quant sidecars) plus an
        iterator of bounded ``{"keys", "vectors"}`` row fragments, at most
        ``rows_per_fragment`` rows each. Stores with a native page-walking
        export (the tiered IVF store) stream without ever concatenating the
        corpus; dense stores chunk one host gather."""
        header: Dict[str, Any] = {
            "filter_data": dict(self.filter_data),
            # replica children construct their index FROM the header (they
            # have no graph to read the dim off), so geometry rides along
            "dim": int(getattr(self.store, "dim", 0)),
            "metric": str(getattr(self.store, "metric", "l2sq")),
        }
        quant_state = getattr(self.store, "quant_state", None)
        if quant_state is not None:
            header["quant"] = quant_state()
        stream = getattr(self.store, "iter_export_fragments", None)
        if stream is not None:
            def native() -> Any:
                for keys, vecs in stream(rows_per_fragment):
                    yield {"keys": keys, "vectors": vecs}

            return header, native()
        export = getattr(self.store, "export_rows", None)
        if export is None:
            raise RuntimeError(
                "index store cannot export rows; replica bootstrap is refused "
                "for device-opaque stores (same contract as rebuild_descriptor)"
            )
        keys, vecs = export()

        def chunked() -> Any:
            for lo in range(0, max(len(keys), 1), rows_per_fragment):
                yield {
                    "keys": list(keys[lo : lo + rows_per_fragment]),
                    "vectors": np.asarray(
                        vecs[lo : lo + rows_per_fragment], dtype=np.float32
                    ),
                }

        return header, chunked()

    def install_descriptor_header(self, header: Dict[str, Any]) -> None:
        """Install the non-row half of a descriptor (filter data; quant mode
        verification). A descriptor whose quantization mode differs from this
        store's is a typed refusal (``QuantConfigError``) — replicating fp32
        geometry into an int8 replica (or vice versa) must fail loudly, never
        serve silently mismatched scores."""
        quant = header.get("quant")
        if quant is not None:
            from pathway_tpu.ops.knn_quant import QuantConfigError

            want = str(quant.get("mode", "off"))
            have = str(getattr(self.store, "quant", "off"))
            if want != have:
                raise QuantConfigError(
                    f"rebuild descriptor carries quant mode {want!r} but this "
                    f"store runs {have!r}: replication across quantization "
                    "modes is refused (set PATHWAY_IVF_QUANT consistently)"
                )
        self.filter_data = dict(header.get("filter_data", {}))

    def install_descriptor_rows(self, keys: List[Any], vectors: Any) -> None:
        """Install one bounded row fragment (bulk append — quantized stores
        regenerate their codes on append, bit-identically per the
        ``quant_state`` contract)."""
        keys = list(keys)
        if keys:
            self.store.add_many(keys, np.asarray(vectors, dtype=np.float32))

    def install_rebuild_descriptor(self, desc: Dict[str, Any]) -> None:
        """Rebuild this (fresh) index from a :meth:`rebuild_descriptor`
        export: one bulk ingest, filter data restored alongside (the
        monolithic form of the header + fragment install pair above)."""
        self.install_descriptor_header(desc)
        self.install_descriptor_rows(
            list(desc.get("keys", [])), desc.get("vectors")
        )

    def search(self, query_vector: Any, limit: int, filter_expr: Any = None) -> List[tuple]:
        return self.search_many([query_vector], [limit], [filter_expr])[0]

    def search_many(
        self,
        query_vectors: List[Any],
        limits: List[int],
        filter_exprs: List[Any] | None = None,
    ) -> List[List[tuple]]:
        """Answer a whole commit's queries with ONE device matmul+top-k (the per-batch
        kernel the reference runs per worker, ``brute_force_knn_integration.rs:113``)."""
        n = len(query_vectors)
        if n == 0 or len(self.store) == 0:
            return [[] for _ in range(n)]
        limits = [int(l) for l in limits]
        if max(limits) <= 0:
            return [[] for _ in range(n)]
        has_filter = filter_exprs is not None and any(
            f is not None for f in filter_exprs
        )
        overfetch = max(limits) if not has_filter else max(max(limits) * 4, 16)
        overfetch = min(overfetch, max(len(self.store), 1))
        vecs = [_as_vector(v) for v in query_vectors]
        if any(isinstance(v, jax.Array) for v in vecs):
            q: Any = jnp.stack([jnp.asarray(v, dtype=jnp.float32) for v in vecs])
        else:
            q = np.stack(vecs)
        scores, idx, valid = self.store.search_batch(q, overfetch)
        from pathway_tpu.stdlib.indexing.filters import matches_filter

        results: List[List[tuple]] = []
        for qi in range(n):
            if limits[qi] <= 0:
                results.append([])
                continue
            flt = filter_exprs[qi] if filter_exprs is not None else None
            out: List[tuple] = []
            for j in range(idx.shape[1]):
                if not valid[qi, j]:
                    continue
                key = self.store.key_of.get(int(idx[qi, j]))
                if key is None:
                    continue
                if flt is not None and not matches_filter(
                    self.filter_data.get(key), flt
                ):
                    continue
                out.append((key, float(scores[qi, j])))
                if len(out) >= limits[qi]:
                    break
            results.append(out)
        return results


class LshKnnIndex:
    """Random-projection LSH (reference ``stdlib/ml/classifiers/_knn_lsh.py:64``), with the
    bucket scoring matmul on the TPU: candidates from bucket intersection, exact re-rank via
    the dense kernel over the candidate subset."""

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        bucket_length: float = 4.0,
        n_or: int = 8,
        n_and: int = 4,
        seed: int = 0,
    ):
        self.dim = dim
        self.metric = metric
        rng = np.random.default_rng(seed)
        self.projections = rng.normal(size=(n_or, n_and, dim)).astype(np.float32)
        self.offsets = rng.uniform(0, bucket_length, size=(n_or, n_and)).astype(np.float32)
        self.bucket_length = bucket_length
        self.n_or = n_or
        self.buckets: List[Dict[tuple, set]] = [dict() for _ in range(n_or)]
        self.vectors: Dict[Any, np.ndarray] = {}
        self.filter_data: Dict[Any, Any] = {}

    def _bucket_ids(self, vector: np.ndarray) -> List[tuple]:
        # (n_or, n_and) integer bucket coordinates
        proj = np.einsum("oad,d->oa", self.projections, vector)
        ids = np.floor((proj + self.offsets) / self.bucket_length).astype(np.int64)
        return [tuple(ids[o]) for o in range(self.n_or)]

    def add(self, key: Any, vector: Any, filter_data: Any = None) -> None:
        vector = _as_vector(vector)
        if key in self.vectors:
            self.remove(key)
        self.vectors[key] = vector
        for o, bid in enumerate(self._bucket_ids(vector)):
            self.buckets[o].setdefault(bid, set()).add(key)
        if filter_data is not None:
            self.filter_data[key] = filter_data

    def remove(self, key: Any) -> None:
        vector = self.vectors.pop(key, None)
        if vector is None:
            return
        for o, bid in enumerate(self._bucket_ids(vector)):
            bucket = self.buckets[o].get(bid)
            if bucket:
                bucket.discard(key)
        self.filter_data.pop(key, None)

    def search(self, query_vector: Any, limit: int, filter_expr: Any = None) -> List[tuple]:
        query = _as_vector(query_vector)
        candidates: set = set()
        for o, bid in enumerate(self._bucket_ids(query)):
            candidates |= self.buckets[o].get(bid, set())
        if not candidates:
            return []
        from pathway_tpu.stdlib.indexing.filters import matches_filter

        if filter_expr is not None:
            candidates = {
                c for c in candidates if matches_filter(self.filter_data.get(c), filter_expr)
            }
            if not candidates:
                return []
        cand = list(candidates)
        matrix = np.stack([self.vectors[c] for c in cand])
        scores = _score_candidates(jnp.asarray(matrix), jnp.asarray(query), self.metric)
        scores = np.asarray(scores)
        order = np.argsort(-scores)[:limit]
        return [(cand[i], float(scores[i])) for i in order]


@functools.partial(jax.jit, static_argnames=("metric",))
def _score_candidates(matrix: jax.Array, query: jax.Array, metric: str) -> jax.Array:
    scores = matrix @ query
    if metric == "l2sq":
        scores = -(jnp.sum(matrix * matrix, axis=1) + jnp.sum(query * query) - 2.0 * scores)
    elif metric == "cos":
        scores = scores / jnp.maximum(
            jnp.linalg.norm(matrix, axis=1) * jnp.linalg.norm(query), 1e-30
        )
    return scores


def _as_vector(value: Any) -> Any:
    if isinstance(value, jax.Array):
        # device-resident: normalize shape/dtype lazily, stays on device
        return value.astype(jnp.float32).reshape(-1)
    if isinstance(value, np.ndarray):
        return value.astype(np.float32).reshape(-1)
    if isinstance(value, (tuple, list)):
        return np.asarray(value, dtype=np.float32)
    raise TypeError(f"expected a vector, got {type(value).__name__}")


class IvfKnnIndex(BruteForceKnnIndex):
    """ExternalIndex-protocol adapter over the IVF-Flat store (the reference's
    approximate index role — USearch HNSW — served the TPU way; see
    ``ops/knn_ivf.py``)."""

    def __init__(
        self,
        dim: int,
        metric: str = "l2sq",
        initial_capacity: int = 1024,
        n_clusters: int = 64,
        n_probe: int = 8,
        mesh: Any = None,
        tiered: "bool | None" = None,
    ):
        from pathway_tpu.ops.knn_tiers import tiering_enabled

        if tiered is None:
            tiered = tiering_enabled()
        if mesh is not None:
            from pathway_tpu.parallel.knn_sharded import ShardedIvfKnnStore

            store: Any = ShardedIvfKnnStore(
                mesh,
                dim,
                metric=metric,
                initial_capacity=initial_capacity,
                n_clusters=n_clusters,
                n_probe=n_probe,
                tiered=tiered,
            )
        elif tiered:
            from pathway_tpu.ops.knn_tiers import TieredIvfKnnStore

            store = TieredIvfKnnStore(
                dim,
                metric=metric,
                initial_capacity=initial_capacity,
                n_clusters=n_clusters,
                n_probe=n_probe,
            )
        else:
            from pathway_tpu.ops.knn_ivf import IvfKnnStore

            store = IvfKnnStore(
                dim,
                metric=metric,
                initial_capacity=initial_capacity,
                n_clusters=n_clusters,
                n_probe=n_probe,
            )
        super().__init__(
            dim,
            metric=metric,
            initial_capacity=initial_capacity,
            _store=store,
        )
