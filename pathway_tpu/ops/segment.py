"""Segment reduction kernels — the groupby-reduce hot path.

TPU-native counterpart of the reference's incremental reduce (``src/engine/reduce.rs:22-56``
semigroup impls applied inside DD's ``reduce``). A commit's delta rows are assigned dense
segment ids (one per touched group) and reduced with vectorized kernels:

- large float32/bfloat16 batches lower to ``jax.ops.segment_sum`` under ``jit`` — XLA
  compiles the scatter-add for the VPU, and the batch stays on device when the caller's
  columns already live there;
- everything else uses exact host kernels (``np.add.at`` / ``np.bincount``) — int64 sums
  must not round-trip through float32, and tiny unit-test batches would lose to the
  host↔device transfer.

The split mirrors the reference's semigroup-vs-recompute reducer taxonomy: these kernels
serve the semigroup side (count/sum); recompute reducers keep per-group multisets.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any

import numpy as np

from pathway_tpu.internals.shapes import next_pow2 as _next_pow2

# Below this, host↔device transfer dominates the reduction itself.
_DEVICE_THRESHOLD = 1 << 15


@lru_cache(maxsize=1)
def _jax():
    try:
        import jax

        return jax
    except Exception:  # pragma: no cover - jax is baked into this image
        return None


@lru_cache(maxsize=8)
def _jit_segment_sum(num_segments: int):
    # callers pad num_segments to a power of two so the per-commit touched-group
    # count doesn't retrace/recompile the kernel every batch
    jax = _jax()

    @jax.jit
    def kernel(values, segment_ids):
        return jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)

    return kernel


# Above this row count, a configured multi-shard mesh routes the reduction through
# the key-hash exchange (mutable for tests/dryruns to force the collective path).
MESH_THRESHOLD = 1 << 15

# Opt-out for the float64 two-float-split mesh policy (set False to force f64 sums
# onto the exact host reduction even when a mesh is configured).
MESH_F64_SPLIT = True

# Magnitudes above this risk float32 partial-sum overflow on the mesh (f32 max is
# ~3.4e38; a 2^15-row batch of equal-sign values needs ~2^15 headroom) — such
# batches stay on the exact host path.
_F32_SAFE_MAX = 1e33


def segment_sum(
    values: np.ndarray,
    segment_ids: np.ndarray,
    num_segments: int,
    key_lo: np.ndarray | None = None,
) -> np.ndarray:
    """Sum ``values`` into ``num_segments`` buckets given per-row segment ids.

    Exactness contract: integer inputs reduce in int64 on host; small float batches
    reduce on host. float32 batches above the device threshold ride XLA. With a
    default mesh configured (``parallel.set_default_mesh``) and ``key_lo`` given,
    large float batches route through the mesh exchange (``groupby_sharded``) —
    float64 via a COMPENSATED TWO-FLOAT SPLIT (TPUs have no f64): each value splits
    into a float32 high part and a float32 residual, both ride the same exchange,
    and the halves recombine in float64 on host. Input-representation error is
    eliminated; accumulation error is that of two f32 segment sums (~1e-7 relative
    per summand), the documented engine policy for mesh-routed f64 reductions.
    """
    values = np.asarray(values)
    segment_ids = np.asarray(segment_ids, dtype=np.int64)
    jax = _jax()
    if jax is not None and key_lo is not None and values.dtype.kind == "f":
        from pathway_tpu.parallel.mesh import data_shards, get_default_mesh

        mesh = get_default_mesh()
        if data_shards(mesh) > 1 and len(values) >= MESH_THRESHOLD:
            from pathway_tpu.parallel.groupby_sharded import sharded_segment_sum

            key_lo = np.asarray(key_lo)
            if values.dtype == np.float32:
                return sharded_segment_sum(
                    mesh, key_lo, segment_ids, values, num_segments
                ).astype(values.dtype)
            if MESH_F64_SPLIT and np.max(np.abs(values), initial=0.0) < _F32_SAFE_MAX:
                hi = values.astype(np.float32)
                lo = (values - hi.astype(np.float64)).astype(np.float32)
                s_hi = sharded_segment_sum(mesh, key_lo, segment_ids, hi, num_segments)
                s_lo = sharded_segment_sum(mesh, key_lo, segment_ids, lo, num_segments)
                return s_hi.astype(np.float64) + s_lo.astype(np.float64)
            # overflow-risky or opted-out f64: exact host reduction
    if (
        jax is not None
        and values.dtype == np.float32
        and len(values) >= _DEVICE_THRESHOLD
    ):
        padded = _next_pow2(num_segments)
        out = _jit_segment_sum(padded)(values, segment_ids)
        return np.asarray(out)[:num_segments]
    if values.dtype == object:
        out_obj = np.zeros(num_segments, dtype=object)
        for i in range(len(values)):
            out_obj[segment_ids[i]] = out_obj[segment_ids[i]] + values[i]
        return out_obj
    out = np.zeros(num_segments, dtype=values.dtype if values.dtype.kind == "f" else np.int64)
    np.add.at(out, segment_ids, values)
    return out


def segment_count(
    segment_ids: np.ndarray, num_segments: int, weights: np.ndarray | None = None
) -> np.ndarray:
    """Count rows (or sum integer weights, e.g. +1/-1 diffs) per segment."""
    segment_ids = np.asarray(segment_ids, dtype=np.int64)
    if weights is None:
        return np.bincount(segment_ids, minlength=num_segments).astype(np.int64)
    out = np.zeros(num_segments, dtype=np.int64)
    np.add.at(out, segment_ids, np.asarray(weights, dtype=np.int64))
    return out


def segment_min(values: np.ndarray, segment_ids: np.ndarray, num_segments: int) -> np.ndarray:
    values = np.asarray(values)
    segment_ids = np.asarray(segment_ids, dtype=np.int64)
    if values.dtype.kind == "f":
        out = np.full(num_segments, np.inf, dtype=values.dtype)
    else:
        out = np.full(num_segments, np.iinfo(np.int64).max, dtype=np.int64)
    np.minimum.at(out, segment_ids, values)
    return out


def segment_max(values: np.ndarray, segment_ids: np.ndarray, num_segments: int) -> np.ndarray:
    values = np.asarray(values)
    segment_ids = np.asarray(segment_ids, dtype=np.int64)
    if values.dtype.kind == "f":
        out = np.full(num_segments, -np.inf, dtype=values.dtype)
    else:
        out = np.full(num_segments, np.iinfo(np.int64).min, dtype=np.int64)
    np.maximum.at(out, segment_ids, values)
    return out


def segment_slices(
    segment_ids: np.ndarray, num_segments: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stable sort rows by segment: returns (order, starts, ends) such that
    ``order[starts[s]:ends[s]]`` are the row indices of segment ``s`` in input order.
    Segments with no rows get empty slices."""
    segment_ids = np.asarray(segment_ids, dtype=np.int64)
    order = np.argsort(segment_ids, kind="stable")
    sorted_ids = segment_ids[order]
    if num_segments is None:
        num_segments = int(sorted_ids[-1]) + 1 if len(sorted_ids) else 0
    starts = np.searchsorted(sorted_ids, np.arange(num_segments), side="left")
    ends = np.searchsorted(sorted_ids, np.arange(num_segments), side="right")
    return order, starts, ends
