"""Run-progress monitoring (parity: reference ``internals/monitoring.py`` rich dashboard)."""

from __future__ import annotations

import enum
import sys
import time
from typing import Any, Dict, List


class MonitoringLevel(enum.Enum):
    AUTO = "auto"
    AUTO_ALL = "auto_all"
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"


class StatsMonitor:
    """Lightweight operator-counter monitor; rich live table when attached to a tty."""

    def __init__(self, nodes: List[Any]):
        self.nodes = nodes
        self.counts: Dict[int, int] = {}
        self.start = time.monotonic()
        self._last_print = 0.0

    def update(self, commit: int, row_counts: Dict[int, int], states: Dict[int, Any] | None = None) -> None:
        for node_id, n in row_counts.items():
            self.counts[node_id] = self.counts.get(node_id, 0) + n
        now = time.monotonic()
        if now - self._last_print > 1.0 and sys.stderr.isatty():
            self._last_print = now
            total = sum(self.counts.values())
            print(
                f"[pathway-tpu] commit={commit} rows_processed={total} "
                f"elapsed={now - self.start:.1f}s",
                file=sys.stderr,
            )

    def close(self) -> None:
        pass
