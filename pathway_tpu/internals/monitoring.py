"""Run-progress monitoring.

Parity: reference ``internals/monitoring.py`` — a rich-powered live terminal dashboard
(operator latencies, connector counts, ``:56-190``) with ``MonitoringLevel`` (``:228``)
controlling detail. Falls back to plain stderr lines off-tty or without rich —
the plain path runs whenever the rich live display is unavailable (no tty, no
rich, a broken console), so redirected/CI runs still see throttled progress.

The dashboard reads the engine's per-operator profile totals
(``engine/profile.py``): each operator row shows cumulative wall seconds and
rows/s next to the row counters, so "which operator is slow" is answerable
from the live view, not only from ``/metrics``.
"""

from __future__ import annotations

import enum
import sys
import time
from typing import Any, Dict, List


class MonitoringLevel(enum.Enum):
    AUTO = "auto"
    AUTO_ALL = "auto_all"
    NONE = "none"
    IN_OUT = "in_out"
    ALL = "all"


class StatsMonitor:
    """Operator-counter monitor: rich live table on a tty, plain lines otherwise."""

    def __init__(self, nodes: List[Any], level: MonitoringLevel = MonitoringLevel.AUTO):
        self.nodes = nodes
        self.level = level
        self.counts: Dict[int, int] = {}
        self.latest_commit_rows: Dict[int, int] = {}
        self.start = time.monotonic()
        self._last_print = 0.0
        self._live: Any = None
        if sys.stderr.isatty():
            try:
                from rich.console import Console
                from rich.live import Live

                # stderr console: program stdout stays clean under redirection
                self._live = Live(
                    self._render(0),
                    refresh_per_second=2,
                    transient=True,
                    console=Console(stderr=True),
                )
                self._live.start()
            except Exception:
                self._live = None

    def _interesting_nodes(self) -> List[Any]:
        show_all = self.level in (MonitoringLevel.ALL, MonitoringLevel.AUTO_ALL)
        out = []
        for node in self.nodes:
            if node.kind in ("input", "output") or show_all:
                out.append(node)
        return out

    def _profile_totals(self) -> Dict[tuple, dict]:
        """Per-operator cumulative seconds from the engine profiler, keyed by
        the full (node_id, name, kind) triple — node ids restart at 0 for
        every graph in the process, so an id-only key would show another
        graph's operator seconds. Empty when profiling is off (the dashboard
        then shows zeros, not a crash)."""
        try:
            from pathway_tpu.engine.profile import get_profiler

            return {
                (e["node"], e["name"], e["kind"]): e
                for e in get_profiler().operator_totals()
            }
        except Exception:
            return {}

    def _render(self, commit: int) -> Any:
        from rich.table import Table

        elapsed = max(time.monotonic() - self.start, 1e-9)
        totals = self._profile_totals()
        table = Table(title=f"pathway_tpu run — commit {commit}")
        table.add_column("operator")
        table.add_column("kind")
        table.add_column("rows in latest commit", justify="right")
        table.add_column("rows total", justify="right")
        table.add_column("time (s)", justify="right")
        table.add_column("rows/s", justify="right")
        for node in self._interesting_nodes():
            rows_total = self.counts.get(node.id, 0)
            seconds = totals.get(
                (node.id, node.name, node.kind), {}
            ).get("seconds", 0.0)
            table.add_row(
                node.name,
                node.kind,
                str(self.latest_commit_rows.get(node.id, 0)),
                str(rows_total),
                f"{seconds:.3f}",
                f"{rows_total / elapsed:.1f}",
            )
        table.caption = f"elapsed {elapsed:.1f}s"
        return table

    def update(
        self,
        commit: int,
        row_counts: Dict[int, int],
        states: Dict[int, Any] | None = None,
    ) -> None:
        self.latest_commit_rows = dict(row_counts)
        for node_id, n in row_counts.items():
            self.counts[node_id] = self.counts.get(node_id, 0) + n
        now = time.monotonic()
        if self._live is not None:
            if now - self._last_print > 0.4:
                self._last_print = now
                try:
                    self._live.update(self._render(commit))
                except Exception:
                    pass
        elif now - self._last_print > 1.0:
            # plain-line fallback whenever the rich live display is not
            # running — including redirected/non-tty stderr (CI logs), which
            # previously got NOTHING despite the module contract
            self._last_print = now
            total = sum(self.counts.values())
            elapsed = max(now - self.start, 1e-9)
            slowest = ""
            totals = self._profile_totals()
            if totals:
                worst = max(totals.values(), key=lambda e: e["seconds"])
                if worst["seconds"] > 0:
                    slowest = (
                        f" slowest={worst['name']}:{worst['seconds']:.2f}s"
                    )
            print(
                f"[pathway-tpu] commit={commit} rows_processed={total} "
                f"rows_per_s={total / elapsed:.1f} "
                f"elapsed={elapsed:.1f}s{slowest}",
                file=sys.stderr,
            )

    def close(self) -> None:
        if self._live is not None:
            try:
                self._live.stop()
            except Exception:
                pass
            self._live = None
