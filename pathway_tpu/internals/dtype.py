"""Data-type lattice for the TPU-native dataflow engine.

Role parity with the reference's ``python/pathway/internals/dtype.py`` (dtype lattice with
arrays/Json/Pointer/Optional) and ``src/engine/value.rs:507`` (``enum Type``), re-designed for a
columnar JAX backend: every dtype knows its numpy storage dtype and whether it is eligible for
the jit'd (TPU) expression path.
"""

from __future__ import annotations

import datetime
from abc import ABC
from typing import Any, Optional, Tuple, get_args, get_origin

import numpy as np


class DType(ABC):
    """Base of the dtype lattice."""

    _name: str = "DType"

    @property
    def np_dtype(self) -> np.dtype:
        """Numpy storage dtype for a column of this type (object for boxed values)."""
        return np.dtype(object)

    @property
    def is_device_friendly(self) -> bool:
        """True when columns of this dtype can live on the TPU as dense jax arrays."""
        return False

    def is_optional(self) -> bool:
        return False

    def strip_optional(self) -> "DType":
        return self

    @property
    def typehint(self) -> Any:
        return Any

    def __repr__(self) -> str:
        return self._name

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other) and self.__dict__ == getattr(other, "__dict__", None)

    def __hash__(self) -> int:
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items(), key=str))))


class _SimpleDType(DType):
    def __init__(self, name: str, np_dtype: np.dtype, device_friendly: bool, typehint: Any):
        self._name = name
        self._np = np.dtype(np_dtype)
        self._device = device_friendly
        self._hint = typehint

    @property
    def np_dtype(self) -> np.dtype:
        return self._np

    @property
    def is_device_friendly(self) -> bool:
        return self._device

    @property
    def typehint(self) -> Any:
        return self._hint


NONE = _SimpleDType("NONE", object, False, type(None))
BOOL = _SimpleDType("BOOL", np.bool_, True, bool)
INT = _SimpleDType("INT", np.int64, True, int)
FLOAT = _SimpleDType("FLOAT", np.float64, True, float)
STR = _SimpleDType("STR", object, False, str)
BYTES = _SimpleDType("BYTES", object, False, bytes)
ANY = _SimpleDType("ANY", object, False, Any)
DATE_TIME_NAIVE = _SimpleDType("DATE_TIME_NAIVE", "datetime64[ns]", False, np.datetime64)
DATE_TIME_UTC = _SimpleDType("DATE_TIME_UTC", "datetime64[ns]", False, np.datetime64)
DURATION = _SimpleDType("DURATION", "timedelta64[ns]", False, np.timedelta64)


class _JsonDType(DType):
    _name = "JSON"

    @property
    def typehint(self) -> Any:
        from pathway_tpu.internals.json import Json

        return Json


JSON = _JsonDType()


class Pointer(DType):
    """128-bit row reference (reference: ``Value::Pointer`` / ``api.Pointer``)."""

    def __init__(self, *args: DType):
        self.args: Tuple[DType, ...] = tuple(args)
        self._name = "POINTER" if not args else f"Pointer({', '.join(map(repr, args))})"

    @property
    def typehint(self) -> Any:
        from pathway_tpu.internals.keys import Pointer as PointerValue

        return PointerValue


POINTER = Pointer()


class Optional_(DType):
    def __init__(self, wrapped: DType):
        if isinstance(wrapped, Optional_):
            wrapped = wrapped.wrapped
        self.wrapped = wrapped
        self._name = f"Optional({wrapped!r})"

    def is_optional(self) -> bool:
        return True

    def strip_optional(self) -> DType:
        return self.wrapped

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(object)

    @property
    def typehint(self) -> Any:
        return Optional[self.wrapped.typehint]


class Array(DType):
    """N-dim numeric array column (reference ``Type::Array``); device friendly."""

    def __init__(self, n_dim: int | None = None, wrapped: DType = FLOAT):
        self.n_dim = n_dim
        self.wrapped = wrapped
        self._name = f"Array({n_dim}, {wrapped!r})"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(object)  # ragged rows boxed; dense path promotes to device

    @property
    def is_device_friendly(self) -> bool:
        return True

    @property
    def typehint(self) -> Any:
        return np.ndarray


ANY_ARRAY = Array(None, ANY)
INT_ARRAY = Array(None, INT)
FLOAT_ARRAY = Array(None, FLOAT)


class Tuple_(DType):
    def __init__(self, *args: DType):
        self.args = tuple(args)
        self._name = f"Tuple({', '.join(map(repr, args))})"

    @property
    def typehint(self) -> Any:
        return tuple


ANY_TUPLE = Tuple_(ANY)


class List_(DType):
    def __init__(self, wrapped: DType = ANY):
        self.wrapped = wrapped
        self._name = f"List({wrapped!r})"

    @property
    def typehint(self) -> Any:
        return tuple


class Callable_(DType):
    def __init__(self, arg_types: Any = ..., return_type: DType = ANY):
        self.arg_types = arg_types
        self.return_type = return_type
        self._name = "Callable"


class Future(DType):
    """Result of an async UDF not yet awaited (reference ``Type::Future``)."""

    def __init__(self, wrapped: DType):
        self.wrapped = wrapped
        self._name = f"Future({wrapped!r})"


def wrap(input_type: Any) -> DType:
    """Map a python typehint to a DType (reference ``dtype.wrap``)."""
    from pathway_tpu.internals.json import Json
    from pathway_tpu.internals.keys import Pointer as PointerValue

    if isinstance(input_type, DType):
        return input_type
    if input_type is None or input_type is type(None):
        return NONE
    if input_type is bool or input_type is np.bool_:
        return BOOL
    if input_type is int or input_type in (np.int32, np.int64):
        return INT
    if input_type is float or input_type in (np.float32, np.float64):
        return FLOAT
    if input_type is str:
        return STR
    if input_type is bytes:
        return BYTES
    if input_type is datetime.datetime:
        return DATE_TIME_NAIVE
    if input_type is datetime.timedelta:
        return DURATION
    if input_type is Json or input_type is dict:
        return JSON
    if input_type is PointerValue:
        return POINTER
    if input_type is np.ndarray:
        return ANY_ARRAY
    if input_type is Any:
        return ANY
    origin = get_origin(input_type)
    if origin is not None:
        args = get_args(input_type)
        if origin is tuple:
            if len(args) == 2 and args[1] is Ellipsis:
                return List_(wrap(args[0]))
            return Tuple_(*(wrap(a) for a in args))
        if origin is list:
            return List_(wrap(args[0]) if args else ANY)
        # typing.Optional / Union
        import typing

        if origin is typing.Union or str(origin) in ("typing.Union", "types.UnionType"):
            non_none = [a for a in args if a is not type(None)]
            if len(non_none) == 1 and len(args) == 2:
                return Optional_(wrap(non_none[0]))
            return ANY
    if isinstance(input_type, type) and issubclass(input_type, PointerValue):
        return POINTER
    return ANY


def unoptionalize(dtype: DType) -> DType:
    return dtype.strip_optional()


def types_lca(a: DType, b: DType, raising: bool = False) -> DType:
    """Least common ancestor in the lattice (reference ``dtype.types_lca``)."""
    if a == b:
        return a
    if a == NONE:
        return b if b.is_optional() or b in (ANY, NONE) else Optional_(b)
    if b == NONE:
        return a if a.is_optional() or a in (ANY, NONE) else Optional_(a)
    if a.is_optional() or b.is_optional():
        inner = types_lca(unoptionalize(a), unoptionalize(b), raising=raising)
        return inner if inner == ANY else Optional_(inner)
    if {a, b} == {INT, FLOAT}:
        return FLOAT
    if isinstance(a, Pointer) and isinstance(b, Pointer):
        return POINTER
    if isinstance(a, Array) and isinstance(b, Array):
        return ANY_ARRAY
    if isinstance(a, (Tuple_, List_)) and isinstance(b, (Tuple_, List_)):
        return ANY_TUPLE
    if raising:
        raise TypeError(f"no common supertype of {a!r} and {b!r}")
    return ANY


def dtype_issubclass(sub: DType, sup: DType) -> bool:
    if sup == ANY or sub == sup:
        return True
    if sub == NONE:
        return sup.is_optional() or sup == NONE
    if sup.is_optional():
        return dtype_issubclass(unoptionalize(sub), unoptionalize(sup))
    if sub.is_optional():
        return False
    if sub == INT and sup == FLOAT:
        return True
    if isinstance(sub, Pointer) and isinstance(sup, Pointer) and sup == POINTER:
        return True
    if isinstance(sub, Array) and isinstance(sup, Array):
        return True
    if isinstance(sub, (Tuple_, List_)) and sup in (ANY_TUPLE,):
        return True
    return False


def coerce_np(values: np.ndarray, dtype: DType) -> np.ndarray:
    """Coerce a column of boxed python values into this dtype's numpy storage."""
    target = dtype.np_dtype
    if target == object:
        out = np.empty(len(values), dtype=object)
        out[:] = list(values)
        return out
    return np.asarray(values, dtype=target)
