"""Checkable models of the cluster protocols for the deterministic scheduler.

Each model is a small, faithful port of one hand-written thread protocol from
the runtime — the epoch fence/rejoin install (``parallel/cluster.py``), the
snapshot→ack→manifest→compact coordinated checkpoint (``engine/runner.py`` +
``persistence/engine.py``), and the query-coalescer admission/shed path
(``models/embed_pipeline.py``) — rewritten against ``internals/sched.py``
primitives so EVERY interleaving decision is scheduler-controlled. Run them
under :func:`~pathway_tpu.internals.sched.explore` (bounded-exhaustive DFS) or
:func:`~pathway_tpu.internals.sched.sweep_seeds` (seeded walks) and the
invariants below hold on every schedule — or fail with a replayable choice
sequence:

- **fence/rejoin**: no stale-epoch frame is ever delivered, future-epoch
  frames park and deliver exactly once at install, every survivor adopts the
  new epoch, and the protocol never deadlocks;
- **checkpoint**: at most one manifest per commit id, compaction only behind
  a durable manifest, and an aborted attempt leaves the previous manifest
  intact;
- **coalescer**: every request is shed XOR answered, admission slots are
  always released (queued rows return to zero), and close never strands a
  waiter;
- **encoder service**: the continuous-batching admission/tick/shutdown
  protocol (``models/encoder_service.py``) — every request shed XOR answered,
  waiting and in-flight row counts return to zero, shutdown drains the queue,
  and the timed tick keeps the idle wait abortable (no lost-wakeup deadlock).

Each model takes a ``bug=`` knob that plants a realistic regression
(``"no_purge"`` skips the install-time inbox purge, ``"toctou_commit"``
releases the manifest lock between the read-back check and the write,
``"leak_slot"`` drops the queued-row release on the encode error path,
``"no_timeout"`` makes a wait unabortable). The broken variants exist so the
model-check suite can prove it DETECTS the bug class with a replayable
schedule — the safety net ROADMAP item 1's membership protocol will run
under.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from pathway_tpu.internals.sched import DeterministicScheduler

# ---------------------------------------------------------------------------
# fence broadcast + rejoin install (parallel/cluster.py)
# ---------------------------------------------------------------------------


class _ModelSurvivor:
    """One fenced survivor: epoch-checked inbox with park/drop semantics —
    the reader-thread logic of ``ClusterExchange._reader`` + the install step
    of ``await_rejoin``, minus the sockets."""

    def __init__(self, sched: DeterministicScheduler, idx: int, bug: Optional[str]):
        self.idx = idx
        self.bug = bug
        self.cv = sched.condition(name=f"s{idx}.cv")
        self.epoch = 0
        self.inbox: List[tuple] = []  # (frame_epoch, payload) awaiting delivery
        self.parked: List[tuple] = []  # future-epoch frames
        self.delivered: List[tuple] = []  # (frame_epoch, epoch_at_delivery, payload)
        self.stale_dropped = 0
        self.fence_pending = False
        self.rejoin_ready = False
        self.installed = False

    def on_frame(self, frame_epoch: int, payload: str) -> None:
        """A peer/replacement/zombie frame arrives (any thread)."""
        with self.cv:
            if frame_epoch < self.epoch and self.bug != "deliver_stale":
                self.stale_dropped += 1
                return
            if frame_epoch > self.epoch:
                self.parked.append((frame_epoch, payload))
                self.cv.notify_all()
                return
            self.inbox.append((frame_epoch, payload))
            self.cv.notify_all()

    def set_fence(self) -> None:
        with self.cv:
            self.fence_pending = True
            self.cv.notify_all()

    def set_rejoin_ready(self) -> None:
        with self.cv:
            self.rejoin_ready = True
            self.cv.notify_all()

    def install(self, new_epoch: int) -> None:
        """Adopt the rejoin: purge the aborted epoch's inbox, deliver parked
        frames already sent at the adopted epoch."""
        with self.cv:
            if self.bug != "no_purge":
                self.stale_dropped += len(self.inbox)
                self.inbox = []
            self.epoch = new_epoch
            keep = [(e, p) for (e, p) in self.parked if e == new_epoch]
            self.stale_dropped += len(self.parked) - len(keep)
            self.inbox.extend(keep)
            self.parked = []
            self.installed = True
            self.cv.notify_all()

    def drain(self, expect: int) -> None:
        """Deliver frames until ``expect`` post-install frames arrived."""
        while True:
            with self.cv:
                while self.inbox:
                    frame_epoch, payload = self.inbox.pop(0)
                    self.delivered.append((frame_epoch, self.epoch, payload))
                if len([d for d in self.delivered if d[1] == self.epoch]) >= expect:
                    return
                self.cv.wait()


def fence_rejoin_model(
    n_survivors: int = 2, *, bug: Optional[str] = None
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The surgical-restart epoch fence: ``n_survivors`` fenced survivors, a
    fence broadcaster, a zombie still sending epoch-0 frames (the dead rank's
    in-flight traffic), and a replacement dialing in and then talking at
    epoch 1. Survivors that install first immediately send epoch-1 frames to
    the others — the future-epoch parking path races exactly like the real
    mesh. Invariants: every delivered frame matches the epoch at delivery, no
    parked frames are stranded, all survivors converge to epoch 1, and the
    protocol cannot deadlock."""

    new_epoch = 1

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        survivors = [_ModelSurvivor(sched, i, bug) for i in range(n_survivors)]
        # post-install each survivor expects: one replacement frame + one
        # frame from every other survivor
        expect = 1 + (n_survivors - 1)

        def survivor_body(me: _ModelSurvivor) -> None:
            # barrier-wait aborted by the fence (ClusterFenceError path)
            with me.cv:
                while not me.fence_pending:
                    me.cv.wait()
            # await_rejoin: quiesce until the replacement re-dialed
            with me.cv:
                while not me.rejoin_ready:
                    me.cv.wait()
            me.install(new_epoch)
            # replayed barriers: talk to the other survivors at the new epoch
            for peer in survivors:
                if peer is not me:
                    peer.on_frame(new_epoch, f"s{me.idx}->s{peer.idx}")
            me.drain(expect)

        def zombie_body() -> None:
            # the dead rank's frames still in flight: stale once epochs move
            for peer in survivors:
                peer.on_frame(0, f"zombie->s{peer.idx}")

        def fence_body() -> None:
            for peer in survivors:
                peer.set_fence()

        def replacement_body() -> None:
            # re-dial each survivor (install order is scheduler-chosen) …
            for peer in survivors:
                peer.set_rejoin_ready()
            # … then run the replayed barriers at the new epoch
            for peer in survivors:
                peer.on_frame(new_epoch, f"replacement->s{peer.idx}")

        for surv in survivors:
            sched.spawn(survivor_body, surv, name=f"survivor{surv.idx}")
        sched.spawn(fence_body, name="fence")
        sched.spawn(zombie_body, name="zombie")
        sched.spawn(replacement_body, name="replacement")

        def check() -> None:
            for surv in survivors:
                assert surv.epoch == new_epoch, (
                    f"survivor {surv.idx} never adopted epoch {new_epoch}"
                )
                assert not surv.parked, (
                    f"survivor {surv.idx} stranded parked frames: {surv.parked}"
                )
                for frame_epoch, at_epoch, payload in surv.delivered:
                    assert frame_epoch == at_epoch, (
                        f"stale-epoch delivery on survivor {surv.idx}: frame "
                        f"{payload!r} from epoch {frame_epoch} delivered at "
                        f"epoch {at_epoch}"
                    )
                post = [d for d in surv.delivered if d[1] == new_epoch]
                assert len(post) == expect, (
                    f"survivor {surv.idx} delivered {len(post)} post-install "
                    f"frames, expected {expect}"
                )
                # install + frame conservation (last, so the planted-bug
                # batteries keep their original first-failure messages):
                # adopting the epoch must have gone THROUGH install(), and
                # every frame addressed to a survivor (zombie + replacement +
                # each peer) is accounted for — delivered or dropped stale,
                # never silently vanished
                assert surv.installed, (
                    f"survivor {surv.idx} adopted epoch {new_epoch} without "
                    "running install()"
                )
                assert len(surv.delivered) + surv.stale_dropped == n_survivors + 1, (
                    f"survivor {surv.idx} frame accounting broke: "
                    f"{len(surv.delivered)} delivered + {surv.stale_dropped} "
                    f"stale-dropped != {n_survivors + 1} sent"
                )

        return check

    return model


# ---------------------------------------------------------------------------
# coordinated checkpoint: snapshot → ack → manifest → compact
# ---------------------------------------------------------------------------


def checkpoint_model(
    n_ranks: int = 3,
    *,
    crash_rank: Optional[int] = None,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The aligned checkpoint protocol at one commit id: every rank snapshots,
    acks durability, rank 0 commits the read-back-verified manifest only after
    ALL acks, everyone compacts only behind the manifest. A ``backup``
    committer models the retry path — with the real protocol's
    check-and-commit held under one lock it can never double-commit; with
    ``bug="toctou_commit"`` the lock drops between the read-back check and the
    write, and some interleaving commits the manifest twice.
    ``crash_rank`` kills one rank after its snapshot (the chaos
    ``post_snapshot_kill``): the ack barrier must then abort on its deadline
    and leave the PREVIOUS manifest intact."""

    commit_id = 7

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("store")
        cv = sched.condition(lock, name="store.cv")
        store: Dict[str, Any] = {
            "snapshots": {},  # rank -> commit id
            "acks": set(),
            "manifests": [("prev", commit_id - 1)],  # the durable previous checkpoint
            "compacted": set(),
            "aborted": False,
        }
        # a barrier wait is abortable by construction in the real protocol
        # (the mesh barrier deadline); model the deadline as a bounded number
        # of timeout wakeups
        deadline_polls = 4

        def ack_barrier_wait() -> bool:
            """True when every rank acked; False = deadline expired (abort)."""
            polls = 0
            with cv:
                while len(store["acks"]) < n_ranks:
                    if store["aborted"]:
                        return False
                    timeout = None if bug == "no_timeout" else 1.0
                    if not cv.wait(timeout=timeout):
                        polls += 1
                        if polls >= deadline_polls:
                            store["aborted"] = True
                            cv.notify_all()
                            return False
                return not store["aborted"]

        def commit_manifest() -> None:
            """Read-back-verified manifest commit (rank 0 and the retry path
            race through here; the lock must cover check AND write)."""
            if bug == "toctou_commit":
                with lock:
                    already = any(m[0] == "ckpt" for m in store["manifests"])
                sched.yield_point("manifest-gap")  # lock dropped: the TOCTOU window
                if not already:
                    with lock:
                        store["manifests"].append(("ckpt", commit_id))
            else:
                with lock:
                    if not any(m[0] == "ckpt" for m in store["manifests"]):
                        store["manifests"].append(("ckpt", commit_id))
            with cv:
                cv.notify_all()

        def rank_body(rank: int) -> None:
            with cv:
                store["snapshots"][rank] = commit_id
            sched.yield_point("snapshot-durable")
            if rank == crash_rank:
                return  # post-snapshot kill: no ack ever arrives
            with cv:
                store["acks"].add(rank)
                cv.notify_all()
            ok = ack_barrier_wait()
            if rank == 0 and ok:
                commit_manifest()
            # outcome: compact only once a manifest for THIS commit is durable
            polls = 0
            with cv:
                while not any(m == ("ckpt", commit_id) for m in store["manifests"]):
                    if store["aborted"]:
                        return
                    if not cv.wait(timeout=1.0):
                        polls += 1
                        if polls >= deadline_polls:
                            return
                store["compacted"].add(rank)

        def backup_committer() -> None:
            """The retry path: re-drive the manifest commit once every ack is
            in (a supervisor re-poke after a slow rank 0). Safe only because
            commit_manifest re-verifies under the lock."""
            polls = 0
            with cv:
                while len(store["acks"]) < n_ranks:
                    if store["aborted"]:
                        return
                    if not cv.wait(timeout=1.0):
                        polls += 1
                        if polls >= deadline_polls:
                            return
            commit_manifest()

        for rank in range(n_ranks):
            sched.spawn(rank_body, rank, name=f"rank{rank}")
        sched.spawn(backup_committer, name="backup")

        def check() -> None:
            manifests = [m for m in store["manifests"] if m == ("ckpt", commit_id)]
            assert len(manifests) <= 1, (
                f"double manifest commit for commit {commit_id}: "
                f"{store['manifests']}"
            )
            assert ("prev", commit_id - 1) in store["manifests"], (
                "previous checkpoint manifest was lost"
            )
            if crash_rank is not None:
                assert not manifests, (
                    "manifest committed although a rank died before acking"
                )
            for rank in store["compacted"]:
                assert manifests, (
                    f"rank {rank} compacted its journal with no durable manifest"
                )

        return check

    return model


# ---------------------------------------------------------------------------
# query-coalescer admission / shed (models/embed_pipeline.py)
# ---------------------------------------------------------------------------


def coalescer_model(
    n_clients: int = 3,
    *,
    cap: int = 2,
    fail_batch: bool = False,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The QueryCoalescer admission protocol: clients admit one row each
    against ``cap`` queued rows (past it they shed), a worker batches the
    queue and answers every taken request, close() wakes everyone. With
    ``fail_batch`` the encoder raises on the first batch — the error must
    propagate to exactly the taken requests WITH their admission slots
    released (``bug="leak_slot"`` drops the release on that path, the real
    regression class behind a permanently-429 coalescer)."""

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("coalescer")
        cv = sched.condition(lock, name="coalescer.cv")
        state: Dict[str, Any] = {
            "queue": [],  # request ids waiting for the worker
            "queued_rows": 0,
            "shed": set(),
            "answered": set(),
            "errored": set(),
            "closed": False,
            "batches": 0,
        }

        def client_body(req: int) -> None:
            with cv:
                if state["queued_rows"] + 1 > cap:
                    state["shed"].add(req)
                    cv.notify_all()  # a shed is a terminal outcome too
                    return
                state["queue"].append(req)
                state["queued_rows"] += 1
                cv.notify_all()

        def worker_body() -> None:
            while True:
                with cv:
                    # notify-driven idle wait (every queue/closed transition
                    # notifies): an untimed wait here also makes the deadlock
                    # detector prove no state change can be missed
                    while not state["queue"]:
                        if state["closed"]:
                            return
                        cv.wait()
                    take = list(state["queue"])
                    state["queue"] = []
                fail = fail_batch and state["batches"] == 0
                state["batches"] += 1
                sched.yield_point("encode")
                with cv:
                    if fail:
                        state["errored"].update(take)
                        if bug != "leak_slot":
                            state["queued_rows"] -= len(take)
                    else:
                        state["answered"].update(take)
                        state["queued_rows"] -= len(take)
                    cv.notify_all()

        def closer_body() -> None:
            # close after every client's request reached a terminal state
            with cv:
                while (
                    len(state["shed"]) + len(state["answered"]) + len(state["errored"])
                    < n_clients
                ):
                    cv.wait()
                state["closed"] = True
                cv.notify_all()

        sched.spawn(worker_body, name="worker")
        for req in range(n_clients):
            sched.spawn(client_body, req, name=f"client{req}")
        sched.spawn(closer_body, name="closer")

        def check() -> None:
            outcomes = [state["shed"], state["answered"], state["errored"]]
            seen: set = set()
            for group in outcomes:
                assert not (seen & group), f"request answered twice: {seen & group}"
                seen |= group
            assert seen == set(range(n_clients)), (
                f"requests stranded with no outcome: {set(range(n_clients)) - seen}"
            )
            assert state["queued_rows"] == 0, (
                f"admission slots leaked: {state['queued_rows']} rows still "
                "counted after every request terminated"
            )

        return check

    return model


# ---------------------------------------------------------------------------
# encoder-service admission / tick / shutdown (models/encoder_service.py)
# ---------------------------------------------------------------------------


def encoder_service_model(
    n_clients: int = 3,
    *,
    cap: int = 2,
    max_inflight: int = 2,
    fail_batch: bool = False,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The EncoderService protocol, modeled BEFORE the real threads were wired
    (the PR-9 discipline): clients admit one row each against ``cap`` waiting
    rows (past it they shed); a continuous-batching worker takes up to
    ``max_inflight`` rows per tick, encodes, answers exactly the taken
    requests, and releases the in-flight slots; a stopper requests shutdown
    once every client made its admission decision, and the worker must DRAIN
    the queue before exiting. Clients abort typed only when the worker is gone
    with their request still queued (the self-heal/abort path of
    ``EncoderService._await``).

    All waits are modeled UNTIMED (notify-driven, like the coalescer model):
    under the deadlock detector that PROVES every state transition notifies
    its waiters — the real implementation's timed tick/poll bounds are
    defense-in-depth on top of a protocol shown to need no timeout wakeups.

    Invariants: no deadlock, every request shed XOR answered XOR errored
    (none aborted/dropped under the correct protocol), and slots always
    released (waiting AND in-flight row counts return to zero).

    Planted bugs: ``"leak_inflight"`` drops the in-flight release on the
    encode-error path (the slot-leak class behind a permanently-"full"
    service); ``"drop_on_close"`` makes the worker exit on stop WITHOUT
    draining, stranding admitted requests (caught as aborted requests);
    ``"lost_close_wakeup"`` drops the stop notify — the lost-wakeup deadlock
    class, caught because the idle wait is notify-driven."""

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("svc")
        cv = sched.condition(lock, name="svc.cv")
        state: Dict[str, Any] = {
            "queue": [],  # admitted request ids waiting for the worker
            "queued_rows": 0,
            "inflight_rows": 0,
            "decided": 0,  # clients whose admission decision happened
            "shed": set(),
            "answered": set(),
            "errored": set(),
            "aborted": set(),
            "stop": False,
            "worker_done": False,
            "ticks": 0,
        }

        def client_body(req: int) -> None:
            with cv:
                if state["queued_rows"] + 1 > cap:
                    state["shed"].add(req)
                    state["decided"] += 1
                    cv.notify_all()
                    return
                state["queue"].append(req)
                state["queued_rows"] += 1
                state["decided"] += 1
                cv.notify_all()
            # notify-driven wait for a terminal outcome; typed abort only when
            # no worker remains to drain the queue
            with cv:
                while req not in state["answered"] and req not in state["errored"]:
                    if state["worker_done"] and req in state["queue"]:
                        state["queue"].remove(req)
                        state["queued_rows"] -= 1
                        state["aborted"].add(req)
                        cv.notify_all()
                        return
                    cv.wait()

        def worker_body() -> None:
            while True:
                with cv:
                    while not state["queue"]:
                        if state["stop"]:
                            state["worker_done"] = True
                            cv.notify_all()
                            return
                        cv.wait()  # notify-driven idle wait (see docstring)
                    if bug == "drop_on_close" and state["stop"]:
                        # exits with the queue non-empty: admitted requests drop
                        state["worker_done"] = True
                        cv.notify_all()
                        return
                    take = []
                    while state["queue"] and len(take) < max_inflight:
                        take.append(state["queue"].pop(0))
                    state["queued_rows"] -= len(take)
                    state["inflight_rows"] += len(take)
                fail = fail_batch and state["ticks"] == 0
                state["ticks"] += 1
                sched.yield_point("encode")
                with cv:
                    if fail:
                        state["errored"].update(take)
                        if bug != "leak_inflight":
                            state["inflight_rows"] -= len(take)
                    else:
                        state["answered"].update(take)
                        state["inflight_rows"] -= len(take)
                    cv.notify_all()

        def stopper_body() -> None:
            # server stop races the in-flight tick: shutdown may begin as soon
            # as every client made its admission decision — admitted-but-
            # unanswered requests must still be drained
            with cv:
                while state["decided"] < n_clients:
                    cv.wait()
                state["stop"] = True
                if bug != "lost_close_wakeup":
                    cv.notify_all()

        sched.spawn(worker_body, name="worker")
        for req in range(n_clients):
            sched.spawn(client_body, req, name=f"client{req}")
        sched.spawn(stopper_body, name="stopper")

        def check() -> None:
            groups = [
                state["shed"], state["answered"], state["errored"], state["aborted"],
            ]
            seen: set = set()
            for group in groups:
                assert not (seen & group), f"request in two outcomes: {seen & group}"
                seen |= group
            assert seen == set(range(n_clients)), (
                f"requests stranded with no outcome: {set(range(n_clients)) - seen}"
            )
            assert not state["aborted"], (
                f"admitted requests dropped at shutdown (worker exited without "
                f"draining): {state['aborted']}"
            )
            assert state["queued_rows"] == 0, (
                f"admission slots leaked: {state['queued_rows']} rows still "
                "queued after every request terminated"
            )
            assert state["inflight_rows"] == 0, (
                f"in-flight slots leaked: {state['inflight_rows']} rows still "
                "counted after every request terminated"
            )
            if not fail_batch:
                assert not state["errored"]

        return check

    return model


# ---------------------------------------------------------------------------
# elastic membership change: quiesce -> handoff -> manifest -> install
# ---------------------------------------------------------------------------


class _ModelMember:
    """One cluster member in the membership-change model: an epoch-checked
    mailbox (stale frames dropped, future frames parked — the
    ``ClusterExchange._reader`` discipline) plus a slot-ownership map that
    must only change at install time."""

    def __init__(self, sched: DeterministicScheduler, rank: int, owned: "set[int]"):
        self.rank = rank
        self.cv = sched.condition(name=f"m{rank}.cv")
        self.epoch = 0
        self.owned = set(owned)  # slots this member serves rows for
        self.tokens: Dict[int, "set[str]"] = {}  # slot -> row tokens held here
        self.emitted: Dict[int, bool] = {}  # slot -> join match already emitted
        self.inbox: List[tuple] = []  # (frame_epoch, slot, token)
        self.parked: List[tuple] = []  # future-epoch frames
        self.delivered: List[tuple] = []  # (frame_epoch, epoch_at_delivery, slot)
        self.bad_rows: List[tuple] = []  # rows delivered for a slot not owned
        self.stale_dropped = 0
        self.released = False  # leaver gave up its process

    def on_frame(self, frame_epoch: int, slot: int, token: str) -> None:
        with self.cv:
            if frame_epoch < self.epoch:
                self.stale_dropped += 1
                return
            if frame_epoch > self.epoch:
                self.parked.append((frame_epoch, slot, token))
                self.cv.notify_all()
                return
            self.inbox.append((frame_epoch, slot, token))
            self.cv.notify_all()


def membership_model(
    old_n: int = 2,
    new_n: int = 3,
    *,
    n_slots: int = 6,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The epoch-fenced elastic membership transition (``MEMBERSHIP_CHANGE``):
    ``old_n`` live members quiesce at a commit boundary, partition their
    per-slot state into handoff fragments addressed by the NEW ownership map
    (slot -> rank = slot % new_n), ack durability, rank 0 commits the single
    membership manifest (check-and-write under one lock), and only then does
    every member of the new topology install — adopting the new epoch, the
    new ownership map, and the imported fragments atomically — while leavers
    release only after their fragments are durable and the manifest
    committed. Joiners import their fragments and join post-install traffic;
    every member then routes one row per moved slot to its owner under the
    new map (epoch-stamped frames park at not-yet-installed receivers, the
    real mesh's future-epoch discipline).

    Universal-reshard extension: each slot additionally holds JOIN-side
    state — a build-side token (``jleft``), a probe-side token (``jright``)
    and per-slot match bookkeeping. Donors emit each slot's match exactly
    once pre-cut; the bookkeeping rides the fragments so the new owner does
    NOT re-emit after install. Fragments themselves travel as a CHUNKED
    stream per (donor, dest) pair — two bounded chunks followed by a chunk
    manifest naming the chunk count — and an installer imports a stream
    only when its manifest matches (complete-or-abort).

    Invariants over every interleaving: every slot owned by exactly one live
    member at the final epoch (and by the mapped owner); the row-token set
    INCLUDING both join sides is preserved across the handoff (no row lost
    or duplicated) and resides with the slot's owner; every slot's match is
    emitted exactly once (never replayed across the cut); chunk streams are
    complete-or-abort (a manifest never overstates its chunks); no
    stale-epoch delivery and no row delivered to a non-owner; leavers fully
    drained (fragments durable) before release; no deadlock.

    Planted bugs (each must be CAUGHT with a replayable schedule):
    ``"double_owner"`` — a donor keeps serving slots it handed off (two
    owners at the new epoch, rows duplicated); ``"orphan_range"`` — one moved
    slot's fragment is dropped (a key range with no surviving rows);
    ``"release_before_drain"`` — a leaver releases before writing its
    fragments (its rows are lost); ``"epoch_before_install"`` — the epoch is
    bumped and traffic resumes before the ownership map installs, so rows
    route to ranks that no longer own the slot; ``"join_row_orphan"`` — one
    moved slot's probe-side join rows are left out of its fragment (the
    arrangement re-keys under the new map but the probe side is gone);
    ``"double_match"`` — match bookkeeping is dropped from the fragments, so
    the new owner re-emits matches the donor already emitted;
    ``"torn_chunk_install"`` — a donor tears one chunk stream (chunk written,
    no manifest) yet still acks, and the installer imports the partial
    stream instead of aborting it; ``"owner_map_stale"`` — a donor partitions
    its fragments with a stale ownership map, landing rows on ranks that do
    not own them under the committed map."""

    grow = new_n >= old_n
    members_after = list(range(new_n))
    joiners = list(range(old_n, new_n)) if grow else []
    leavers = list(range(new_n, old_n)) if not grow else []
    new_epoch = 1

    def old_owner(slot: int) -> int:
        return slot % old_n

    def new_owner(slot: int) -> int:
        return slot % new_n

    moved = {s for s in range(n_slots) if new_owner(s) != old_owner(s)}

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("store")
        cv = sched.condition(lock, name="store.cv")
        store: Dict[str, Any] = {
            "ready": set(),
            # (donor, dest) -> [chunk, ...]; each chunk is
            # {"slots": {slot: tokens}, "emitted": {slot: bool}} and is
            # durable once appended (the bounded-transport stream)
            "chunks": {},
            "chunk_manifest": {},  # (donor, dest) -> promised chunk count
            "acks": set(),
            "manifests": [],
            "matches": [],  # every join match ever emitted, in order
            "misrouted": [],  # rows routed to a released leaver (lost)
            "traffic_done": 0,  # new-topology members done sending
        }
        init_owned = {
            m: {s for s in range(n_slots) if old_owner(s) == m}
            for m in range(old_n)
        }
        members: Dict[int, _ModelMember] = {
            m: _ModelMember(sched, m, init_owned[m]) for m in range(old_n)
        }
        for j in joiners:
            members[j] = _ModelMember(sched, j, set())
        for m in range(old_n):
            for s in init_owned[m]:
                # two plain rows + the join arrangement's build and probe
                # sides — all four must survive the cut together
                members[m].tokens[s] = {
                    f"row{s}a", f"row{s}b", f"jleft{s}", f"jright{s}"
                }

        def notify_everyone() -> None:
            for mm in members.values():
                with mm.cv:
                    mm.cv.notify_all()

        def emit_matches(m: int) -> None:
            """Join matches: emit each owned slot's match exactly once (the
            bookkeeping is per-slot state and rides the handoff fragments)."""
            me = members[m]
            with me.cv:
                slots = sorted(me.owned)
            for slot in slots:
                with me.cv:
                    have = me.tokens.get(slot, set())
                    both = any(t.startswith("jleft") for t in have) and any(
                        t.startswith("jright") for t in have
                    )
                    if not both or me.emitted.get(slot):
                        continue
                    me.emitted[slot] = True
                with cv:
                    store["matches"].append(f"match{slot}")
                    cv.notify_all()

        def write_fragments(m: int) -> None:
            """Chunked handoff: per destination the donor streams TWO bounded
            chunks, then commits a chunk manifest naming the count — the
            installer's complete-or-abort basis."""
            me = members[m]
            skipped = False
            streams: Dict[int, list] = {}
            with me.cv:
                owned_slots = sorted(me.owned)
            for slot in owned_slots:
                dest = new_owner(slot)
                if bug == "owner_map_stale" and m == 0 and slot in moved:
                    # a stale (prior-attempt) ownership map partitions the
                    # fragment: rows land on ranks the committed map does
                    # not assign the slot to
                    dest = (new_owner(slot) + 1) % new_n
                if dest == m:
                    continue  # kept slots stay in place
                if bug == "orphan_range" and m == 0 and slot in moved and not skipped:
                    skipped = True  # this key range's fragment never lands
                    continue
                toks = sorted(me.tokens.get(slot, set()))
                if (
                    bug == "join_row_orphan" and m == 0 and slot in moved
                    and not skipped
                ):
                    # the probe-side join rows are left out of the fragment
                    skipped = True
                    toks = [t for t in toks if not t.startswith("jright")]
                half = (len(toks) + 1) // 2
                st = streams.setdefault(dest, [
                    {"slots": {}, "emitted": {}},
                    {"slots": {}, "emitted": {}},
                ])
                st[0]["slots"][slot] = set(toks[:half])
                st[1]["slots"][slot] = set(toks[half:])
                if bug != "double_match":
                    # match bookkeeping rides the SECOND chunk (torn streams
                    # must not leave it half-installed either)
                    st[1]["emitted"][slot] = bool(me.emitted.get(slot))
            torn_dest = min(streams) if streams else None
            for dest in sorted(streams):
                c0, c1 = streams[dest]
                with cv:
                    store["chunks"].setdefault((m, dest), []).append(c0)
                    cv.notify_all()
                sched.yield_point(f"chunk0-durable-d{dest}")
                if bug == "torn_chunk_install" and m == 0 and dest == torn_dest:
                    # torn stream: the second chunk and the manifest never
                    # land, yet this donor still acks below
                    continue
                with cv:
                    store["chunks"][(m, dest)].append(c1)
                    cv.notify_all()
                sched.yield_point(f"chunk1-durable-d{dest}")
                with cv:
                    store["chunk_manifest"][(m, dest)] = 2
                    cv.notify_all()

        def read_imports(m: int) -> tuple:
            """Assemble this rank's imports from the chunk streams addressed
            to it. Complete-or-abort: a stream whose manifest is missing or
            overstates its chunks contributes NOTHING (the buggy installer
            under ``torn_chunk_install`` trusts partial streams instead)."""
            imports: Dict[int, "set[str]"] = {}
            imported_emitted: Dict[int, bool] = {}
            with cv:
                for (donor, dest), chunks in store["chunks"].items():
                    if dest != m:
                        continue
                    promised = store["chunk_manifest"].get((donor, dest))
                    if promised is None or len(chunks) < promised:
                        if bug != "torn_chunk_install":
                            continue  # abort the incomplete stream atomically
                    for chunk in chunks:
                        for slot, toks in chunk["slots"].items():
                            imports.setdefault(slot, set()).update(toks)
                        for slot, em in chunk.get("emitted", {}).items():
                            imported_emitted[slot] = (
                                imported_emitted.get(slot, False) or em
                            )
            return imports, imported_emitted

        def install(m: int) -> None:
            """Adopt epoch + ownership map + imported fragments atomically
            (purging parked future frames into the live inbox)."""
            me = members[m]
            target = {s for s in range(n_slots) if new_owner(s) == m}
            imports, imported_emitted = read_imports(m)
            with me.cv:
                me.epoch = new_epoch
                if bug == "epoch_before_install" and m == 0:
                    # the planted regression: the epoch (and traffic) move
                    # while the ownership map still reflects the OLD topology
                    pass
                elif bug == "double_owner" and m == 0:
                    me.owned = me.owned | target  # never releases donated slots
                    for slot, toks in imports.items():
                        me.tokens.setdefault(slot, set()).update(toks)
                    me.emitted.update(imported_emitted)
                else:
                    for slot in list(me.owned - target):
                        me.owned.discard(slot)
                        me.tokens.pop(slot, None)
                    me.owned = set(target)
                    for slot, toks in imports.items():
                        me.tokens.setdefault(slot, set()).update(toks)
                    me.emitted.update(imported_emitted)
                keep = [(e, s, t) for (e, s, t) in me.parked if e == new_epoch]
                me.stale_dropped += len(me.parked) - len(keep)
                me.inbox.extend(keep)
                me.parked = []
                me.cv.notify_all()

        def late_map_fix(m: int) -> None:
            """epoch_before_install only: the map catches up after traffic
            already ran at the new epoch."""
            me = members[m]
            target = {s for s in range(n_slots) if new_owner(s) == m}
            imports, imported_emitted = read_imports(m)
            with me.cv:
                for slot in list(me.owned - target):
                    me.owned.discard(slot)
                    me.tokens.pop(slot, None)
                me.owned = set(target)
                for slot, toks in imports.items():
                    me.tokens.setdefault(slot, set()).update(toks)
                me.emitted.update(imported_emitted)
                me.cv.notify_all()

        def traffic(m: int) -> None:
            """Post-install: route one row per moved slot to its owner under
            MY current map, stamped with MY epoch."""
            me = members[m]
            with me.cv:
                epoch = me.epoch
                stale_map = (
                    bug == "epoch_before_install" and m == 0
                    and me.owned == init_owned.get(0, set())
                )
            for slot in sorted(moved):
                dest = old_owner(slot) if stale_map else new_owner(slot)
                if dest == m:
                    continue
                target = members[dest]
                if target.released:
                    with cv:
                        store["misrouted"].append((slot, dest))
                        cv.notify_all()
                    continue
                target.on_frame(epoch, slot, f"routed{slot}from{m}")
            with cv:
                store["traffic_done"] += 1
                cv.notify_all()
            notify_everyone()

        def drain(m: int) -> None:
            """Deliver inbox rows until every new member finished sending and
            nothing is left queued here."""
            me = members[m]
            while True:
                with me.cv:
                    while me.inbox:
                        frame_epoch, slot, token = me.inbox.pop(0)
                        me.delivered.append((frame_epoch, me.epoch, slot))
                        if slot not in me.owned:
                            me.bad_rows.append((slot, token))
                        else:
                            me.tokens.setdefault(slot, set()).add(token)
                    with cv:
                        done = store["traffic_done"] >= len(members_after)
                    if done and not me.inbox:
                        return
                    me.cv.wait()

        def old_member_body(m: int) -> None:
            me = members[m]
            # 0. pre-cut serving: the join emits each owned slot's match
            #    (bookkeeping recorded, to ride the fragments)
            emit_matches(m)
            # 1. quiesce: every old member votes ready at the commit boundary
            with cv:
                store["ready"].add(m)
                cv.notify_all()
                while len(store["ready"]) < old_n:
                    cv.wait()
            # 2. handoff fragments (per-slot state partitioned by NEW owner)
            if bug == "release_before_drain" and m in leavers:
                # the planted regression: the leaver tears down before its
                # fragments are durable — its slots' rows are simply gone
                # (it still acks, hiding the loss until the check)
                with me.cv:
                    me.released = True
                    me.owned.clear()
                    me.tokens.clear()
                with cv:
                    store["acks"].add(m)
                    cv.notify_all()
                return
            write_fragments(m)
            sched.yield_point("fragments-durable")
            # 3. durability-ack barrier
            with cv:
                store["acks"].add(m)
                cv.notify_all()
                while len(store["acks"]) < old_n:
                    cv.wait()
            # 4. rank 0 commits the single membership manifest (check-and-
            #    write under one lock; at-most-one by construction)
            if m == 0:
                with lock:
                    if not any(x[0] == "member" for x in store["manifests"]):
                        store["manifests"].append(("member", old_n, new_n))
                with cv:
                    cv.notify_all()
            with cv:
                while not store["manifests"]:
                    cv.wait()
            # 5. leavers release only now: fragments durable AND manifest
            #    committed (their journal shard is drained by construction)
            if m in leavers:
                with me.cv:
                    me.released = True
                    me.owned.clear()
                    me.tokens.clear()
                notify_everyone()
                return
            # 6. survivors install (epoch + map + imports, atomically),
            #    re-check the join (imported bookkeeping suppresses
            #    re-emission), then run post-install traffic and drain
            install(m)
            emit_matches(m)
            traffic(m)
            if bug == "epoch_before_install" and m == 0:
                late_map_fix(m)
            drain(m)

        def joiner_body(j: int) -> None:
            me = members[j]
            # joiners wait for the committed manifest (their catch-up is the
            # manifest + fragments, never a history replay), then install
            with cv:
                while not store["manifests"]:
                    cv.wait()
            install(j)
            emit_matches(j)
            traffic(j)
            drain(j)

        for m in range(old_n):
            sched.spawn(old_member_body, m, name=f"member{m}")
        for j in joiners:
            sched.spawn(joiner_body, j, name=f"joiner{j}")

        def check() -> None:
            # every slot owned by exactly one live member, and by the mapped one
            for slot in range(n_slots):
                owners = [
                    mm.rank for mm in members.values()
                    if slot in mm.owned and not mm.released
                ]
                assert len(owners) == 1, (
                    f"slot {slot} owned by {owners} (expected exactly one "
                    "owner at the final epoch)"
                )
                assert owners[0] == new_owner(slot), (
                    f"slot {slot} owned by rank {owners[0]}, expected "
                    f"{new_owner(slot)}"
                )
            # rows reside ONLY with their slot's owner under the committed
            # map (a stale partition map lands them elsewhere)
            for mm in members.values():
                if mm.released:
                    continue
                for slot, toks in mm.tokens.items():
                    base = {t for t in toks if not t.startswith("routed")}
                    assert not base or mm.rank == new_owner(slot), (
                        f"slot {slot} rows reside on rank {mm.rank} but the "
                        f"committed map owns it to rank {new_owner(slot)} "
                        "(stale owner map at partition time?)"
                    )
            # no row lost or duplicated across the handoff — including both
            # join arrangement sides
            for slot in range(n_slots):
                want = {
                    f"row{slot}a", f"row{slot}b",
                    f"jleft{slot}", f"jright{slot}",
                }
                held: "set[str]" = set()
                for mm in members.values():
                    if mm.released:
                        continue
                    base = {
                        t for t in mm.tokens.get(slot, set())
                        if not t.startswith("routed")
                    }
                    assert not (held & base), (
                        f"slot {slot} rows duplicated across ranks: {held & base}"
                    )
                    held |= base
                assert held == want, (
                    f"slot {slot} rows lost across the handoff: have "
                    f"{sorted(held)}, want {sorted(want)}"
                )
            assert not store["misrouted"], (
                f"rows routed to released leavers: {store['misrouted']}"
            )
            for m in members_after:
                mm = members[m]
                assert mm.epoch == new_epoch, f"rank {m} never adopted the epoch"
                assert not mm.parked, f"rank {m} stranded parked frames"
                for frame_epoch, at_epoch, slot in mm.delivered:
                    assert frame_epoch == at_epoch, (
                        f"stale-epoch delivery on rank {m} (slot {slot}; "
                        f"{mm.stale_dropped} other stale frames were dropped "
                        "correctly)"
                    )
                assert not mm.bad_rows, (
                    f"rows delivered to a non-owner on rank {m}: {mm.bad_rows}"
                )
            for lv in leavers:
                assert members[lv].released, f"leaver {lv} never released"
            assert (
                len([x for x in store["manifests"] if x[0] == "member"]) == 1
            ), "membership manifest committed more than once (or never)"
            # every join match emitted exactly once — the bookkeeping riding
            # the fragments must suppress re-emission after install
            for slot in range(n_slots):
                n_emitted = store["matches"].count(f"match{slot}")
                assert n_emitted == 1, (
                    f"slot {slot} match emitted {n_emitted} time(s) — the "
                    "join replayed (or lost) a match across the cut"
                )
            # chunk streams complete-or-abort: a committed manifest never
            # overstates the chunks that actually landed
            for (donor, dest), promised in store["chunk_manifest"].items():
                got = len(store["chunks"].get((donor, dest), []))
                assert got == promised, (
                    f"chunk stream {donor}->{dest} committed a manifest for "
                    f"{promised} chunk(s) but {got} landed"
                )

        return check

    return model


# ---------------------------------------------------------------------------
# tiered IVF index: prefetch staging / background rebuild / generation swap
# ---------------------------------------------------------------------------


def tiered_index_model(
    *,
    n_clusters: int = 3,
    n_reads: int = 4,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The tiered-index residency protocol (``ops/knn_tiers.py``), modeled
    BEFORE the real threads were wired (the PR-9 discipline): reader threads
    serve queries against the current generation (coarse probe reads the
    centroids, scoring reads the cluster pages — BOTH under one lock hold,
    the commit-boundary atomicity the engine thread gets for free); a
    prefetch worker stages cold clusters hot (taking a staging slot, doing
    the H2D work off-lock, releasing the slot on every path); a background
    rebuilder builds the next generation's pages off to the side and SWAPS —
    centroids and pages re-point together, only after every cluster of the
    new generation is built, with the old generation's pages intact until
    the instant the swap commits.

    Invariants over every interleaving: no torn read (a query never mixes
    generation-g centroids with generation-g' pages, and never reads an
    incomplete or missing page set); the swap happens exactly once and only
    after the new generation is complete; staging slots always return to
    zero; no deadlock.

    Planted bugs (each must be CAUGHT with a replayable schedule):
    ``"torn_swap"`` — the swap publishes centroids and pages in two lock
    acquisitions, so a reader between them mixes generations;
    ``"swap_incomplete"`` — the rebuilder swaps after building only part of
    the new generation (queries hit missing clusters);
    ``"drop_old_early"`` — the rebuilder frees the old generation's pages
    before the swap commits (in-flight queries read freed pages);
    ``"leak_stage"`` — the prefetcher skips the staging-slot release when a
    swap invalidated its target mid-stage (the slot-leak class behind a
    permanently-wedged promotion pipeline)."""

    new_gen = 1

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("index")
        cv = sched.condition(lock, name="index.cv")
        state: Dict[str, Any] = {
            "centroids_gen": 0,
            "pages_gen": 0,
            # generation -> set of built cluster ids (complete == n_clusters)
            "pages": {0: set(range(n_clusters))},
            "hot": set(),
            "staging": 0,
            "swaps": 0,
            "reads": [],  # (centroids_gen, pages_gen, missing_clusters)
            "rebuild_done": False,
            "readers_done": 0,
        }

        def reader_body(idx: int) -> None:
            for _ in range(n_reads):
                with cv:
                    cg = state["centroids_gen"]
                    pg = state["pages_gen"]
                    built = state["pages"].get(pg, set())
                    missing = n_clusters - len(built)
                    state["reads"].append((cg, pg, missing))
                sched.yield_point(f"reader{idx}")
            with cv:
                state["readers_done"] += 1
                cv.notify_all()

        def prefetcher_body() -> None:
            for cid in range(n_clusters):
                with cv:
                    gen_at_start = state["pages_gen"]
                    if cid not in state["pages"].get(gen_at_start, set()):
                        continue
                    state["staging"] += 1
                sched.yield_point("stage")  # the off-lock H2D / unspill work
                with cv:
                    invalidated = state["pages_gen"] != gen_at_start
                    if invalidated and bug == "leak_stage":
                        # the planted leak: an invalidated stage abandons its
                        # slot instead of releasing it on the way out
                        continue
                    state["staging"] -= 1
                    if not invalidated:
                        state["hot"].add(cid)
                    cv.notify_all()

        def rebuilder_body() -> None:
            built: set = set()
            target = (
                range(n_clusters - 1)
                if bug == "swap_incomplete"
                else range(n_clusters)
            )
            for cid in target:
                sched.yield_point("build")  # off-to-the-side training work
                with cv:
                    built.add(cid)
                    state["pages"].setdefault(new_gen, set()).add(cid)
            if bug == "drop_old_early":
                # the planted regression: the old generation is freed BEFORE
                # the swap commits — in-flight readers lose their pages
                with cv:
                    state["pages"][0] = set()
            sched.yield_point("pre-swap")
            if bug == "torn_swap":
                # two lock acquisitions: a reader between them mixes gens
                with cv:
                    state["centroids_gen"] = new_gen
                sched.yield_point("swap-gap")
                with cv:
                    state["pages_gen"] = new_gen
                    state["swaps"] += 1
                    state["rebuild_done"] = True
                    cv.notify_all()
            else:
                with cv:
                    state["centroids_gen"] = new_gen
                    state["pages_gen"] = new_gen
                    state["swaps"] += 1
                    state["rebuild_done"] = True
                    cv.notify_all()

        for idx in range(2):
            sched.spawn(reader_body, idx, name=f"reader{idx}")
        sched.spawn(prefetcher_body, name="prefetch")
        sched.spawn(rebuilder_body, name="rebuild")

        def check() -> None:
            for cg, pg, missing in state["reads"]:
                assert cg == pg, (
                    f"torn generation read: centroids from generation {cg} "
                    f"scored against generation-{pg} pages"
                )
                assert missing == 0, (
                    f"query read an incomplete generation: {missing} cluster "
                    f"page set(s) missing from generation {pg}"
                )
            assert state["staging"] == 0, (
                f"staging slots leaked: {state['staging']} still held after "
                "every stage terminated"
            )
            assert state["swaps"] == 1, (
                f"generation swap committed {state['swaps']} times (expected "
                "exactly once)"
            )
            assert state["pages_gen"] == new_gen and state["centroids_gen"] == new_gen
            assert len(state["pages"].get(new_gen, set())) == n_clusters, (
                "swap committed an incomplete generation"
            )

        return check

    return model


# ---------------------------------------------------------------------------
# quantized retrieval: scale recalibration install vs concurrent scoring
# ---------------------------------------------------------------------------


def quant_recalibration_model(
    *,
    n_pages: int = 3,
    n_reads: int = 4,
    abort: bool = False,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The quantization-sidecar recalibration protocol
    (``ops/knn_tiers.py::_recalibrate_quant``), modeled before the chaos
    acceptance was wired: reader threads score pages by reading the
    (scale, codes, cached-f32-cast) triple per page under one lock hold —
    the commit-boundary atomicity a quantized score depends on, because a
    new scale applied to old codes (or a stale cached cast of old codes)
    silently mis-scores every row on the page. The recalibrator requantizes
    every page off to the side (off-lock), then either ABORTS before the
    install (the ``quant`` chaos op: nothing published, old sidecars keep
    serving) or installs scales + codes + cast-invalidation in ONE lock
    acquisition.

    Invariants over every interleaving: no torn sidecar read (a reader
    never mixes new scales with old codes or vice versa); the cached cast
    always matches the codes it was cast from; an aborted recalibration
    publishes NOTHING (serving state is bitwise the old generation); a
    completed one installs exactly once, completely; no deadlock.

    Planted bugs (each must be CAUGHT with a replayable schedule):
    ``"torn_install"`` — scales and codes install in two lock acquisitions,
    so a reader between them scores old codes at new scales;
    ``"stale_cast"`` — the install forgets to invalidate the cached f32
    cast of the codes (the real ``_qf32`` hazard), so readers score the OLD
    cast at the new scale;
    ``"install_after_abort"`` — the chaos-abort path publishes the new
    scales anyway (recovery must serve the old generation bit-exactly)."""

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("index")
        cv = sched.condition(lock, name="index.quant.cv")
        state: Dict[str, Any] = {
            # per-page sidecar versions; a consistent page has all three equal
            "scales_ver": [0] * n_pages,
            "codes_ver": [0] * n_pages,
            "cast_ver": [0] * n_pages,
            "installs": 0,
            "aborts": 0,
            "reads": [],  # (page, scales_ver, codes_ver, cast_ver)
            "readers_done": 0,
        }

        def reader_body(idx: int) -> None:
            for r in range(n_reads):
                page = (idx + r) % n_pages
                with cv:
                    state["reads"].append(
                        (
                            page,
                            state["scales_ver"][page],
                            state["codes_ver"][page],
                            state["cast_ver"][page],
                        )
                    )
                sched.yield_point(f"reader{idx}")
            with cv:
                state["readers_done"] += 1
                cv.notify_all()

        def recalibrator_body() -> None:
            for _page in range(n_pages):
                sched.yield_point("requantize")  # off-lock scale+code rebuild
            if abort:
                # the chaos `quant` op fires before the install: the new
                # sidecars are dropped on the floor, old scales keep serving
                with cv:
                    state["aborts"] += 1
                    if bug == "install_after_abort":
                        # planted: the abort path publishes anyway
                        for page in range(n_pages):
                            state["scales_ver"][page] = 1
                    cv.notify_all()
                return
            sched.yield_point("pre-install")
            if bug == "torn_install":
                # two lock acquisitions: a reader between them scores old
                # codes at new scales
                with cv:
                    for page in range(n_pages):
                        state["scales_ver"][page] = 1
                sched.yield_point("install-gap")
                with cv:
                    for page in range(n_pages):
                        state["codes_ver"][page] = 1
                        state["cast_ver"][page] = 1
                    state["installs"] += 1
                    cv.notify_all()
            else:
                with cv:
                    for page in range(n_pages):
                        state["scales_ver"][page] = 1
                        state["codes_ver"][page] = 1
                        if bug != "stale_cast":
                            state["cast_ver"][page] = 1
                    state["installs"] += 1
                    cv.notify_all()

        for idx in range(2):
            sched.spawn(reader_body, idx, name=f"reader{idx}")
        sched.spawn(recalibrator_body, name="recalibrate")

        def check() -> None:
            for page, sv, codv, castv in state["reads"]:
                assert sv == codv, (
                    f"torn sidecar read on page {page}: generation-{sv} "
                    f"scales applied to generation-{codv} codes"
                )
                assert castv == codv, (
                    f"stale cached cast on page {page}: generation-{codv} "
                    f"codes scored through a generation-{castv} f32 cast"
                )
            # the cast invariant also holds at quiescence: a stale cache is
            # a latent mis-score even if no read raced the install
            for page in range(n_pages):
                assert state["cast_ver"][page] == state["codes_ver"][page], (
                    f"stale cached cast on page {page}: generation-"
                    f"{state['codes_ver'][page]} codes left behind a "
                    f"generation-{state['cast_ver'][page]} f32 cast"
                )
            if abort:
                assert state["installs"] == 0 and state["aborts"] == 1
                assert all(v == 0 for v in state["scales_ver"]), (
                    "aborted recalibration published new scales — recovery "
                    "must serve the old sidecars bit-exactly"
                )
            else:
                assert state["installs"] == 1, (
                    f"recalibration installed {state['installs']} times "
                    "(expected exactly once)"
                )
                assert all(v == 1 for v in state["scales_ver"])
                assert all(v == 1 for v in state["codes_ver"])

        return check

    return model


# ---------------------------------------------------------------------------
# closed-loop autoscaler: sample -> decide -> directive -> transition outcome
# ---------------------------------------------------------------------------


def autoscaler_model(
    *,
    ticks: int = 10,
    high_ticks: int = 6,
    cooldown: int = 3,
    backoff: int = 4,
    refuse_up: bool = False,
    crash_up: bool = False,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The autoscale control loop (``parallel/autoscaler.py``) against the
    membership-transition executor (the supervisor's ``request_scale`` /
    ``_watch_transition`` path), modeled BEFORE the real controller was wired
    (the PR-9 discipline). A controller thread ticks ``ticks`` times over a
    scripted load profile (overload for the first ``high_ticks`` ticks, idle
    after), engaging the brownout rung FIRST and only then deciding scale
    directions; an executor thread consumes issued directives and either
    completes them, REFUSES the first scale-up (``refuse_up`` — the preflight
    vote), or dies mid-flight (``crash_up`` — the manifest committed, so the
    recovery thread brings the cluster back STABLE at the new topology).
    Model time is the controller's tick counter, so cooldown/backoff windows
    are exact whatever the interleaving.

    Invariants over every interleaving: never two transitions in flight (a
    directive is only issued with none active), consecutive directives
    respect the cooldown window, a refused scale-up is never retried inside
    its backoff window (at most one retry per window), every overload-driven
    scale-up is preceded by a brownout engage (shed first, scale second), no
    directive is issued while the cluster is recovering from the mid-flight
    crash, and the protocol never deadlocks.

    Planted bugs (each must be CAUGHT with a replayable schedule):
    ``"double_directive"`` — the controller skips the in-flight check, so a
    slow transition overlaps a second directive; ``"cooldown_skip"`` — the
    cooldown gate is dropped, back-to-back directives storm the transition
    path; ``"refusal_retry"`` — the refusal backoff is ignored, the refused
    scale-up is hammered every eligible tick; ``"no_shed_first"`` — the
    controller scales on overload without engaging the brownout rung."""

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("autoscale")
        cv = sched.condition(lock, name="autoscale.cv")
        state: Dict[str, Any] = {
            "n": 1,
            "cluster": "stable",  # stable | recovering
            "in_flight": 0,
            "queue": [],  # (issue_tick, direction, target)
            "issued": [],  # (issue_tick, direction, target)
            "completed": [],
            "refusals": [],  # (issue_tick, target)
            "refused": None,  # pending feedback for the controller
            "backoff_until": None,
            "last_issue_tick": None,
            "brownout": 0,
            "events": [],  # ordered: ("brownout"|"issue_up"|"issue_down"|"refusal_backoff", tick)
            "overlap": 0,  # directives issued while one was in flight
            "unstable_issue": 0,  # directives issued while recovering
            "crashed": False,
            "recover_to": None,
            "done": False,
        }

        def controller_body() -> None:
            for tick in range(ticks):
                pressure = 2 if tick < high_ticks else 0
                with cv:
                    if state["refused"] is not None:
                        state["refused"] = None
                        state["backoff_until"] = tick + backoff
                        state["events"].append(("refusal_backoff", tick))
                    # shed first: the brownout rung engages before any scale
                    # decision is even considered
                    if bug != "no_shed_first":
                        if pressure >= 2 and state["brownout"] == 0:
                            state["brownout"] = 1
                            state["events"].append(("brownout", tick))
                        elif pressure <= 0:
                            state["brownout"] = 0
                    direction = None
                    if pressure >= 2 and (
                        state["brownout"] > 0 or bug == "no_shed_first"
                    ):
                        direction = "up"
                    elif pressure <= 0 and state["n"] > 1:
                        direction = "down"
                    issue = direction is not None
                    if issue and state["in_flight"] > 0 and bug != "double_directive":
                        issue = False
                    if issue and state["cluster"] != "stable":
                        issue = False
                    if (
                        issue
                        and bug != "cooldown_skip"
                        and state["last_issue_tick"] is not None
                        and tick - state["last_issue_tick"] < cooldown
                    ):
                        issue = False
                    if (
                        issue
                        and direction == "up"
                        and bug != "refusal_retry"
                        and state["backoff_until"] is not None
                        and tick < state["backoff_until"]
                    ):
                        issue = False
                    if issue:
                        if state["in_flight"] > 0:
                            state["overlap"] += 1
                        if state["cluster"] != "stable":
                            state["unstable_issue"] += 1
                        target = state["n"] + (1 if direction == "up" else -1)
                        state["in_flight"] += 1
                        state["last_issue_tick"] = tick
                        state["queue"].append((tick, direction, target))
                        state["issued"].append((tick, direction, target))
                        state["events"].append((f"issue_{direction}", tick))
                        cv.notify_all()
                sched.yield_point(f"tick{tick}")
            with cv:
                state["done"] = True
                cv.notify_all()

        def executor_body() -> None:
            refused_once = False
            while True:
                with cv:
                    while not state["queue"]:
                        if state["done"]:
                            return
                        cv.wait()
                    issue_tick, direction, target = state["queue"].pop(0)
                sched.yield_point("transition")
                with cv:
                    if refuse_up and direction == "up" and not refused_once:
                        # the preflight capability vote: typed refusal, the
                        # cluster keeps running at its current size
                        refused_once = True
                        state["refused"] = (target, "non-reshardable state")
                        state["refusals"].append((issue_tick, target))
                    elif crash_up and direction == "up" and not state["crashed"]:
                        # mid-flight death AFTER the manifest committed: the
                        # recovery ladder owns the cluster until it restarts
                        # everyone at the committed topology
                        state["crashed"] = True
                        state["cluster"] = "recovering"
                        state["recover_to"] = target
                    else:
                        state["n"] = target
                        state["completed"].append((issue_tick, direction, target))
                    state["in_flight"] -= 1
                    cv.notify_all()

        def recovery_body() -> None:
            with cv:
                while state["cluster"] != "recovering":
                    if state["done"] and not state["queue"] and state["in_flight"] == 0:
                        return
                    cv.wait()
            sched.yield_point("recovering")
            with cv:
                state["n"] = state["recover_to"]
                state["cluster"] = "stable"
                cv.notify_all()

        sched.spawn(controller_body, name="controller")
        sched.spawn(executor_body, name="executor")
        if crash_up:
            sched.spawn(recovery_body, name="recovery")

        def check() -> None:
            assert state["overlap"] == 0, (
                f"two membership transitions in flight: {state['overlap']} "
                f"directive(s) issued while one was active ({state['issued']})"
            )
            assert state["unstable_issue"] == 0, (
                "directive issued while the cluster was recovering from a "
                "mid-flight crash"
            )
            issue_ticks = [t for (t, _d, _n) in state["issued"]]
            for t1, t2 in zip(issue_ticks, issue_ticks[1:]):
                assert t2 - t1 >= cooldown, (
                    f"cooldown violated: directives at ticks {t1} and {t2} "
                    f"(window {cooldown})"
                )
            # refusal backoff: no scale-up inside (observation, observation+backoff)
            for kind, r_obs in state["events"]:
                if kind != "refusal_backoff":
                    continue
                storm = [
                    t
                    for (t, d, _n) in state["issued"]
                    if d == "up" and r_obs <= t < r_obs + backoff
                ]
                assert not storm, (
                    f"refused scale-up retried inside its backoff window "
                    f"(refusal observed at tick {r_obs}, retries at {storm})"
                )
            # shed before scale: the first overload scale-up must be preceded
            # by a brownout engage in the event order
            seq = state["events"]
            first_up = next(
                (i for i, (k, _t) in enumerate(seq) if k == "issue_up"), None
            )
            if first_up is not None:
                assert any(k == "brownout" for k, _t in seq[:first_up]), (
                    "scale-up issued before the brownout rung engaged "
                    "(shed-first ordering violated)"
                )
            if crash_up and state["crashed"]:
                assert state["cluster"] == "stable", (
                    "cluster never recovered from the mid-flight crash"
                )
                assert state["n"] >= state["recover_to"] or not [
                    1 for (_t, d, _n) in state["completed"] if d == "down"
                ], "recovery lost the committed topology"
            assert 1 <= state["n"] <= 1 + len(
                [1 for (_t, d, _n) in state["issued"] if d == "up"]
            ), f"worker count escaped its bounds: n={state['n']}"

        return check

    return model


# ---------------------------------------------------------------------------
# read-replica bootstrap / follow / bounded-staleness serve (parallel/replica.py)
# ---------------------------------------------------------------------------


def replica_follow_model(
    n_commits: int = 4,
    n_clients: int = 2,
    *,
    lag_bound: int = 1,
    torn: bool = False,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The read-replica follow protocol (``parallel/replica.py``), modeled
    BEFORE the fleet was wired (the PR-9 discipline). Staleness is measured
    in COMMITS (model time — no wall clock): a primary thread exports frames
    1..``n_commits``; a bootstrap thread installs the snapshot (or refuses it
    typed when ``torn``); TWO poller threads race the frame tail — the exact
    race the exactly-once apply guard exists for; client threads each issue
    one query with a ``lag_bound`` staleness bound and either serve at the
    applied commit or shed.

    Invariants over every interleaving: every frame is applied EXACTLY once
    and in commit order; every serve happens at lag <= ``lag_bound`` at the
    instant of serving; a torn bootstrap never serves a single query (the
    replica refuses typed and stays out of rotation); every client query is
    shed XOR answered; the follower converges to the feed tip; and the
    protocol never deadlocks.

    Planted bugs (each must be CAUGHT with a replayable schedule):
    ``"double_apply"`` — the commit-id guard is dropped, so racing pollers
    apply one frame twice (the regression class that breaks bitwise replica/
    primary parity); ``"stale_serve"`` — the staleness bound is not checked
    at serve time, so a lagging replica answers beyond the client's bound;
    ``"torn_bootstrap_serve"`` — the torn-bootstrap refusal is swallowed and
    the replica serves from a half-installed index."""

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("replica")
        cv = sched.condition(lock, name="replica.cv")
        state: Dict[str, Any] = {
            "tip": 0,  # latest commit the primary exported a frame for
            "done": False,  # primary finished exporting
            "bootstrapped": False,
            "refused": False,
            "applied": 0,  # the follower's applied commit id
            "applied_log": [],  # every frame application, in order
            "serves": [],  # (served_commit, tip_at_serve)
            "sheds": 0,
            "outcomes": 0,  # terminal client outcomes (serve XOR shed)
        }

        def primary_body() -> None:
            for commit in range(1, n_commits + 1):
                with cv:
                    state["tip"] = commit
                    cv.notify_all()
                sched.yield_point(f"export{commit}")
            with cv:
                state["done"] = True
                cv.notify_all()

        def bootstrap_body() -> None:
            sched.yield_point("read_manifest")
            with cv:
                if torn and bug != "torn_bootstrap_serve":
                    # checksum mismatch on a fragment: TYPED refusal, the
                    # replica never enters rotation
                    state["refused"] = True
                else:
                    # (with the planted bug, a torn export installs anyway)
                    state["bootstrapped"] = True
                cv.notify_all()

        def poller_body(idx: int) -> None:
            while True:
                with cv:
                    while True:
                        if state["refused"]:
                            return
                        if state["bootstrapped"] and state["applied"] < state["tip"]:
                            break
                        if state["done"] and (
                            state["bootstrapped"] or state["refused"]
                        ):
                            if state["applied"] >= state["tip"]:
                                return
                            break
                        cv.wait()
                    floor = state["applied"]
                    frames = list(range(floor + 1, state["tip"] + 1))
                # frames are READ outside the apply lock, one at a time — the
                # window in which the other poller may already have applied them
                for commit in frames:
                    sched.yield_point(f"p{idx}.read{commit}")
                    with cv:
                        if bug != "double_apply" and commit <= state["applied"]:
                            continue  # the exactly-once guard
                        state["applied_log"].append(commit)
                        state["applied"] = max(state["applied"], commit)
                        cv.notify_all()

        def client_body(q: int) -> None:
            sched.yield_point(f"q{q}.arrive")
            with cv:
                while not (state["bootstrapped"] or state["refused"]):
                    cv.wait()
                if state["refused"]:
                    # out of rotation: the router fails over — a shed outcome
                    # from this replica's perspective, never an answer
                    state["sheds"] += 1
                    state["outcomes"] += 1
                    cv.notify_all()
                    return
                lag = state["tip"] - state["applied"]
                if lag > lag_bound and bug != "stale_serve":
                    state["sheds"] += 1
                else:
                    state["serves"].append((state["applied"], state["tip"]))
                state["outcomes"] += 1
                cv.notify_all()

        sched.spawn(primary_body, name="primary")
        sched.spawn(bootstrap_body, name="bootstrap")
        for i in range(2):
            sched.spawn(poller_body, i, name=f"poller{i}")
        for q in range(n_clients):
            sched.spawn(client_body, q, name=f"client{q}")

        def check() -> None:
            log = state["applied_log"]
            assert len(log) == len(set(log)), (
                f"frame applied twice (bitwise parity broken): {log}"
            )
            assert log == sorted(log), f"frames applied out of order: {log}"
            if torn:
                assert not state["serves"], (
                    "torn bootstrap served queries from a half-installed "
                    f"index: {state['serves']}"
                )
            else:
                assert state["applied"] == n_commits, (
                    f"follower never converged to the feed tip: applied "
                    f"{state['applied']} of {n_commits}"
                )
            for served_commit, tip_at in state["serves"]:
                assert tip_at - served_commit <= lag_bound, (
                    f"served {tip_at - served_commit} commit(s) stale, past "
                    f"the bound {lag_bound} (serve at commit {served_commit} "
                    f"with tip {tip_at})"
                )
            assert state["outcomes"] == n_clients, (
                f"client query stranded with no outcome: "
                f"{state['outcomes']}/{n_clients} terminal"
            )

        return check

    return model


# ---------------------------------------------------------------------------
# trace ring / pending-buffer protocol (engine/tracing.py)
# ---------------------------------------------------------------------------


def trace_ring_model(
    n_writers: int = 2,
    n_traces: int = 2,
    *,
    ring_cap: int = 8,
    bug: Optional[str] = None,
) -> Callable[[DeterministicScheduler], Callable[[], None]]:
    """The tracing plane's span-routing protocol (``engine/tracing.py``):
    the bounded ring, the pending buffer unsampled spans wait in until
    their root's slow-promotion verdict, the epoch bump an elastic
    membership change installs mid-flight, and the crash flush the flight
    recorder drives from a dying rank.

    Threads: ``n_writers`` span writers each start+finish one span per
    trace (the SAME trace ids cross writers — one cross-rank trace whose
    sampling verdict every rank must derive identically); an epoch
    installer bumps the epoch between any two steps; a crash thread flushes
    the ring concurrently (the SIGTERM flight-dump path — file lock, then
    ring lock, the one canonical order).

    Invariants over every interleaving: **span conservation** — every span
    a writer starts terminates in the ring or the accounted drop list, so
    an epoch bump never orphans a buffered span; **flush-on-crash never
    deadlocks** — the crash flush and writer promotion take the file and
    ring locks in one global order; **sampling is consistent across a
    trace** — the head decision is a pure function of the trace id, so no
    trace ends half-kept, half-dropped across ranks; the flush completes
    exactly once.

    Planted bugs (each must be CAUGHT with a replayable schedule):
    ``"orphan_on_bump"`` — the epoch installer clears the pending buffer
    unaccounted, stranding in-flight spans; ``"flush_deadlock"`` — writer
    promotion grabs the file lock while holding the ring lock (the AB/BA
    inversion with the crash flush); ``"split_sampling"`` — each writer
    flips its own per-rank coin instead of hashing the trace id."""

    def model(sched: DeterministicScheduler) -> Callable[[], None]:
        lock = sched.lock("trace.ring")
        cv = sched.condition(lock, name="trace.cv")
        file_lock = sched.lock("trace.file")
        state: Dict[str, Any] = {
            "epoch": 0,
            "ring": [],  # (trace, writer, epoch_at_start) — kept spans
            "pending": {},  # trace -> [(writer, epoch_at_start)] buffered
            "dropped": [],  # ("unsampled"|"evicted", trace, writer, epoch)
            "started": 0,
            "finished": 0,
            "flushes": [],  # ring snapshots the crash flush captured
        }

        def _route_locked(trace: int, w: int, sampled: bool) -> None:
            # promotion verdict: pop THIS writer's buffered entries for the
            # trace and route them — ring (evicting over cap, accounted) or
            # the drop list; nothing may vanish silently
            bucket = state["pending"].get(trace, [])
            mine = [e for e in bucket if e[0] == w]
            state["pending"][trace] = [e for e in bucket if e[0] != w]
            for writer, epoch_at in mine:
                if sampled:
                    state["ring"].append((trace, writer, epoch_at))
                    if len(state["ring"]) > ring_cap:
                        state["dropped"].append(
                            ("evicted",) + state["ring"].pop(0)
                        )
                else:
                    state["dropped"].append(
                        ("unsampled", trace, writer, epoch_at)
                    )
            state["finished"] += len(mine)
            cv.notify_all()

        def writer_body(w: int) -> None:
            for trace in range(n_traces):
                with cv:
                    epoch_at_start = state["epoch"]
                    state["started"] += 1
                    state["pending"].setdefault(trace, []).append(
                        (w, epoch_at_start)
                    )
                    cv.notify_all()
                sched.yield_point(f"w{w}.t{trace}.work")
                if bug == "split_sampling":
                    # each rank flips its own coin — the exact divergence
                    # the hash-of-trace-id decision function exists to bar
                    sampled = (trace + w) % 2 == 0
                else:
                    # pure function of the trace id: every rank agrees
                    sampled = trace % 2 == 0
                if bug == "flush_deadlock":
                    with cv:
                        sched.yield_point(f"w{w}.t{trace}.inverted")
                        # ring lock held, file lock wanted: AB/BA against
                        # the crash flush's file-then-ring order
                        with file_lock:
                            _route_locked(trace, w, sampled)
                else:
                    with cv:
                        _route_locked(trace, w, sampled)

        def installer_body() -> None:
            sched.yield_point("bump.arrive")
            with cv:
                state["epoch"] += 1
                if bug == "orphan_on_bump":
                    # the regression: "stale" buffers swept on bump — any
                    # span between its start and its root's verdict vanishes
                    state["pending"].clear()
                cv.notify_all()

        def crash_body() -> None:
            sched.yield_point("crash.arrive")
            with file_lock:
                sched.yield_point("crash.flush")
                with cv:
                    state["flushes"].append(list(state["ring"]))
                    cv.notify_all()

        for w in range(n_writers):
            sched.spawn(writer_body, w, name=f"writer{w}")
        sched.spawn(installer_body, name="installer")
        sched.spawn(crash_body, name="crash")

        def check() -> None:
            expected = n_writers * n_traces
            assert state["started"] == expected
            total = len(state["ring"]) + len(state["dropped"])
            assert total == state["started"] and (
                state["finished"] == state["started"]
            ), (
                f"span orphaned: started {state['started']}, ring+dropped "
                f"{total}, finished {state['finished']} — an epoch bump "
                "stranded a buffered span"
            )
            leftovers = [
                entry
                for bucket in state["pending"].values()
                for entry in bucket
            ]
            assert not leftovers, f"spans left buffered: {leftovers}"
            ringed = {trace for (trace, _, _) in state["ring"]}
            for drop in state["dropped"]:
                if drop[0] == "evicted":
                    ringed.add(drop[1])
            unsampled = {
                drop[1] for drop in state["dropped"] if drop[0] == "unsampled"
            }
            split = sorted(ringed & unsampled)
            assert not split, (
                f"sampling split across ranks for trace(s) {split}: one rank "
                "kept the trace, another dropped it"
            )
            assert len(state["flushes"]) == 1, (
                f"crash flush ran {len(state['flushes'])} time(s), not once"
            )

        return check

    return model


# ---------------------------------------------------------------------------
# planted lock-order inversion (the PWA101 <-> model-check bridge)
# ---------------------------------------------------------------------------


def lock_order_model(
    *, inverted: bool = False
) -> Callable[[DeterministicScheduler], Optional[Callable[[], None]]]:
    """Two threads over two locks. ``inverted=False`` is the fixed ordering
    discipline (both take A before B — never deadlocks); ``inverted=True``
    plants the classic AB/BA inversion, which deadlocks under the right
    interleaving. The same shape, written with real ``threading`` primitives,
    is what PWA101 catches statically — the model-check run is the dynamic
    proof of the same bug."""

    def model(sched: DeterministicScheduler) -> None:
        a = sched.lock("A")
        b = sched.lock("B")

        def forward() -> None:
            with a:
                sched.yield_point("between")
                with b:
                    pass

        def backward() -> None:
            first, second = (b, a) if inverted else (a, b)
            with first:
                sched.yield_point("between")
                with second:
                    pass

        sched.spawn(forward, name="forward")
        sched.spawn(backward, name="backward")
        return None

    return model
